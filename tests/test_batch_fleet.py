"""Lease plane for the batch fleet (seist_tpu/batch/fleet.py):

* lease-store matrix (shared-directory AND the KV algorithm over an
  in-memory fake): contention, TTL expiry + reclaim, fence
  monotonicity, done markers;
* guarded wrapper: retry-with-backoff over transient errors, the
  LeaseStoreUnavailable ladder, injected partition windows;
* HeldLease: heartbeat renewal, the check_commit fence ladder
  (reachable-store fence reject; partitioned-store local-validity
  window), lost-lease latching;
* FleetWorker: work-stealing contention, partition park/heal,
  preemption release, zombie completion rejection;
* exactly-once segment publish: the fenced catalog.commit_segment
  exclusive link + the merge-side stale-fence audit;
* engine.run_units structured per-unit error records on the obs bus;
* the exit-75 contract end-to-end in the FLEET path (slow/chaos lane):
  preempt via SEIST_FAULT_BATCH_PREEMPT_UNIT, peer reclaim, rejoin,
  merged catalog byte-identical to the serial run.

Everything above the e2e runs with fake work and millisecond clocks —
no jax, no model — so the matrix rides tier-1 and the lockgraph lane.
"""

import json
import os
import threading
import time
import types

import pytest

from seist_tpu.batch import catalog, fleet
from seist_tpu.utils.faults import BatchFaultInjector, BatchFaultPlan

# Millisecond clocks: every wait in this file is bounded by these.
FAST = dict(
    ttl_s=0.25, heartbeat_s=0.05, grace_s=0.02, retries=3,
    backoff_base_s=0.01, backoff_cap_s=0.05, op_timeout_s=0.5,
    park_s=0.02, rescan_s=0.02,
)


def _cfg(**over):
    return fleet.LeaseConfig(**{**FAST, **over})


def _inert():
    return BatchFaultInjector(BatchFaultPlan())


class FakeKV:
    """In-memory KV speaking the KVLeaseStore protocol, with an
    injectable failure window (fail_ops counts down per op)."""

    def __init__(self):
        self._d = {}
        self._lock = threading.Lock()
        self.fail_ops = 0

    def _maybe_fail(self):
        with self._lock:
            if self.fail_ops > 0:
                self.fail_ops -= 1
                raise fleet.LeaseStoreError("injected kv failure")

    def put_new(self, key, value):
        self._maybe_fail()
        with self._lock:
            if key in self._d:
                return False
            self._d[key] = value
            return True

    def put(self, key, value):
        self._maybe_fail()
        with self._lock:
            self._d[key] = value

    def get(self, key):
        self._maybe_fail()
        with self._lock:
            return self._d.get(key)

    def keys(self, prefix):
        self._maybe_fail()
        with self._lock:
            return sorted(k for k in self._d if k.startswith(prefix))


@pytest.fixture(params=["dir", "kv"])
def store(request, tmp_path):
    if request.param == "dir":
        return fleet.DirLeaseStore(str(tmp_path / "leases"))
    return fleet.KVLeaseStore(FakeKV())


# ---------------------------------------------------------- store matrix
def test_acquire_contention_single_winner(store):
    a = store.try_acquire(7, "w0", ttl_s=5.0)
    assert a is not None and a.fence == 1 and a.owner == "w0"
    assert store.try_acquire(7, "w1", ttl_s=5.0) is None  # held, live
    assert store.current_fence(7) == 1


def test_ttl_expiry_then_reclaim_bumps_fence(store):
    a = store.try_acquire(1, "w0", ttl_s=0.05, grace_s=0.02)
    assert a.fence == 1
    # grace not yet elapsed: deadline alone doesn't open the reclaim
    time.sleep(0.06)
    b = store.try_acquire(1, "w1", ttl_s=5.0, grace_s=5.0)
    assert b is None
    time.sleep(0.02)
    b = store.try_acquire(1, "w1", ttl_s=5.0, grace_s=0.02)
    assert b is not None and b.fence == 2 and b.owner == "w1"
    # the zombie's renew now fails authoritatively
    with pytest.raises(fleet.LeaseLost, match="fence advanced"):
        store.renew(a, 5.0)


def test_fence_strictly_monotonic_across_handoffs(store):
    fences = []
    for i in range(4):
        rec = store.try_acquire(2, f"w{i}", ttl_s=5.0)
        assert rec is not None
        fences.append(rec.fence)
        store.release(rec)  # zero deadline -> immediate reclaimability
        time.sleep(0.03)  # > grace
    assert fences == [1, 2, 3, 4]


def test_renew_extends_deadline(store):
    a = store.try_acquire(3, "w0", ttl_s=0.2)
    b = store.renew(a, 5.0)
    assert b.fence == a.fence and b.deadline > a.deadline
    assert store.peek(3).deadline == b.deadline


def test_mark_done_first_writer_wins_and_blocks_acquire(store):
    a = store.try_acquire(4, "w0", ttl_s=5.0)
    assert store.mark_done(4, a.fence, "w0") is True
    assert store.mark_done(4, 9, "w1") is False  # first writer won
    assert store.done_fence(4) == a.fence
    assert store.is_done(4)
    assert store.try_acquire(4, "w1", ttl_s=5.0) is None
    with pytest.raises(fleet.LeaseLost):
        store.renew(fleet.LeaseRecord(4, a.fence + 1, "w1", 0.0), 5.0)
    assert store.done_fences([4, 5]) == {4: a.fence}


# ------------------------------------------------------- guarded wrapper
def test_guarded_retries_transient_then_succeeds():
    kv = FakeKV()
    g = fleet.GuardedLeaseStore(
        fleet.KVLeaseStore(kv), config=_cfg(), faults=_inert()
    )
    kv.fail_ops = 2  # < retries: the caller never sees the failures
    rec = g.try_acquire(0, "w0")
    assert rec is not None and rec.fence == 1
    assert g.snapshot()["store_errors"] == 2


def test_guarded_unavailable_after_retry_budget():
    kv = FakeKV()
    g = fleet.GuardedLeaseStore(
        fleet.KVLeaseStore(kv), config=_cfg(), faults=_inert()
    )
    kv.fail_ops = 10_000
    with pytest.raises(fleet.LeaseStoreUnavailable):
        g.try_acquire(0, "w0")
    assert g.snapshot()["store_errors"] >= g.config.retries


def test_guarded_passes_lease_lost_through_unretried():
    kv = FakeKV()
    st = fleet.KVLeaseStore(kv)
    g = fleet.GuardedLeaseStore(st, config=_cfg(), faults=_inert())
    a = g.try_acquire(0, "w0")
    st.mark_done(0, a.fence + 1, "w1")
    before = g.snapshot()["store_errors"]
    with pytest.raises(fleet.LeaseLost):
        g.renew(a)
    assert g.snapshot()["store_errors"] == before  # authoritative, no retry


def test_injected_partition_window_is_transient():
    """BatchFaultInjector partition: ops inside the window raise, ops
    after it succeed — the guarded wrapper surfaces Unavailable during
    and recovers after (the park/heal cycle's store-level substrate)."""
    inj = BatchFaultInjector(BatchFaultPlan(
        partition_after_s=0.0, partition_for_s=0.15,
    ))
    g = fleet.GuardedLeaseStore(
        fleet.KVLeaseStore(FakeKV()),
        config=_cfg(op_timeout_s=0.08, retries=2), faults=inj,
    )
    with pytest.raises(fleet.LeaseStoreUnavailable):
        g.try_acquire(0, "w0")  # also anchors the injector's clock
    time.sleep(0.16)
    assert g.try_acquire(0, "w0") is not None  # healed


# ------------------------------------------------------------ held lease
def test_heartbeat_keeps_short_ttl_alive(tmp_path):
    g = fleet.GuardedLeaseStore(
        fleet.DirLeaseStore(str(tmp_path)), config=_cfg(), faults=_inert()
    )
    held = fleet.HeldLease(g, g.try_acquire(0, "w0"))
    try:
        time.sleep(0.4)  # > ttl without renewal
        held.check_commit()  # heartbeat renewed through it
        assert held.locally_valid()
        assert g.try_acquire(0, "w1") is None  # still held
    finally:
        held.stop()
    assert g.snapshot()["renews"] >= 3


def test_check_commit_rejects_advanced_fence(tmp_path):
    st = fleet.DirLeaseStore(str(tmp_path))
    g = fleet.GuardedLeaseStore(st, config=_cfg(), faults=_inert())
    rec = g.try_acquire(0, "w0")
    held = fleet.HeldLease(g, rec)
    try:
        # A peer reclaims behind our back (forced via release).
        st.release(rec)
        time.sleep(0.03)
        assert st.try_acquire(0, "w1", ttl_s=5.0, grace_s=0.02).fence == 2
        with pytest.raises(fleet.FenceRejected):
            held.check_commit()
        # the reject latches: later commits refuse without store I/O
        with pytest.raises(fleet.FenceRejected):
            held.check_commit()
    finally:
        held.stop()
    assert g.snapshot()["fence_rejects"] >= 1


def test_check_commit_partition_honors_local_validity():
    """Store partitioned at commit time: allowed while locally valid
    (no peer CAN have reclaimed yet), refused once the local window
    passes — the degradation ladder's middle rungs."""
    kv = FakeKV()
    g = fleet.GuardedLeaseStore(
        fleet.KVLeaseStore(kv),
        config=_cfg(ttl_s=0.3, op_timeout_s=0.05, retries=2),
        faults=_inert(),
    )
    held = fleet.HeldLease(g, g.try_acquire(0, "w0"))
    try:
        kv.fail_ops = 1 << 30  # hard partition from here on
        held.check_commit()  # locally valid -> allowed
        time.sleep(0.35)  # local validity window expires
        with pytest.raises(fleet.LeaseLost, match="locally expired|unreachable"):
            held.check_commit()
    finally:
        kv.fail_ops = 0
        held.stop()


# ------------------------------------------------------ exactly-once commit
def test_fenced_commit_exclusive_and_sidecar(tmp_path):
    out = str(tmp_path)
    catalog.commit_segment(out, 0, 0, ["a\n"], fence=1)
    assert catalog.read_segment_fence(out, 0, 0) == 1
    with open(catalog.segment_path(out, 0, 0)) as f:
        assert f.read() == "a\n"
    # The zombie's publish: refused at the filesystem, content intact.
    with pytest.raises(FileExistsError):
        catalog.commit_segment(out, 0, 0, ["a\n"], fence=2)
    assert catalog.read_segment_fence(out, 0, 0) == 1
    # Serial commits (fence=None) keep overwrite semantics.
    catalog.commit_segment(out, 0, 1, ["b\n"])
    catalog.commit_segment(out, 0, 1, ["b\n"])
    assert catalog.read_segment_fence(out, 0, 1) is None


def test_merge_audit_rejects_zombie_fence(tmp_path):
    out = str(tmp_path)
    units = [catalog.WorkUnit(0, 0, 8)]
    catalog.commit_segment(out, 0, 0, ['{"row":0}\n'], fence=3)
    # done under fence 2, sidecar says 3 -> a zombie wrote after handover
    with pytest.raises(ValueError, match="zombie|NEWER"):
        catalog.merge_catalog(out, units, 8, 1, fences={0: 2})
    # done fence >= sidecar: normal history, merges + audited meta
    meta = catalog.merge_catalog(out, units, 8, 1, fences={0: 3})
    assert meta["fleet"]["fenced_segments"] == 1
    assert meta["fleet"]["done_fences"] == {"0": 3}
    # and the fence sidecar never reaches catalog bytes
    with open(os.path.join(out, "catalog.jsonl")) as f:
        assert f.read() == '{"row":0}\n'


# ------------------------------------------------------------ fleet worker
def _units(n):
    return [types.SimpleNamespace(unit_id=i) for i in range(n)]


def test_worker_contention_each_unit_once(tmp_path):
    ran = []
    results = {}

    def work(owner, offset):
        st = fleet.DirLeaseStore(str(tmp_path))
        w = fleet.FleetWorker(
            st, _units(5), owner,
            lambda u, held: (ran.append((owner, u.unit_id)),
                             {"preempted": False})[-1],
            config=_cfg(), faults=_inert(), scan_offset=offset,
        )
        results[owner] = w.run()

    ts = [
        threading.Thread(target=work, args=(f"w{i}", i)) for i in range(3)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert sorted(u for _, u in ran) == [0, 1, 2, 3, 4]  # exactly once
    assert all(r["all_done"] for r in results.values())
    assert sum(r["units_done"] for r in results.values()) == 5


def test_worker_parks_through_partition_then_heals(tmp_path):
    inj = BatchFaultInjector(BatchFaultPlan(
        partition_after_s=0.0, partition_for_s=0.25,
    ))
    w = fleet.FleetWorker(
        fleet.DirLeaseStore(str(tmp_path)), _units(2), "w0",
        lambda u, held: {"preempted": False},
        config=_cfg(op_timeout_s=0.05, retries=2), faults=inj,
    )
    stats = w.run()
    assert stats["all_done"] and stats["units_done"] == 2
    assert stats["parks"] >= 1  # it parked, it never crashed
    assert stats["lease"]["store_errors"] >= 1


def test_worker_preempt_releases_lease_for_peer(tmp_path):
    st = fleet.DirLeaseStore(str(tmp_path))
    stop = threading.Event()

    def preempted_work(u, held):
        stop.set()  # SIGTERM lands mid-unit
        return {"preempted": True}

    w0 = fleet.FleetWorker(
        st, _units(2), "w0", preempted_work,
        config=_cfg(), faults=_inert(), stop_event=stop,
    )
    s0 = w0.run()
    assert s0["preempted"] and not s0["all_done"]
    assert s0["lease"]["releases"] == 1
    # The peer reclaims the RELEASED lease immediately (fence 2) and
    # finishes everything.
    time.sleep(0.03)  # > grace
    w1 = fleet.FleetWorker(
        st, _units(2), "w1", lambda u, held: {"preempted": False},
        config=_cfg(), faults=_inert(),
    )
    s1 = w1.run()
    assert s1["all_done"] and s1["units_done"] == 2
    assert s1["lease"]["reclaims"] >= 1


def test_worker_abandons_lost_unit_to_peer(tmp_path):
    st = fleet.DirLeaseStore(str(tmp_path))

    def losing_work(u, held):
        raise fleet.LeaseLost("simulated mid-run loss")

    w = fleet.FleetWorker(
        st, _units(1), "w0", losing_work,
        config=_cfg(), faults=_inert(),
    )
    done = {}

    def finish():
        time.sleep(0.35)  # let w0's fence-1 lease expire
        w1 = fleet.FleetWorker(
            st, _units(1), "w1", lambda u, held: {"preempted": False},
            config=_cfg(), faults=_inert(),
        )
        done.update(w1.run())

    t = threading.Thread(target=finish)
    t.start()
    stats = w.run()
    t.join(timeout=30)
    assert stats["units_lost"] >= 1
    assert stats["all_done"]  # w0 exits because the DONE marker exists
    assert done["units_done"] == 1


def test_worker_zombie_completion_counted_as_fence_reject(tmp_path):
    """w0 finishes the work but a peer completed the unit under a later
    fence while w0 was cut off — w0's done marker loses the race and the
    stale fence is counted (the chaos lane's deterministic reject)."""
    st = fleet.DirLeaseStore(str(tmp_path))
    units = _units(1)

    def slow_work(u, held):
        # While w0 computes, the unit is released + completed by a peer
        # under fence 2 (simulating expiry + reclaim during a partition).
        st.release(held.record)
        time.sleep(0.03)
        rec2 = st.try_acquire(0, "w1", ttl_s=5.0, grace_s=0.02)
        assert rec2.fence == 2
        st.mark_done(0, rec2.fence, "w1")
        return {"preempted": False}

    w = fleet.FleetWorker(st, units, "w0", slow_work,
                          config=_cfg(), faults=_inert())
    stats = w.run()
    assert stats["all_done"]
    assert stats["units_lost"] == 1 and stats["units_done"] == 0
    assert stats["lease"]["fence_rejects"] >= 1
    assert stats["lease"]["double_commits"] == 0


# ------------------------------------------------- engine error records
def test_run_units_surfaces_structured_unit_errors(monkeypatch):
    """Satellite: a failing unit is VISIBLE — a structured record in the
    returned stats and a labeled counter on the obs bus (/metrics.json),
    not only a log line; unit_retries re-runs it before re-raising."""
    from seist_tpu.batch.engine import RepickEngine
    from seist_tpu.obs.bus import BUS

    eng = RepickEngine.__new__(RepickEngine)
    eng._warm = True
    eng.stage = {"fill": 0.0, "device": 0.0, "decode": 0.0, "write": 0.0}
    calls = {"n": 0}

    def flaky_run_unit(unit, out_dir, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("first attempt dies")
        return {
            "unit": unit.unit_id, "rows": 4, "calls": 1, "segments": 1,
            "segments_skipped": 0, "preempted": False,
        }

    monkeypatch.setattr(eng, "run_unit", flaky_run_unit)
    units = [catalog.WorkUnit(0, 0, 4)]
    stats = eng.run_units(units, "/nonexistent", unit_retries=1)
    assert stats["unit_errors"] == [
        {"unit": 0, "exc": "OSError", "retries": 0}
    ]
    assert stats["rows"] == 4 and calls["n"] == 2
    c = BUS.counter("batch_unit_error", unit="0", exc="OSError")
    assert c.value >= 1

    # Budget exhausted: the record lands, then the error propagates
    # (fail-loud unchanged).
    calls["n"] = -10_000  # every attempt fails
    monkeypatch.setattr(
        eng, "run_unit",
        lambda *a, **k: (_ for _ in ()).throw(ValueError("stuck")),
    )
    with pytest.raises(ValueError, match="stuck"):
        eng.run_units(units, "/nonexistent", unit_retries=1)


# ------------------------------------------------------------ fleet e2e
@pytest.mark.slow
@pytest.mark.chaos
def test_exit75_fleet_preempt_reclaim_rejoin_byte_identical(
    tmp_path, monkeypatch, capsys
):
    """The exit-75 contract END-TO-END in the fleet path: worker 0 is
    preempted by the fault knob at its first lease (SIGTERM -> drain ->
    release -> exit 75), a peer reclaims the released lease and finishes
    the archive, the original worker REJOINS and finds only done
    markers, and the fence-audited merge is byte-identical to the
    serial no-fault run."""
    import seist_tpu
    from seist_tpu.utils import faults as faults_mod
    from tools.repick_archive import main as repick_main

    seist_tpu.load_all()
    from seist_tpu.data.packed import PackSource, pack_sources

    archive = pack_sources(
        [PackSource(
            name="synthetic",
            dataset_kwargs={
                "num_events": 22, "trace_samples": 256, "cache": False,
            },
        )],
        str(tmp_path / "archive"),
        samples_per_shard=10,
    )["out"]
    base = [
        "--archive", archive, "--model", "phasenet",
        "--batch-size", "4", "--batches-per-call", "2",
        "--commit-every", "1",
    ]
    serial_out = str(tmp_path / "serial")
    assert repick_main(base + ["--out", serial_out]) == 0
    with open(os.path.join(serial_out, "catalog.jsonl"), "rb") as f:
        serial_bytes = f.read()

    fleet_out = str(tmp_path / "fleet")
    lease_dir = str(tmp_path / "leases")
    fl = base + [
        "--out", fleet_out, "--fleet", "--lease-dir", lease_dir,
        "--lease-store", "dir", "--no-merge",
    ]

    def run(worker, *, env=()):
        # Fresh injector per incarnation (subprocess semantics in-proc).
        for k in list(os.environ):
            if k.startswith("SEIST_FAULT_BATCH_"):
                monkeypatch.delenv(k)
        for k, v in env:
            monkeypatch.setenv(k, v)
        monkeypatch.setattr(faults_mod, "_BATCH_FAULTS", None)
        return repick_main(fl + [
            "--worker-index", str(worker), "--worker-id", f"w{worker}",
        ])

    monkeypatch.setenv("SEIST_LEASE_TTL_S", "2.0")
    monkeypatch.setenv("SEIST_LEASE_HEARTBEAT_S", "0.2")
    monkeypatch.setenv("SEIST_LEASE_GRACE_S", "0.05")
    stamp = str(tmp_path / "w0.stamp")
    rc = run(0, env=(
        ("SEIST_FAULT_BATCH_PREEMPT_UNIT", "1"),
        ("SEIST_FAULT_STAMP", stamp),
    ))
    assert rc == 75  # the preemption contract
    assert os.path.exists(stamp)

    rc = run(1)  # the peer: reclaims the released lease, finishes all
    assert rc == 0
    verdicts = [
        json.loads(line)
        for line in capsys.readouterr().out.splitlines()
        if line.startswith("{")
    ]
    peer = [v for v in verdicts if v.get("owner") == "w1"][-1]
    assert peer["all_done"]
    assert peer["lease"]["reclaims"] >= 1  # took over w0's lease
    assert peer["lease"]["double_commits"] == 0

    rc = run(0)  # the original worker rejoins: nothing left, exits clean
    assert rc == 0
    verdicts = [
        json.loads(line)
        for line in capsys.readouterr().out.splitlines()
        if line.startswith("{")
    ]
    rejoined = [v for v in verdicts if v.get("owner") == "w0"][-1]
    assert rejoined["all_done"] and rejoined["units_done"] == 0

    assert repick_main([
        "--archive", archive, "--out", fleet_out, "--merge-only",
        "--lease-dir", lease_dir,
    ]) == 0
    with open(os.path.join(fleet_out, "catalog.jsonl"), "rb") as f:
        assert f.read() == serial_bytes
