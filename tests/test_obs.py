"""Unit tests for the telemetry plane (seist_tpu/obs/): metrics bus +
span API, Prometheus exposition, JSONL event log, flight recorder, the
metrics HTTP endpoint, and jaxpr per-op attribution."""

import json
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from seist_tpu import obs
from seist_tpu.obs import bus as bus_mod
from seist_tpu.obs import flight as flight_mod
from seist_tpu.obs.bus import Counter, Gauge, Histogram, MetricsBus


@pytest.fixture
def bus():
    return MetricsBus()


@pytest.fixture
def fresh_flight(monkeypatch):
    """Isolate the module-level installed recorder + dedup clock."""
    monkeypatch.setattr(flight_mod, "_INSTALLED", None)
    monkeypatch.setattr(flight_mod, "_LAST_DUMP_MONO", None)
    monkeypatch.setattr(flight_mod, "DUMPED", [])
    yield


# ------------------------------------------------------------------- bus
def test_counter_gauge_identity_and_values(bus):
    c = bus.counter("reads")
    c.inc()
    c.inc(4)
    assert bus.counter("reads") is c  # same name+labels -> same object
    assert c.value == 5
    g = bus.gauge("loss", model="m1")
    g.set(1.5)
    assert bus.gauge("loss", model="m1") is g
    assert bus.gauge("loss", model="m2") is not g
    assert g.value == 1.5


def test_metric_type_conflict_raises(bus):
    bus.counter("x")
    with pytest.raises(TypeError):
        bus.gauge("x")


def test_span_records_histogram_and_duration(bus):
    with bus.span("phase") as sp:
        time.sleep(0.01)
    assert sp.duration_s is not None and sp.duration_s >= 0.01
    h = bus.histogram("phase_ms")
    assert h.count == 1
    assert h.mean >= 10.0


def test_span_begin_end_idempotent(bus):
    sp = bus.begin("p")
    d1 = sp.end()
    time.sleep(0.005)
    assert sp.end() == d1  # second end() is a no-op
    assert bus.histogram("p_ms").count == 1


def test_span_sink_receives_spans(bus):
    seen = []
    bus.add_span_sink(seen.append)
    with bus.span("s", k="v"):
        pass
    assert len(seen) == 1
    assert seen[0].name == "s" and seen[0].labels == {"k": "v"}
    bus.remove_span_sink(seen.append)
    with bus.span("s"):
        pass
    assert len(seen) == 1


def test_sick_span_sink_never_breaks_timed_path(bus):
    def boom(span):
        raise RuntimeError("sink died")

    bus.add_span_sink(boom)
    with bus.span("s"):
        pass  # must not raise
    assert bus.histogram("s_ms").count == 1


def test_timed_iter_spans_every_next(bus):
    out = list(bus_mod.timed_iter([1, 2, 3], "wait", bus=bus))
    assert out == [1, 2, 3]
    assert bus.histogram("wait_ms").count == 3


def test_collectors_flatten_replace_unregister(bus):
    bus.register_collector("src", lambda: {"a": 1, "nested": {"b": 2.5},
                                           "flag": True, "skip": "str"})
    samples = {name: v for name, _, v in bus._collect()}
    assert samples == {"src_a": 1.0, "src_nested_b": 2.5, "src_flag": 1.0}
    bus.register_collector("src", lambda: {"a": 9})  # same key replaces
    samples = {name: v for name, _, v in bus._collect()}
    assert samples == {"src_a": 9.0}
    bus.unregister_collector("src")
    assert bus._collect() == []


def test_sick_collector_skipped(bus):
    bus.register_collector("bad", lambda: 1 / 0)
    bus.register_collector("good", lambda: {"v": 1})
    assert {n for n, _, _ in bus._collect()} == {"good_v"}


def test_collector_name_override_and_labels(bus):
    bus.register_collector(
        "serve_batcher:m1", lambda: {"n": 3}, name="serve_batcher", model="m1"
    )
    [(name, labels, v)] = bus._collect()
    assert name == "serve_batcher_n" and labels == {"model": "m1"} and v == 3


def test_snapshot_shape(bus):
    bus.counter("c").inc()
    bus.gauge("g").set(2)
    with bus.span("sp"):
        pass
    bus.register_collector("col", lambda: {"k": 7})
    snap = bus.snapshot()
    assert snap["counters"] == {"c": 1.0}
    assert snap["gauges"] == {"g": 2.0}
    assert snap["histograms"]["sp_ms"]["count"] == 1.0
    assert snap["collectors"] == {"col_k": 7.0}
    json.dumps(snap)  # JSON-able end to end


# ------------------------------------------------------------ prometheus
def test_render_prometheus_format(bus):
    bus.counter("reads", source="h5").inc(3)
    bus.gauge("depth").set(4)
    h = bus.histogram("lat_ms", bounds=(1.0, 10.0))
    h.observe(0.5)
    h.observe(5.0)
    h.observe(100.0)  # overflow bucket
    bus.register_collector("io", lambda: {"retries": 2})
    text = bus_mod.render_prometheus(bus)
    assert '# TYPE seist_reads_total counter' in text
    assert 'seist_reads_total{source="h5"} 3' in text
    assert "seist_depth 4" in text
    # Cumulative buckets + +Inf == count.
    assert 'seist_lat_ms_bucket{le="1"} 1' in text
    assert 'seist_lat_ms_bucket{le="10"} 2' in text
    assert 'seist_lat_ms_bucket{le="+Inf"} 3' in text
    assert "seist_lat_ms_count 3" in text
    assert "seist_io_retries 2" in text
    assert text.endswith("\n")


def test_prometheus_label_escaping(bus):
    bus.gauge("g", path='a"b\\c').set(1)
    text = bus_mod.render_prometheus(bus)
    assert 'path="a\\"b\\\\c"' in text


# -------------------------------------------------------------- event log
def test_event_log_jsonl(tmp_path):
    log = obs.EventLog(str(tmp_path / "events.jsonl"))
    log.emit("epoch_summary", epoch=1, loss=0.5)
    log.emit("weird", obj=object())  # unserializable -> fallback via str
    log.close()
    log.emit("after_close")  # no-op, no raise
    lines = (tmp_path / "events.jsonl").read_text().strip().splitlines()
    assert len(lines) == 2
    first = json.loads(lines[0])
    assert first["event"] == "epoch_summary" and first["epoch"] == 1
    assert "t" in first
    json.loads(lines[1])


# ---------------------------------------------------------- flight recorder
def test_flight_ring_capacity_and_order():
    rec = obs.FlightRecorder(capacity=8)
    for i in range(20):
        rec.record_step(i, loss=float(i))
    p = rec.payload("test")
    assert len(p["steps"]) == 8
    assert [s["step"] for s in p["steps"]] == list(range(12, 20))
    assert p["last_step"] == 19


def test_flight_spans_tagged_with_current_step(bus):
    rec = obs.FlightRecorder(capacity=8)
    bus.add_span_sink(rec.on_span)
    rec.record_step(5)
    with bus.span("host_wait"):
        pass
    p = rec.payload("test")
    assert p["spans"][0]["name"] == "host_wait"
    assert p["spans"][0]["step"] == 5


def test_flight_dump_writes_json(tmp_path):
    rec = obs.FlightRecorder(capacity=4)
    rec.record_step(1)
    rec.record_event("rollback", rollback_to_step=0)
    path = rec.dump("unit_test", path=str(tmp_path / "f.json"), extra=7)
    data = json.loads(open(path).read())
    assert data["reason"] == "unit_test" and data["extra"] == 7
    assert data["steps"][0]["step"] == 1
    assert data["events"][0]["kind"] == "rollback"
    assert "metrics" in data


def test_dump_on_death_no_recorder_is_noop(fresh_flight):
    assert flight_mod.dump_on_death("x") is None


def test_dump_on_death_and_dedup(fresh_flight, tmp_path, monkeypatch):
    from seist_tpu.utils.logger import logger

    monkeypatch.setattr(logger, "_logdir", str(tmp_path), raising=False)
    rec = obs.FlightRecorder(capacity=4)
    flight_mod.install(rec)
    rec.record_step(3)
    p1 = flight_mod.dump_on_death("stall_watchdog")
    assert p1 and "stall_watchdog" in p1
    # The hard_exit funnel dedups against the richer dump just written...
    assert flight_mod.dump_on_death("hard_exit", dedup_s=5.0) is None
    # ...but an explicit dump (no dedup) still lands.
    assert flight_mod.dump_on_death("hard_exit") is not None
    assert flight_mod.DUMPED[0] == p1
    flight_mod.install(None)


def test_install_swaps_bus_sink(fresh_flight):
    from seist_tpu.obs.bus import BUS

    r1 = obs.FlightRecorder(capacity=4)
    r2 = obs.FlightRecorder(capacity=4)
    flight_mod.install(r1)
    flight_mod.install(r2)  # replaces r1's sink
    r1.record_step(0)
    r2.record_step(0)
    with BUS.span("swap_probe"):
        pass
    assert len(r1.payload("t")["spans"]) == 0
    assert len(r2.payload("t")["spans"]) == 1
    flight_mod.install(None)
    with BUS.span("swap_probe"):
        pass
    assert len(r2.payload("t")["spans"]) == 1


# ------------------------------------------------------------- http server
def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read().decode(), r.headers.get("Content-Type", "")


def test_metrics_http_endpoints(bus, fresh_flight):
    bus.counter("reads").inc(2)
    rec = obs.FlightRecorder(capacity=4)
    rec.record_step(1)
    flight_mod.install(rec)
    trigger = obs.ProfileTrigger()
    server = obs.start_metrics_server(0, bus=bus, profile_trigger=trigger)
    try:
        port = server.server_address[1]
        base = f"http://127.0.0.1:{port}"
        status, text, ctype = _get(base + "/metrics")
        assert status == 200 and "seist_reads_total 2" in text
        assert ctype.startswith("text/plain")
        status, text, _ = _get(base + "/metrics.json")
        assert status == 200
        assert json.loads(text)["counters"]["reads"] == 2.0
        status, text, _ = _get(base + "/flight")
        assert status == 200
        assert json.loads(text)["steps"][0]["step"] == 1
        status, text, _ = _get(base + "/healthz")
        assert status == 200
        # POST /profile arms the trigger the train loop polls.
        req = urllib.request.Request(
            base + "/profile?steps=3", method="POST", data=b""
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            assert r.status == 200
            assert json.loads(r.read())["requested_steps"] == 3
        assert trigger.consume() == 3
        assert trigger.consume() == 0  # one-shot
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/nope")
        assert ei.value.code == 404
    finally:
        server.shutdown()
        flight_mod.install(None)


def test_profile_trigger_last_write_wins():
    t = obs.ProfileTrigger()
    assert t.consume() == 0
    t.request(2)
    t.request(7)
    assert t.consume() == 7
    t.request(0)  # clamped to >= 1
    assert t.consume() == 1


def test_profile_trigger_request_during_consume_not_dropped():
    """The PR 6 consumed-and-dropped hazard, re-pinned after the
    lock-free rework: a request landing while consume() is mid-drain
    (HTTP handler thread vs the train loop's step poll) must be captured
    by that poll or the next one, never silently discarded."""
    from collections import deque

    t = obs.ProfileTrigger()

    class MidDrainRequest(deque):
        injected = False

        def popleft(self):
            v = deque.popleft(self)
            if not self.injected:
                # a second requester fires exactly between the drain's
                # atomic popleft operations
                MidDrainRequest.injected = True
                t.request(20)
            return v

    t._requests = MidDrainRequest([5], maxlen=64)
    assert t.consume() == 20  # the mid-drain request survives
    assert t.consume() == 0


# -------------------------------------------------------------- attribution
def test_attribution_dot_flops_exact():
    import jax.numpy as jnp

    def f(a, b):
        return jnp.dot(a, b)

    out = obs.attribute_step(
        f, (np.ones((4, 8), np.float32), np.ones((8, 16), np.float32))
    )
    dot = next(o for o in out["top_ops"] if o["op"] == "dot_general")
    assert dot["flops"] == 2 * 4 * 16 * 8
    assert dot["class"] == "matmul"
    # bytes: lhs + rhs + out, fp32
    assert dot["bytes_accessed"] == 4 * (4 * 8 + 8 * 16 + 4 * 16)


def test_attribution_scan_multiplies_by_length():
    import jax
    import jax.numpy as jnp

    def f(x):
        def body(c, _):
            return jnp.tanh(c), None

        c, _ = jax.lax.scan(body, x, None, length=5)
        return c

    out = obs.attribute_step(f, (np.ones((8,), np.float32),))
    tanh = next(o for o in out["top_ops"] if o["op"] == "tanh")
    assert tanh["count"] == 5
    assert tanh["flops"] == 5 * 8


def test_attribution_conv_flops_exact():
    import jax

    def f(x, k):
        return jax.lax.conv_general_dilated(
            x, k, window_strides=(1,), padding="VALID",
            dimension_numbers=("NCH", "OIH", "NCH"),
        )

    x = np.ones((2, 3, 32), np.float32)  # N=2 C=3 L=32
    k = np.ones((4, 3, 5), np.float32)  # O=4 I=3 K=5
    out = obs.attribute_step(f, (x, k))
    conv = next(o for o in out["top_ops"] if o["op"] == "conv_general_dilated")
    # MACs = N * L_out * O * I * K = 2*28*4*3*5; flops = 2*MACs
    assert conv["flops"] == 2 * (2 * 28 * 4 * 3 * 5)


def test_attribution_through_jit_and_measured_shares():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(a, b):
        return jnp.tanh(a @ b).sum()

    out = obs.attribute_step(
        f,
        (np.ones((16, 16), np.float32), np.ones((16, 16), np.float32)),
        measured_step_ms=10.0,
        peak_flops=1e12,
    )
    fracs = [o["time_frac"] for o in out["top_ops"]]
    assert out["n_op_kinds"] >= 3
    assert abs(sum(d["time_frac"] for d in out["mfu_decomposition"].values())
               - 1.0) < 1e-3
    assert all(o["est_ms"] is not None for o in out["top_ops"])
    assert fracs == sorted(fracs, reverse=True)  # top-k ordered by time
    assert "mfu_model" in out


def test_attribution_top_k_limit():
    import jax.numpy as jnp

    def f(a):
        return jnp.tanh(jnp.exp(a) + jnp.log(a) * a - a / 3).sum()

    out = obs.attribute_step(f, (np.ones((8,), np.float32) + 1,), top_k=2)
    assert len(out["top_ops"]) == 2
    assert out["n_op_kinds"] > 2


# ----------------------------------------------- dedup onto the span API
def test_profiling_stopwatch_delegates_to_obs(monkeypatch):
    from seist_tpu.utils import profiling

    with profiling.stopwatch() as elapsed:
        time.sleep(0.005)
        mid = elapsed()
    assert 0.005 <= mid
    assert elapsed() >= mid  # frozen after exit


def test_step_time_split_span_helpers():
    from seist_tpu.utils.profiling import StepTimeSplit

    split = StepTimeSplit(skip_first=0)
    for _ in range(2):
        with split.host():
            time.sleep(0.004)
        with split.device():
            time.sleep(0.002)
    s = split.summary()
    assert s["steps"] == 2
    assert s["host_wait_ms_per_step"] >= 4.0
    assert s["device_time_ms_per_step"] >= 2.0
    assert 0.5 < s["input_bound_fraction"] < 1.0


def test_jit_first_call_span_recorded():
    import jax.numpy as jnp

    from seist_tpu.obs.bus import BUS
    from seist_tpu.train.step import _first_call_span

    h = BUS.histogram("jit_first_call_ms", fn="unit_probe")
    before = h.count
    fn = _first_call_span(lambda x: jnp.sum(x), "unit_probe")
    fn(np.ones(4, np.float32))
    fn(np.ones(4, np.float32))
    assert h.count == before + 1  # only the first call is recorded


# ----------------------------------------- scrape-under-load consistency
class TestScrapeUnderLoad:
    """ISSUE 11 satellite: /metrics scrapes racing a flushing batcher
    must return consistent snapshots — no exceptions, parseable
    Prometheus text, and conserved batcher accounting at quiesce."""

    def test_concurrent_scrapes_while_batcher_flushes(self):
        import threading as th

        import numpy as np

        from seist_tpu.obs import trace as obs_trace
        from seist_tpu.obs.bus import BUS, render_prometheus
        from seist_tpu.serve.batcher import BatcherConfig, MicroBatcher

        def forward(batch):
            obs_trace.annotate_flush(program="scr/full/fp32", aot=True)
            time.sleep(0.001)
            return batch

        b = MicroBatcher(
            forward,
            BatcherConfig(max_batch=4, max_delay_ms=1.0, max_queue=64),
            name="scrape_load",
        )
        stop = th.Event()
        scrape_errors = []
        scrapes = {"n": 0}

        def scraper():
            # The scrape path a Prometheus server + the fleet aggregator
            # hit concurrently with traffic.
            while not stop.is_set():
                try:
                    text = render_prometheus(BUS)
                    assert "seist_serve_batcher_submitted" in text
                    for line in text.splitlines():
                        if line.startswith("#"):
                            continue
                        float(line.rsplit(" ", 1)[1])  # every sample parses
                    snap = BUS.snapshot()
                    stats = snap["collectors"]
                    sub = stats.get(
                        "serve_batcher_submitted{model=scrape_load}", 0
                    )
                    done = (
                        stats.get(
                            "serve_batcher_completed{model=scrape_load}", 0)
                        + stats.get(
                            "serve_batcher_expired{model=scrape_load}", 0)
                        + stats.get(
                            "serve_batcher_rejected{model=scrape_load}", 0)
                        + stats.get(
                            "serve_batcher_failed{model=scrape_load}", 0)
                    )
                    # Monotone sanity on a live snapshot: never more
                    # settled than submitted.
                    assert done <= sub
                    scrapes["n"] += 1
                except Exception as e:  # noqa: BLE001 - the assertion
                    scrape_errors.append(repr(e))
                    return

        threads = [th.Thread(target=scraper) for _ in range(2)]
        for t in threads:
            t.start()
        try:
            def client(i):
                rt = obs_trace.RequestTrace(
                    None, buffer=obs_trace.TraceBuffer(capacity=8)
                )
                b.submit(np.zeros((2,), np.float32), timeout_ms=10_000,
                         trace=rt)
                rt.finish(200)

            # ThreadPoolExecutor is imported at module top: concurrent.
            # futures lazy-loads its thread module, which must not first
            # happen inside an instrumented --lock-graph window.
            with ThreadPoolExecutor(8) as ex:
                list(ex.map(client, range(120)))
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
            b.shutdown()
        assert not scrape_errors, scrape_errors
        assert scrapes["n"] > 0, "scrapers never completed a pass"
        stats = b.stats()
        assert stats["submitted"] == 120
        assert (
            stats["completed"] + stats["expired"] + stats["rejected"]
            + stats["failed"]
        ) == 120
