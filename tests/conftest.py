"""Test configuration: force an 8-device virtual CPU mesh.

Must run before the first `import jax` anywhere in the test process, so this
lives at the top of conftest.py. Multi-device sharding tests use these 8
virtual CPU devices; real-TPU behavior is exercised by bench.py and the
driver's dryrun_multichip hook.
"""

import os

# Unconditional override: the session environment pins JAX_PLATFORMS to the
# real TPU tunnel (e.g. "axon") and its sitecustomize registers that backend
# at interpreter start, so the env var alone is not enough — the config update
# below (before any device query) is what actually forces CPU.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
