"""Test configuration: force an 8-device virtual CPU mesh.

Must run before the first `import jax` anywhere in the test process, so this
lives at the top of conftest.py. Multi-device sharding tests use these 8
virtual CPU devices; real-TPU behavior is exercised by bench.py and the
driver's dryrun_multichip hook.
"""

import os

# Unconditional override: the session environment pins JAX_PLATFORMS to the
# real TPU tunnel (e.g. "axon") and its sitecustomize registers that backend
# at interpreter start, so the env var alone is not enough — the config update
# below (before any device query) is what actually forces CPU.
#
# Escape hatch SEIST_TEST_TPU=1: leave the real TPU backend in place so the
# hardware lane (golden parity through the composed/fused TPU-default
# lowerings, tools/r3_silicon.sh parity step) runs on the chip. Virtual-mesh
# multi-device tests will then see 1 device and skip.
_USE_TPU = os.environ.get("SEIST_TEST_TPU") == "1"
if not _USE_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402

if not _USE_TPU:
    jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache for the suite: tier-1 wall time is
# dominated by jit compiles of the same model/step programs run after
# run — the disk cache (the same one bench.py and the CLI use) cuts a
# repeat compile ~3x even on CPU. Threshold 2 s: catches every model
# compile, skips trivial jits. First (cold) run pays full price.
#
# KNOWN HAZARD (ROADMAP open item): cache-DESERIALIZED executables can
# intermittently corrupt donated outputs in unsynchronized donated step
# chains on jax 0.4.37 CPU. tests/test_compile_budget.py (which asserts
# on state after such chains) opts out via its _no_persistent_cache
# fixture; a test that starts flaking with garbage donated outputs on
# warm caches should do the same.
from seist_tpu.utils.misc import enable_compile_cache  # noqa: E402

enable_compile_cache(min_compile_seconds=2)

import sys

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Repo root on sys.path once, for every test/fixture importing tools.*
# (tools.fixtures, tools.jaxlint, ...).
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


# -- jaxlint runtime audit lane -----------------------------------------------
# `pytest -m smoke --tracer-leaks` re-runs the pure-unit lane with
# jax.check_tracer_leaks active around every test: any tracer escaping its
# trace (closure capture, storing tracers on self, ...) becomes a hard
# error instead of a latent use-after-trace bug. Opt-in flag because leak
# checking disables some jit caching and roughly doubles lane wall time.
def pytest_addoption(parser):
    parser.addoption(
        "--tracer-leaks",
        action="store_true",
        default=False,
        help="run every test under jax.check_tracer_leaks "
        "(jaxlint runtime audit lane; see docs/STATIC_ANALYSIS.md)",
    )
    parser.addoption(
        "--lock-graph",
        action="store_true",
        default=False,
        help="run every test under threadlint's LockGraph: locks created "
        "during the test are instrumented, and the test fails on a "
        "lock-acquisition-order cycle (potential deadlock) or a lock "
        "held across a known blocking call (threadlint runtime audit "
        "lane; see docs/STATIC_ANALYSIS.md)",
    )


@pytest.fixture
def compile_budget():
    """Scoped compile counter (tools/jaxlint/runtime.py): everything jitted
    inside the test is attributed by function name + abstract shape
    signature. Assert with ``compile_budget.assert_compiles_once(name)``
    after driving the jitted path — see tests/test_compile_budget.py."""
    from tools.jaxlint.runtime import CompileBudget

    with CompileBudget() as budget:
        yield budget


@pytest.fixture(autouse=True)
def _tracer_leak_lane(request):
    if request.config.getoption("--tracer-leaks", default=False):
        from tools.jaxlint.runtime import tracer_leak_check

        with tracer_leak_check():
            yield
    else:
        yield


@pytest.fixture(autouse=True)
def _lock_graph_lane(request):
    """`pytest --lock-graph` (threadlint runtime lane, `make lockgraph`):
    every lock CREATED during the test is instrumented; teardown fails
    the test on an acquisition-order cycle or a lock held across a
    blocking call. Graphs nest, so tests that drive their own LockGraph
    still work inside the lane."""
    if request.config.getoption("--lock-graph", default=False):
        from tools.threadlint.runtime import LockGraph

        with LockGraph() as graph:
            yield
        graph.assert_clean()
    else:
        yield


# Smoke lane (`pytest -m smoke`): the pure-unit subset that verifies the
# round's core claims in <5 min on a 1-core host (measured ~90 s). Files are
# marked here centrally so the lane can't silently drift as tests are added;
# model-forward/e2e/golden tests stay out (jit compiles dominate them).
_SMOKE_FILES = {
    "test_losses.py",
    "test_metrics.py",
    "test_postprocess.py",
    "test_misc.py",
    "test_taskspec.py",
    "test_preprocess.py",
    "test_results.py",
    "test_common_ops.py",
    "test_collectives.py",
    "test_visualization.py",
    "test_stream.py",
    "test_stream_session.py",
    "test_stream_mux.py",
    "test_supervise.py",
    "test_native.py",
    "test_bench_unit.py",
    "test_packed.py",
    "test_collective_report.py",
    "test_jaxlint.py",
    "test_io_guard.py",
    "test_obs.py",
    "test_trace.py",
    "test_meters.py",
    "test_router.py",
    "test_threadlint.py",
    "test_dist_broadcast.py",
    "test_batch_fleet.py",  # lease plane: fake work, ms clocks (slow e2e opts out)
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if os.path.basename(str(item.fspath)) in _SMOKE_FILES:
            if item.get_closest_marker("slow") is None:
                item.add_marker(pytest.mark.smoke)


def make_packed_dir(tmp_path_factory, n_events=24, trace_samples=1024,
                    n_parts=2, shard_mb=512):
    """Shared recipe: write a DiTing-light fixture, repack it with
    pack_dataset. Returns (source_dataset, packed_dir). Used by
    tests/test_packed.py and the packed worker-e2e lane."""
    from tools.fixtures import write_diting_light_fixture

    from seist_tpu.data.packed import pack_dataset
    from seist_tpu.registry import DATASETS

    src_dir = str(tmp_path_factory.mktemp("packed_src"))
    write_diting_light_fixture(
        src_dir, n_events=n_events, trace_samples=trace_samples,
        n_parts=n_parts,
    )
    src = DATASETS.create(
        "diting_light",
        seed=0,
        mode="train",
        data_dir=src_dir,
        shuffle=False,
        data_split=False,
    )
    out = str(tmp_path_factory.mktemp("packed_out"))
    pack_dataset(src, out, shard_mb=shard_mb)
    return src, out
