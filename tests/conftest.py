"""Test configuration: force an 8-device virtual CPU mesh.

Must run before the first `import jax` anywhere in the test process, so this
lives at the top of conftest.py. Multi-device sharding tests use these 8
virtual CPU devices; real-TPU behavior is exercised by bench.py and the
driver's dryrun_multichip hook.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
