"""Golden-parity tests: our flax models must reproduce the reference's
shipped pretrained checkpoints (SURVEY.md §7.9).

For each of the 18 ``pretrained/*.pth`` artifacts: convert the torch
state-dict with tools/parity.py, forward a fixed waveform through our model,
and compare against the torch reference model's output (reference imported
read-only from /root/reference, with a timm.DropPath stub — identity at
eval). Tolerance 1e-4 absolute on probability/regression outputs; observed
diffs are ~1e-5 (fp32 op-order noise).
"""

import os
import sys
import types

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import seist_tpu  # noqa: E402
from seist_tpu.models import api  # noqa: E402

seist_tpu.load_all()

REFERENCE = "/root/reference"
PRETRAINED = os.path.join(REFERENCE, "pretrained")

pytestmark = [
    pytest.mark.slow,  # 18 ckpts x 8192-sample forwards + torch reference
    pytest.mark.skipif(
        not os.path.isdir(PRETRAINED),
        reason="reference pretrained weights absent",
    ),
]

CHECKPOINTS = sorted(
    f[: -len(".pth")] for f in os.listdir(PRETRAINED) if f.endswith(".pth")
) if os.path.isdir(PRETRAINED) else []


def _stub_timm():
    import torch.nn as tnn

    class DropPath(tnn.Module):  # identity at eval — parity-safe
        def __init__(self, drop_prob=None):
            super().__init__()

        def forward(self, x):
            return x

    timm = types.ModuleType("timm")
    models_m = types.ModuleType("timm.models")
    layers_m = types.ModuleType("timm.models.layers")
    layers_m.DropPath = DropPath
    sys.modules.setdefault("timm", timm)
    sys.modules.setdefault("timm.models", models_m)
    sys.modules.setdefault("timm.models.layers", layers_m)


@pytest.fixture(scope="module")
def torch_models():
    _stub_timm()
    if REFERENCE not in sys.path:
        sys.path.insert(0, REFERENCE)
    from models import create_model as torch_create  # reference registry

    return torch_create


def _as_tuple(x):
    return x if isinstance(x, (tuple, list)) else (x,)


@pytest.mark.parametrize("ckpt", CHECKPOINTS)
def test_pretrained_forward_parity(ckpt, torch_models):
    import torch

    from parity import convert_state_dict

    model_name = ckpt.rsplit("_", 1)[0]  # strip _diting/_pnw suffix

    sd = torch.load(
        os.path.join(PRETRAINED, f"{ckpt}.pth"),
        map_location="cpu",
        weights_only=True,
    )
    model = api.create_model(model_name, in_samples=8192)
    shapes = api.param_shapes(model, in_samples=8192)
    variables = convert_state_dict(sd, shapes)

    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, 8192, 3)).astype(np.float32)
    ours = _as_tuple(model.apply(variables, x, train=False))

    tm = torch_models(model_name, in_channels=3, in_samples=8192)
    tm.load_state_dict(sd)
    tm.eval()
    with torch.no_grad():
        ref = _as_tuple(tm(torch.from_numpy(x.transpose(0, 2, 1))))

    assert len(ours) == len(ref)
    for o, r in zip(ours, ref):
        o = np.asarray(o)
        r = r.numpy()
        if o.ndim == 3:  # dense outputs: ours (N, L, C), torch (N, C, L)
            r = r.transpose(0, 2, 1)
        assert o.shape == r.shape, (o.shape, r.shape)
        np.testing.assert_allclose(o, r, atol=1e-4, rtol=1e-3)


# ----------------------------------------------------- gradient-level parity
# Forward parity can't catch a silent backward divergence (BN momentum,
# DropPath scaling, interpolate vjp...). These tests push ONE identical
# batch through the torch reference (its own loss, ref train.py:108-111)
# and through our flax step with converted weights, then compare loss and
# per-leaf gradients (VERDICT r1 #6).

L_GRAD = 1024
# eqtransformer exercises the scan-BiLSTM + additive-attention backward —
# the converter splits torch's fused LSTM gates into OptimizedLSTMCell
# leaves (tools/parity.py::_convert_lstm_group).
# magnet covers the fused-LSTM split at hidden 100 + MousaviLoss; ditingmotion
# covers CombConv/side-fusion + dual Focal loss (and pinned the channel-major
# flatten fix in models/ditingmotion.py::SideLayer). baz_network is excluded:
# its eigen feature branch uses eigh on the symmetric covariance where the
# reference uses no-grad general eig — eigenvalue ordering/eigenvector sign
# conventions differ, so forward activations (and hence all grads) diverge by
# design (BASELINE.md design notes; the branch is no-grad in BOTH frameworks).
GRAD_MODELS = [
    "phasenet",
    "seist_s_dpk",
    "seist_m_dpk",
    "eqtransformer",
    "magnet",
    "ditingmotion",
]


def _grad_case(model_name):
    """(x, in_channels, y) for one gradient-parity case; the torch-side
    target is derived from ``y`` in the test (transpose for dense labels,
    per-element tensors for tuple labels)."""
    rng = np.random.default_rng(7)
    if model_name == "magnet":
        x = rng.standard_normal((2, L_GRAD, 3)).astype(np.float32)
        y = rng.uniform(1.0, 6.0, (2, 1)).astype(np.float32)
        return x, 3, y
    if model_name == "ditingmotion":
        x = rng.standard_normal((2, L_GRAD, 2)).astype(np.float32)
        clr = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 2)]
        pmp = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 2)]
        return x, 2, (clr, pmp)
    x, y = _dpk_batch()
    return x, 3, y


def _dpk_batch(batch=2, length=L_GRAD):
    rng = np.random.default_rng(42)
    x = rng.standard_normal((batch, length, 3)).astype(np.float32)
    y = np.zeros((batch, length, 3), np.float32)
    y[:, length // 4, 1] = 1.0
    y[:, length // 2, 2] = 1.0
    y[..., 0] = 1.0 - y[..., 1] - y[..., 2]
    return x, y


def _torch_loss_for(model_name):
    """The reference's own loss construction (ref config.py:421-432)."""
    if REFERENCE not in sys.path:
        sys.path.insert(0, REFERENCE)
    from config import Config  # reference, read-only

    return Config.get_loss(model_name)


def _flat_grads_from_torch(tm, shapes):
    """torch .grad tensors -> our flax tree layout via tools/parity.py."""
    from parity import _fit_leaf, torch_key_to_flax

    import jax

    flat_target = {}
    leaves = jax.tree_util.tree_flatten_with_path(shapes["params"])[0]
    for path, leaf in leaves:
        key = tuple(str(k.key) for k in path)
        flat_target[key] = np.shape(leaf)

    from parity import _convert_lstm_group, collect_lstm_leaf

    out = {}
    lstm_groups = {}
    for tkey, p in tm.named_parameters():
        if p.grad is None:
            continue
        mapped = torch_key_to_flax(tkey)
        assert mapped is not None, tkey
        coll, path = mapped
        if coll != "params":
            continue
        if collect_lstm_leaf(path, p.grad.detach().cpu().numpy(), lstm_groups):
            continue
        out[path] = _fit_leaf(
            p.grad.detach().cpu().numpy(), flat_target[path], tkey
        )
    if lstm_groups:
        ft = {("params", k): v for k, v in flat_target.items()}
        for (prefix, direction), leaves in lstm_groups.items():
            # The gate-split transform is linear so it maps grads too, with
            # one twist: flax's single bias is torch's bias_ih + bias_hh, so
            # dL/d(flax bias) == dL/d(bias_ih) == dL/d(bias_hh); the
            # converter SUMS the two bias leaves, so zero one side.
            leaves = dict(leaves)
            leaves["bias_hh"] = np.zeros_like(leaves["bias_hh"])
            for (_, pth), val in _convert_lstm_group(
                prefix, direction, leaves, ft
            ).items():
                out[pth] = val
    return out


def _torch_state_dict(model_name, torch_models, in_channels=3):
    """Shipped pretrained weights for seist models; the 18 published
    checkpoints are all seist variants, so other models use a seeded
    random-init torch model's state-dict instead."""
    import torch

    path = os.path.join(PRETRAINED, f"{model_name}_diting.pth")
    if os.path.exists(path):
        return torch.load(path, map_location="cpu", weights_only=True)
    torch.manual_seed(0)
    tm = torch_models(model_name, in_channels=in_channels, in_samples=L_GRAD)
    return tm.state_dict()


@pytest.mark.parametrize("model_name", GRAD_MODELS)
def test_gradient_parity_eval_mode(model_name, torch_models):
    """Grads of loss(model(x)) w.r.t. every param match torch (eval mode:
    running BN stats, no dropout — isolates the backward of conv /
    attention / interpolate / pooling)."""
    import jax
    import torch

    from parity import convert_state_dict

    from seist_tpu import taskspec

    x, in_ch, y = _grad_case(model_name)
    sd = _torch_state_dict(model_name, torch_models, in_channels=in_ch)
    model = api.create_model(model_name, in_samples=L_GRAD, in_channels=in_ch)
    shapes = api.param_shapes(model, in_samples=L_GRAD, in_channels=in_ch)
    variables = convert_state_dict(sd, shapes)

    flax_loss = taskspec.make_loss(model_name)
    spec = taskspec.get_task_spec(model_name)

    def loss_fn(params):
        var = {"params": params}
        if "batch_stats" in variables:  # ditingmotion/magnet have no BN
            var["batch_stats"] = variables["batch_stats"]
        out = model.apply(
            var,
            x,
            train=False,
        )
        o, t = out, y
        if spec.outputs_transform_for_loss is not None:
            o = spec.outputs_transform_for_loss(o)
        return flax_loss(o, t)

    our_loss, our_grads = jax.value_and_grad(loss_fn)(variables["params"])

    tm = torch_models(model_name, in_channels=in_ch, in_samples=L_GRAD)
    tm.load_state_dict(sd)
    tm.eval()
    tl_fn = _torch_loss_for(model_name)
    tx = torch.from_numpy(x.transpose(0, 2, 1))
    if isinstance(y, tuple):
        ty = [torch.from_numpy(t) for t in y]
    else:
        ty = torch.from_numpy(y)
        ty = ty.permute(0, 2, 1) if ty.ndim == 3 else ty
    t_out = tm(tx)
    t_loss = tl_fn(t_out, ty)
    t_loss.backward()

    np.testing.assert_allclose(
        float(our_loss), float(t_loss.detach()), rtol=1e-5, atol=1e-6
    )

    t_grads = _flat_grads_from_torch(tm, shapes)
    checked = _compare_grad_trees(our_grads, t_grads)
    assert checked > 10


def _compare_grad_trees(
    our_grads, t_grads, cos_tol=0.9999, rel_tol=5e-3, expect_zero=None
):
    """Per-leaf comparison. Leaves with MATHEMATICALLY zero gradients are
    exempted BY NAME (never by a broad magnitude heuristic, which could
    silently exempt a corrupted small leaf):

    * ``k_proj/bias`` always: softmax is invariant to a uniform key shift.
    * ``attn/ba`` always (eqtransformer): the additive-attention score bias
      is a uniform shift under the softmax over L (ref
      eqtransformer.py:135-198), so its gradient is identically 0.
    * ``expect_zero(key)`` per call: e.g. train-mode conv biases feeding
      straight into BatchNorm — the batch-mean subtraction cancels a
      uniform bias exactly, so its gradient is identically 0.

    Exempted leaves are still asserted to BE ~zero on both sides.
    """
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(our_grads)[0]
    gscale = max(
        (np.abs(t_grads[k]).max() for k in t_grads), default=1.0
    )
    checked = 0
    for path, g in leaves:
        key = tuple(str(k.key) for k in path)
        assert key in t_grads, f"missing torch grad for {key}"
        a = np.asarray(g).ravel()
        b = t_grads[key].ravel()
        both_tiny = max(np.abs(a).max(), np.abs(b).max()) < 1e-6 * gscale
        if key[-2:] == ("k_proj", "bias") or key[-2:] == ("attn", "ba") or (
            expect_zero is not None and expect_zero(key)
        ):
            assert both_tiny, f"{key}: expected ~0 grad"
            continue
        if np.abs(a).max() < 1e-20 and np.abs(b).max() < 1e-20:
            continue  # exactly-zero pair (e.g. genuinely unused param)
        cos = float(
            np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b))
        )
        assert cos > cos_tol, f"{key}: grad cosine {cos}"
        scale = max(np.abs(b).max(), 1e-12)
        assert np.abs(a - b).max() / scale < rel_tol, (
            f"{key}: rel grad err {np.abs(a - b).max() / scale}"
        )
        checked += 1
    return checked


def test_gradient_and_bn_parity_train_mode(torch_models):
    """Train-mode parity on phasenet (dropout-free): batch-stat BN forward,
    gradients, AND the updated running stats (BN momentum semantics,
    ref train.py:108-111 + SyncBN analogue)."""
    import jax
    import torch

    from parity import convert_state_dict

    from seist_tpu import taskspec

    model_name = "phasenet"
    sd = _torch_state_dict(model_name, torch_models)
    # drop_rate=0 on BOTH sides: train mode would otherwise draw different
    # dropout masks per framework and nothing would be comparable.
    model = api.create_model(model_name, in_samples=L_GRAD, drop_rate=0.0)
    shapes = api.param_shapes(model, in_samples=L_GRAD)
    variables = convert_state_dict(sd, shapes)
    x, y = _dpk_batch()
    flax_loss = taskspec.make_loss(model_name)

    def loss_fn(params):
        out, mutated = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x,
            train=True,
            mutable=["batch_stats"],
            rngs={"dropout": jax.random.PRNGKey(0)},
        )
        return flax_loss(out, y), mutated["batch_stats"]

    (our_loss, new_stats), our_grads = jax.value_and_grad(
        loss_fn, has_aux=True
    )(variables["params"])

    tm = torch_models(
        model_name, in_channels=3, in_samples=L_GRAD, drop_rate=0.0
    )
    tm.load_state_dict(sd)
    tm.train()
    tl_fn = _torch_loss_for(model_name)
    t_out = tm(torch.from_numpy(x.transpose(0, 2, 1)))
    t_loss = tl_fn(t_out, torch.from_numpy(y.transpose(0, 2, 1)))
    t_loss.backward()

    np.testing.assert_allclose(
        float(our_loss), float(t_loss.detach()), rtol=1e-5, atol=1e-6
    )

    # Updated running stats must match (momentum 0.1 torch == 0.9 flax).
    t_sd = tm.state_dict()
    from parity import torch_key_to_flax

    flat_new = {
        tuple(str(k.key) for k in path): np.asarray(leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(new_stats)[0]
    }
    stats_checked = 0
    for tkey, tval in t_sd.items():
        mapped = torch_key_to_flax(tkey)
        if mapped is None or mapped[0] != "batch_stats":
            continue
        ours_leaf = flat_new[mapped[1]]
        np.testing.assert_allclose(
            ours_leaf, tval.numpy(), rtol=1e-4, atol=1e-5,
            err_msg=f"running stat {tkey}",
        )
        stats_checked += 1
    assert stats_checked > 10

    t_grads = _flat_grads_from_torch(tm, shapes)

    # Train-mode BN cancels any uniform bias added by the conv right before
    # it (batch-mean subtraction), so every conv bias except the final
    # conv_out (no BN after it) has an identically-zero gradient.
    def bn_cancelled_bias(key):
        return (
            key[-1] == "bias"
            and key[-2].startswith("conv")
            and key[-2] != "conv_out"
        )

    assert (
        _compare_grad_trees(our_grads, t_grads, expect_zero=bn_cancelled_bias)
        > 10
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "env",
    [
        # round-2 defaults-on-TPU: shift-FMA depthwise + block-diag-dense
        # grouped, with the per-path stems
        {"SEIST_DWCONV_IMPL": "shift", "SEIST_GCONV_IMPL": "dense"},
        # composed DSConv (the TPU default since the triple-product
        # lowering) + fused one-conv stem, on published weights
        {
            "SEIST_DSCONV_IMPL": "composed",
            "SEIST_STEM_IMPL": "fused",
            "SEIST_GCONV_IMPL": "dense",
        },
    ],
    ids=["shift+dense", "composed+fused"],
)
def test_pretrained_forward_parity_tpu_lowerings(torch_models, monkeypatch, env):
    """Golden parity THROUGH the TPU-default conv lowerings
    (models/common.py, models/seist.py DSConvNormAct/StemBlock). Off-TPU
    the defaults fall back to native grouped convs, so without forcing the
    env this path would only ever be exercised on real hardware."""
    import torch

    from parity import convert_state_dict

    for k, v in env.items():
        monkeypatch.setenv(k, v)

    ckpt = "seist_s_dpk_diting"
    model_name = "seist_s_dpk"
    sd = torch.load(
        os.path.join(PRETRAINED, f"{ckpt}.pth"),
        map_location="cpu",
        weights_only=True,
    )
    model = api.create_model(model_name, in_samples=8192)
    shapes = api.param_shapes(model, in_samples=8192)
    variables = convert_state_dict(sd, shapes)

    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, 8192, 3)).astype(np.float32)
    ours = np.asarray(model.apply(variables, x, train=False))

    tm = torch_models(model_name, in_channels=3, in_samples=8192)
    tm.load_state_dict(sd)
    tm.eval()
    with torch.no_grad():
        ref = tm(torch.from_numpy(x.transpose(0, 2, 1))).numpy()
    np.testing.assert_allclose(ours, ref.transpose(0, 2, 1), atol=1e-4, rtol=1e-3)


def test_distpt_random_init_forward_parity(torch_models):
    """distpt_network has no task spec (the reference ships its config
    commented out, ref config.py:112-125), so it gets forward parity with
    a seeded random-init torch state-dict instead of a gradient test —
    covering the causal-TCN trunk and both regression heads."""
    import torch

    from parity import convert_state_dict

    sd = _torch_state_dict("distpt_network", torch_models)
    model = api.create_model("distpt_network", in_samples=L_GRAD)
    shapes = api.param_shapes(model, in_samples=L_GRAD)
    variables = convert_state_dict(sd, shapes)

    rng = np.random.default_rng(3)
    x = rng.standard_normal((2, L_GRAD, 3)).astype(np.float32)
    ours = _as_tuple(model.apply(variables, x, train=False))

    tm = torch_models("distpt_network", in_channels=3, in_samples=L_GRAD)
    tm.load_state_dict(sd)
    tm.eval()
    with torch.no_grad():
        ref = _as_tuple(tm(torch.from_numpy(x.transpose(0, 2, 1))))

    assert len(ours) == len(ref)
    for o, r in zip(ours, ref):
        # Both heads are (N, 2) regression outputs — no layout transpose.
        np.testing.assert_allclose(
            np.asarray(o), r.numpy(), atol=1e-5, rtol=1e-4
        )
