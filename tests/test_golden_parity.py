"""Golden-parity tests: our flax models must reproduce the reference's
shipped pretrained checkpoints (SURVEY.md §7.9).

For each of the 18 ``pretrained/*.pth`` artifacts: convert the torch
state-dict with tools/parity.py, forward a fixed waveform through our model,
and compare against the torch reference model's output (reference imported
read-only from /root/reference, with a timm.DropPath stub — identity at
eval). Tolerance 1e-4 absolute on probability/regression outputs; observed
diffs are ~1e-5 (fp32 op-order noise).
"""

import os
import sys
import types

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import seist_tpu  # noqa: E402
from seist_tpu.models import api  # noqa: E402

seist_tpu.load_all()

REFERENCE = "/root/reference"
PRETRAINED = os.path.join(REFERENCE, "pretrained")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(PRETRAINED), reason="reference pretrained weights absent"
)

CHECKPOINTS = sorted(
    f[: -len(".pth")] for f in os.listdir(PRETRAINED) if f.endswith(".pth")
) if os.path.isdir(PRETRAINED) else []


def _stub_timm():
    import torch.nn as tnn

    class DropPath(tnn.Module):  # identity at eval — parity-safe
        def __init__(self, drop_prob=None):
            super().__init__()

        def forward(self, x):
            return x

    timm = types.ModuleType("timm")
    models_m = types.ModuleType("timm.models")
    layers_m = types.ModuleType("timm.models.layers")
    layers_m.DropPath = DropPath
    sys.modules.setdefault("timm", timm)
    sys.modules.setdefault("timm.models", models_m)
    sys.modules.setdefault("timm.models.layers", layers_m)


@pytest.fixture(scope="module")
def torch_models():
    _stub_timm()
    if REFERENCE not in sys.path:
        sys.path.insert(0, REFERENCE)
    from models import create_model as torch_create  # reference registry

    return torch_create


def _as_tuple(x):
    return x if isinstance(x, (tuple, list)) else (x,)


@pytest.mark.parametrize("ckpt", CHECKPOINTS)
def test_pretrained_forward_parity(ckpt, torch_models):
    import torch

    from parity import convert_state_dict

    model_name = ckpt.rsplit("_", 1)[0]  # strip _diting/_pnw suffix

    sd = torch.load(
        os.path.join(PRETRAINED, f"{ckpt}.pth"),
        map_location="cpu",
        weights_only=True,
    )
    model = api.create_model(model_name, in_samples=8192)
    shapes = api.param_shapes(model, in_samples=8192)
    variables = convert_state_dict(sd, shapes)

    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, 8192, 3)).astype(np.float32)
    ours = _as_tuple(model.apply(variables, x, train=False))

    tm = torch_models(model_name, in_channels=3, in_samples=8192)
    tm.load_state_dict(sd)
    tm.eval()
    with torch.no_grad():
        ref = _as_tuple(tm(torch.from_numpy(x.transpose(0, 2, 1))))

    assert len(ours) == len(ref)
    for o, r in zip(ours, ref):
        o = np.asarray(o)
        r = r.numpy()
        if o.ndim == 3:  # dense outputs: ours (N, L, C), torch (N, C, L)
            r = r.transpose(0, 2, 1)
        assert o.shape == r.shape, (o.shape, r.shape)
        np.testing.assert_allclose(o, r, atol=1e-4, rtol=1e-3)
