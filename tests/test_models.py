"""Model-zoo tests: registration surface, output shapes, parameter parity.

Parameter parity: reference state-dict totals (BASELINE.md, measured from
pretrained/*.pth) equal our params + batch_stats + one `num_batches_tracked`
scalar per BN layer. Counting uses jax.eval_shape (no compute) so the suite
stays fast.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import seist_tpu
from seist_tpu import taskspec
from seist_tpu.models import api
from seist_tpu.registry import MODELS

seist_tpu.load_all()

ALL_MODELS = [
    "phasenet",
    "eqtransformer",
    "magnet",
    "baz_network",
    "distpt_network",
    "ditingmotion",
] + [f"seist_{s}_{t}" for s in "sml" for t in ("dpk", "pmp", "emg", "baz", "dis")]


def test_registry_has_21_models():
    # API surface parity: SURVEY.md Appendix B / reference README.md:54
    assert set(ALL_MODELS) <= set(MODELS.names())
    assert len(ALL_MODELS) == 21


def _count_with_bn(model, in_samples, in_channels):
    shapes = api.param_shapes(model, in_samples=in_samples, in_channels=in_channels)
    n_params = api.count_params(shapes["params"])
    bn_leaves = jax.tree_util.tree_leaves(shapes.get("batch_stats", {}))
    n_stats = sum(int(np.prod(p.shape)) for p in bn_leaves)
    n_bn_layers = len(bn_leaves) // 2
    return n_params + n_stats + n_bn_layers


@pytest.mark.parametrize(
    "name,ref_total",
    [
        # Reference state-dict numels incl. BN buffers (BASELINE.md).
        ("seist_s_dpk", 128_981),
        ("seist_m_dpk", 387_620),
        ("seist_l_dpk", 670_681),
        ("seist_l_emg", 537_461),
    ],
)
def test_seist_param_parity(name, ref_total):
    model = api.create_model(name)
    assert _count_with_bn(model, 8192, 3) == ref_total


L_SMALL = 512


@pytest.mark.parametrize(
    "size",
    [
        "s",
        pytest.param("m", marks=pytest.mark.slow),
        pytest.param("l", marks=pytest.mark.slow),
    ],
)
def test_seist_dpk_output_shape(size):
    model = api.create_model(f"seist_{size}_dpk", in_samples=L_SMALL)
    x = jnp.zeros((2, L_SMALL, 3))
    v = api.init_variables(model, in_samples=L_SMALL, batch_size=2)
    out = jax.jit(lambda v, x: model.apply(v, x, train=False))(v, x)
    assert out.shape == (2, L_SMALL, 3)
    # sigmoid outputs are probabilities
    assert float(jnp.min(out)) >= 0.0 and float(jnp.max(out)) <= 1.0


def test_seist_cls_and_reg_heads():
    x = jnp.zeros((2, L_SMALL, 3))
    m_cls = api.create_model("seist_s_pmp", in_samples=L_SMALL)
    v = api.init_variables(m_cls, in_samples=L_SMALL, batch_size=2)
    out = jax.jit(lambda v, x: m_cls.apply(v, x, train=False))(v, x)
    assert out.shape == (2, 2)
    np.testing.assert_allclose(np.asarray(out.sum(-1)), 1.0, atol=1e-5)  # softmax

    m_reg = api.create_model("seist_s_emg", in_samples=L_SMALL)
    v = api.init_variables(m_reg, in_samples=L_SMALL, batch_size=2)
    out = jax.jit(lambda v, x: m_reg.apply(v, x, train=False))(v, x)
    assert out.shape == (2, 1)
    assert 0.0 <= float(out.min()) and float(out.max()) <= 8.0  # sigmoid x 8


def test_phasenet_output_is_softmax():
    model = api.create_model("phasenet")
    x = jnp.zeros((2, 1024, 3))
    v = api.init_variables(model, in_samples=1024, batch_size=2)
    out = jax.jit(lambda v, x: model.apply(v, x, train=False))(v, x)
    assert out.shape == (2, 1024, 3)
    np.testing.assert_allclose(np.asarray(out.sum(-1)), 1.0, atol=1e-5)


def test_eqtransformer_output_shape():
    model = api.create_model("eqtransformer", in_samples=L_SMALL)
    x = jnp.zeros((2, L_SMALL, 3))
    v = api.init_variables(model, in_samples=L_SMALL, batch_size=2)
    out = jax.jit(lambda v, x: model.apply(v, x, train=False))(v, x)
    assert out.shape == (2, L_SMALL, 3)
    assert float(out.min()) >= 0.0 and float(out.max()) <= 1.0  # sigmoid


def test_magnet_output_shape():
    model = api.create_model("magnet")
    x = jnp.zeros((2, 1024, 3))
    v = api.init_variables(model, in_samples=1024, batch_size=2)
    out = jax.jit(lambda v, x: model.apply(v, x, train=False))(v, x)
    assert out.shape == (2, 2)  # (y_hat, log sigma^2)


def test_baz_network_output_shape():
    model = api.create_model("baz_network", in_samples=1024)
    x = jnp.ones((2, 1024, 3)) * jnp.arange(3)[None, None, :]
    v = api.init_variables(model, in_samples=1024, batch_size=2)
    out = jax.jit(lambda v, x: model.apply(v, x, train=False))(v, x)
    assert isinstance(out, tuple) and out[0].shape == (2, 1) and out[1].shape == (2, 1)


def test_baz_cov_features_match_reference_semantics(rng):
    import torch

    from seist_tpu.models.baz_network import _cov_features

    x = rng.normal(size=(2, 64, 3)).astype(np.float32)
    feats = np.asarray(_cov_features(jnp.asarray(x)))  # (N, 2C+1, C)
    # torch-side covariance on channels-first input (ref: baz_network.py:67-77)
    xt = torch.from_numpy(np.moveaxis(x, -1, 1).copy())
    diff = xt - xt.mean(-1, keepdim=True)
    cov_ref = torch.einsum("ncl,ndl->ncd", diff, diff) / (x.shape[1] - 1)
    cov_ref = cov_ref / cov_ref.abs().amax(dim=(-2, -1), keepdim=True)
    np.testing.assert_allclose(
        feats[:, :3, :].transpose(0, 2, 1), cov_ref.numpy(), atol=2e-3
    )


def test_distpt_output_shape():
    model = api.create_model("distpt_network")
    x = jnp.zeros((2, 1024, 3))
    v = api.init_variables(model, in_samples=1024, batch_size=2)
    out = jax.jit(lambda v, x: model.apply(v, x, train=False))(v, x)
    assert out[0].shape == (2, 2) and out[1].shape == (2, 2)


def test_ditingmotion_output_shape():
    model = api.create_model("ditingmotion", in_channels=2, in_samples=128)
    x = jnp.zeros((2, 128, 2))
    v = api.init_variables(model, in_samples=128, in_channels=2, batch_size=2)
    clr, pmp = jax.jit(lambda v, x: model.apply(v, x, train=False))(v, x)
    assert clr.shape == (2, 2) and pmp.shape == (2, 2)


def test_every_model_has_a_task_spec():
    for name in ALL_MODELS:
        if name == "distpt_network":
            # Registered but config-disabled in the reference too
            # (config.py:112-125: no travel-time data in DiTing).
            with pytest.raises(KeyError):
                taskspec.get_task_spec(name)
            continue
        taskspec.get_task_spec(name)


def test_train_mode_uses_dropout_rngs():
    model = api.create_model("seist_s_dpk", in_samples=L_SMALL)
    v = api.init_variables(model, in_samples=L_SMALL)
    x = jnp.ones((2, L_SMALL, 3))
    apply = jax.jit(
        lambda v, x, k: model.apply(
            v, x, train=True, rngs={"dropout": k}, mutable=["batch_stats"]
        )
    )
    out1, _ = apply(v, x, jax.random.PRNGKey(1))
    out2, _ = apply(v, x, jax.random.PRNGKey(2))
    # different dropout keys => different outputs
    assert not np.allclose(np.asarray(out1), np.asarray(out2))


def test_batch_stats_update_in_train_mode():
    model = api.create_model("phasenet")
    v = api.init_variables(model, in_samples=256)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 256, 3)), jnp.float32)
    _, updates = jax.jit(
        lambda v, x, k: model.apply(
            v, x, train=True, rngs={"dropout": k}, mutable=["batch_stats"]
        )
    )(v, x, jax.random.PRNGKey(0))
    before = jax.tree_util.tree_leaves(v["batch_stats"])
    after = jax.tree_util.tree_leaves(updates["batch_stats"])
    assert any(
        not np.allclose(np.asarray(b), np.asarray(a)) for b, a in zip(before, after)
    )


def test_eqt_banded_mask_matches_torch():
    torch = pytest.importorskip("torch")
    for w in (3, 4, 5):
        L = 9
        ref = (
            torch.ones((L, L), dtype=torch.bool)
            .tril(w // 2 - 1)
            .triu(-w // 2)
            .numpy()
        )
        i = np.arange(L)[:, None]
        j = np.arange(L)[None, :]
        ours = (j - i <= w // 2 - 1) & (j - i >= (-w) // 2)
        np.testing.assert_array_equal(ours, ref, err_msg=f"width {w}")


class TestComposedDSConv:
    """DSConvNormAct's composed lowering (one dense conv from the
    in_proj*dconv*pconv triple product) must be checkpoint-identical and
    numerically equivalent to the literal 3-stage pipeline
    (seist_tpu/models/seist.py DSConvNormAct docstring)."""

    def _make(self, impl, stride, k=11):
        from seist_tpu.models.seist import DSConvNormAct

        return DSConvNormAct(
            in_dim=8, out_dim=16, kernel_size=k, stride=stride, impl=impl
        )

    @pytest.mark.parametrize("stride", [1, 2])
    def test_param_tree_and_values_identical(self, stride):
        x = jnp.zeros((2, 64, 3))
        key = jax.random.PRNGKey(0)
        vp = self._make("paths", stride).init(key, x, True)
        vc = self._make("composed", stride).init(key, x, True)
        fp = jax.tree_util.tree_flatten_with_path(vp)[0]
        fc = jax.tree_util.tree_flatten_with_path(vc)[0]
        assert [p for p, _ in fp] == [p for p, _ in fc]
        for (p, a), (_, b) in zip(fp, fc):
            np.testing.assert_array_equal(a, b, err_msg=str(p))

    @pytest.mark.parametrize("stride", [1, 2])
    @pytest.mark.parametrize("train", [False, True])
    def test_outputs_and_stats_match(self, stride, train):
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 63, 3))
        variables = self._make("paths", stride).init(
            jax.random.PRNGKey(0), x, True
        )
        outs = {}
        stats = {}
        for impl in ("paths", "composed"):
            m = self._make(impl, stride)
            if train:
                y, mut = m.apply(variables, x, True, mutable=["batch_stats"])
                stats[impl] = mut["batch_stats"]
            else:
                y = m.apply(variables, x, False)
            outs[impl] = y
        np.testing.assert_allclose(
            outs["paths"], outs["composed"], rtol=2e-5, atol=2e-5
        )
        if train:
            fa = jax.tree_util.tree_flatten_with_path(stats["paths"])[0]
            fb = jax.tree_util.tree_flatten_with_path(stats["composed"])[0]
            assert [p for p, _ in fa] == [p for p, _ in fb]
            for (p, a), (_, b) in zip(fa, fb):
                np.testing.assert_allclose(
                    a, b, rtol=2e-5, atol=2e-5, err_msg=str(p)
                )

    @pytest.mark.parametrize("stride", [1, 2])
    def test_gradients_match(self, stride):
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 48, 3))
        variables = self._make("paths", stride, k=7).init(
            jax.random.PRNGKey(0), x, True
        )

        def loss(impl, params):
            m = self._make(impl, stride, k=7)
            y, _ = m.apply(
                {**variables, "params": params}, x, True,
                mutable=["batch_stats"],
            )
            return jnp.sum(y * jnp.cos(y))

        gp = jax.grad(lambda p: loss("paths", p))(variables["params"])
        gc = jax.grad(lambda p: loss("composed", p))(variables["params"])
        fa = jax.tree_util.tree_flatten_with_path(gp)[0]
        fb = jax.tree_util.tree_flatten_with_path(gc)[0]
        assert [p for p, _ in fa] == [p for p, _ in fb]
        for (p, a), (_, b) in zip(fa, fb):
            np.testing.assert_allclose(
                a, b, rtol=5e-4, atol=5e-5, err_msg=str(p)
            )

    def test_full_model_forward_matches(self):
        import os

        from seist_tpu.models import api

        x = jax.random.normal(jax.random.PRNGKey(2), (1, 512, 3))
        model = api.create_model("seist_s_dpk", in_samples=512)
        variables = model.init(jax.random.PRNGKey(0), x, False)
        prev = os.environ.get("SEIST_DSCONV_IMPL")
        try:
            os.environ["SEIST_DSCONV_IMPL"] = "paths"
            y_paths = model.apply(variables, x, False)
            os.environ["SEIST_DSCONV_IMPL"] = "composed"
            y_comp = model.apply(variables, x, False)
        finally:
            if prev is None:
                os.environ.pop("SEIST_DSCONV_IMPL", None)
            else:
                os.environ["SEIST_DSCONV_IMPL"] = prev
        np.testing.assert_allclose(y_paths, y_comp, rtol=1e-5, atol=1e-5)


class TestMergedStem:
    """StemBlock's merged lowering must be checkpoint-identical and
    numerically equivalent to the literal 3-path architecture
    (seist_tpu/models/seist.py StemBlock docstring)."""

    def _make(self, impl, stride):
        from seist_tpu.models.seist import StemBlock

        return StemBlock(
            in_dim=16, out_dim=16, kernel_size=11, stride=stride, impl=impl
        )

    @pytest.mark.parametrize("other", ["merged", "fused"])
    @pytest.mark.parametrize("stride", [1, 2])
    def test_param_tree_and_values_identical(self, stride, other):
        x = jnp.zeros((2, 64, 3))
        key = jax.random.PRNGKey(0)
        vp = self._make("paths", stride).init(key, x, True)
        vm = self._make(other, stride).init(key, x, True)
        fp = jax.tree_util.tree_flatten_with_path(vp)[0]
        fm = jax.tree_util.tree_flatten_with_path(vm)[0]
        assert [p for p, _ in fp] == [p for p, _ in fm]
        for (p, a), (_, b) in zip(fp, fm):
            np.testing.assert_array_equal(a, b, err_msg=str(p))

    @pytest.mark.parametrize("stride", [1, 2])
    @pytest.mark.parametrize("train", [False, True])
    def test_outputs_and_stats_match(self, stride, train):
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 63, 3))
        variables = self._make("paths", stride).init(jax.random.PRNGKey(0), x, True)
        outs = {}
        stats = {}
        for impl in ("paths", "merged", "fused"):
            m = self._make(impl, stride)
            if train:
                y, mut = m.apply(variables, x, True, mutable=["batch_stats"])
                stats[impl] = mut["batch_stats"]
            else:
                y = m.apply(variables, x, False)
            outs[impl] = y
        for other in ("merged", "fused"):
            np.testing.assert_allclose(
                outs["paths"], outs[other], rtol=2e-5, atol=2e-5,
                err_msg=other,
            )
            if train:
                fa = jax.tree_util.tree_flatten_with_path(stats["paths"])[0]
                fb = jax.tree_util.tree_flatten_with_path(stats[other])[0]
                assert [p for p, _ in fa] == [p for p, _ in fb]
                for (p, a), (_, b) in zip(fa, fb):
                    np.testing.assert_allclose(
                        a, b, rtol=2e-5, atol=2e-5, err_msg=f"{other}:{p}"
                    )

    def test_full_model_forward_matches(self):
        import os

        from seist_tpu.models import api

        x = jax.random.normal(jax.random.PRNGKey(2), (1, 512, 3))
        model = api.create_model("seist_s_dpk", in_samples=512)
        variables = model.init(jax.random.PRNGKey(0), x, False)
        prev = os.environ.get("SEIST_STEM_IMPL")
        try:
            os.environ["SEIST_STEM_IMPL"] = "paths"
            y_paths = model.apply(variables, x, False)
            os.environ["SEIST_STEM_IMPL"] = "merged"
            y_merged = model.apply(variables, x, False)
        finally:
            if prev is None:
                os.environ.pop("SEIST_STEM_IMPL", None)
            else:
                os.environ["SEIST_STEM_IMPL"] = prev
        np.testing.assert_allclose(y_paths, y_merged, rtol=1e-5, atol=1e-5)


class TestChannelPad:
    """SEIST_CHANNEL_PAD (off by default) pads composed/fused dense-conv
    out-channels to a lane multiple and slices the zeros away — values,
    grads, and the checkpoint tree must be IDENTICAL to the unpadded
    lowering (models/common.py pad_kernel_out_channels)."""

    @pytest.mark.parametrize("mult", ["8", "128"])
    def test_full_model_forward_identical(self, mult, monkeypatch):
        from seist_tpu.models import api

        x = jax.random.normal(jax.random.PRNGKey(2), (1, 512, 3))
        model = api.create_model("seist_s_dpk", in_samples=512)
        variables = model.init(jax.random.PRNGKey(0), x, False)
        monkeypatch.setenv("SEIST_DSCONV_IMPL", "composed")
        monkeypatch.setenv("SEIST_STEM_IMPL", "fused")
        monkeypatch.delenv("SEIST_CHANNEL_PAD", raising=False)
        y_base = model.apply(variables, x, False)
        monkeypatch.setenv("SEIST_CHANNEL_PAD", mult)
        y_pad = model.apply(variables, x, False)
        # The padded columns are zeros, but a different backend tiling
        # may reorder the real columns' accumulations — tight allclose,
        # not bitwise (the whole point of the flag is to change tiling).
        np.testing.assert_allclose(
            np.asarray(y_base), np.asarray(y_pad), rtol=1e-6, atol=1e-7
        )

    def test_train_step_gradients_identical(self, monkeypatch):
        from seist_tpu.models.seist import DSConvNormAct

        x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 3))
        m = DSConvNormAct(16, 24, 7, 2, impl="composed")
        variables = m.init(jax.random.PRNGKey(0), x, True)

        def loss(params):
            y, _ = m.apply(
                {**variables, "params": params}, x, True,
                mutable=["batch_stats"],
            )
            return jnp.sum(y * jnp.cos(y))

        monkeypatch.delenv("SEIST_CHANNEL_PAD", raising=False)
        g_base = jax.grad(loss)(variables["params"])
        monkeypatch.setenv("SEIST_CHANNEL_PAD", "128")
        g_pad = jax.grad(loss)(variables["params"])
        fa = jax.tree_util.tree_flatten_with_path(g_base)[0]
        fb = jax.tree_util.tree_flatten_with_path(g_pad)[0]
        assert [p for p, _ in fa] == [p for p, _ in fb]
        for (p, a), (_, b) in zip(fa, fb):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7, err_msg=str(p))
