"""Real-format reader tests against tiny on-disk fixtures.

Each dataset's production read path (``_load_meta_data`` +
``_load_event_data``: pandas dtype maps, h5py layouts, key quirks) is
exercised end to end — fixture files on disk -> reader -> preprocessor ->
Loader batch -> one jitted train step — so a malformed dtype/column
assumption dies here, not at step 0 of a real run (VERDICT r1 missing #2).

Formats reproduced (ref anchors):
* DiTing: 28 CSV (+HDF5) parts, ``earthquake/<key>`` datasets of shape
  (L, 3), zero-padded keys, string-numeric columns with stray spaces,
  ms/mb->ml magnitude conversion (ref datasets/diting.py:52-214).
* DiTing_light: single numeric CSV (ref diting.py:217-311).
* PNW: ComCat CSV + bucketed HDF5 ``data/bucket$n`` refs, '|'-separated
  snr triple, polarity word map (ref datasets/pnw.py:102-150).
* PNW_light: same with the light metadata filename (ref pnw.py:153-188).
* SOS: pre-split train/val/test dirs of per-trace npz (data stored (L, 1);
  the reader emits (1, L)) + ``_all_label.csv`` (ref datasets/sos.py:53-86).
"""

import os

import h5py
import numpy as np
import pandas as pd
import pytest

import seist_tpu
from seist_tpu import taskspec
from seist_tpu.data import pipeline
from seist_tpu.data.diting import normalize_key

seist_tpu.load_all()

L_TRACE = 1024  # raw trace samples in fixtures
L_IN = 512  # training window
N_PARTS = 28


def _wave(rng, n_ch=3, length=L_TRACE):
    w = rng.standard_normal((length, n_ch)).astype(np.float32)
    w[300:420] *= 6.0  # an "event"
    return w


# ------------------------------------------------------------------- fixtures
def _diting_row(i, part):
    key = f"{100 + i}.{part}"  # short on purpose: exercises zero-padding
    row = {
        "key": key,
        "part": part,
        "ev_id": 1000 + i,
        "mag_type": "ms" if i % 2 else "ml",
        "p_pick": 300,
        "p_clarity": "i" if i % 2 else "e",
        "p_motion": "u" if i % 2 else "d",
        "s_pick": 420,
        "net": "XX",
        "sta_id": i,
        "dis": 12.5,
        # Full-release quirk: numeric values arrive as strings with spaces
        # (ref diting.py:62-72,95-97).
        "evmag": " 2.3",
        "st_mag": " 2.1",
        "baz": " 405.0",  # exercises %= 360
        "P_residual": " 0.1",
        "S_residual": " 0.2",
    }
    for c in "ZNE":
        for ph in "PS":
            for kind in ("amplitude", "power"):
                row[f"{c}_{ph}_{kind}_snr"] = 10.0 + i
    return row


@pytest.fixture(scope="module")
def diting_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("diting")
    rng = np.random.default_rng(0)
    for part in range(N_PARTS):
        rows = [_diting_row(2 * part + j, part) for j in range(2)]
        pd.DataFrame(rows).to_csv(root / f"DiTing330km_part_{part}.csv")
        with h5py.File(root / f"DiTing330km_part_{part}.hdf5", "w") as f:
            for r in rows:
                # HDF5 layout: (L, 3), read with .T (ref diting.py:139-142).
                f.create_dataset(
                    "earthquake/" + normalize_key(r["key"]), data=_wave(rng)
                )
    return str(root)


@pytest.fixture(scope="module")
def diting_light_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("diting_light")
    rng = np.random.default_rng(1)
    rows = []
    for i in range(12):
        r = _diting_row(i, part=i % 3)
        # Light release: numeric columns are numeric (ref diting.py:217-311).
        for col in ("evmag", "st_mag", "baz", "P_residual", "S_residual"):
            r[col] = float(r[col])
        rows.append(r)
    pd.DataFrame(rows).to_csv(root / "DiTing330km_light.csv")
    for part in sorted({r["part"] for r in rows}):
        with h5py.File(root / f"DiTing330km_part_{part}.hdf5", "w") as f:
            for r in rows:
                if r["part"] == part:
                    f.create_dataset(
                        "earthquake/" + normalize_key(r["key"]),
                        data=_wave(rng),
                    )
    return str(root)


def _pnw_fixture(root, meta_filename):
    rng = np.random.default_rng(2)
    n = 12
    buckets = {"bucket0": [], "bucket1": []}
    rows = []
    for i in range(n):
        bucket = f"bucket{i % 2}"
        bi = len(buckets[bucket])
        trace = _wave(rng).T  # (3, L) rows per bucket entry (ref pnw.py:107-110)
        if i == 0:
            trace[0, :5] = np.nan  # reader must nan_to_num (ref pnw.py:110)
        buckets[bucket].append(trace)
        rows.append(
            {
                "trace_name": f"{bucket}${bi},:3,:{L_TRACE}",
                "trace_P_polarity": ["positive", "negative", "undecidable", ""][i % 4],
                "preferred_source_magnitude_type": "ml",
                "preferred_source_magnitude": 2.0 + 0.1 * i,
                "trace_snr_db": "10.0|nan|12.5",
                "trace_P_arrival_sample": 300,
                "trace_S_arrival_sample": 420,
                "station_network_code": "UW",
            }
        )
    pd.DataFrame(rows).to_csv(root / meta_filename, index=False)
    with h5py.File(root / "comcat_waveforms.hdf5", "w") as f:
        for name, traces in buckets.items():
            f.create_dataset(f"data/{name}", data=np.stack(traces))
    return str(root)


@pytest.fixture(scope="module")
def pnw_dir(tmp_path_factory):
    return _pnw_fixture(tmp_path_factory.mktemp("pnw"), "comcat_metadata.csv")


@pytest.fixture(scope="module")
def pnw_light_dir(tmp_path_factory):
    return _pnw_fixture(
        tmp_path_factory.mktemp("pnw_light"), "comcat_metadata_light.csv"
    )


@pytest.fixture(scope="module")
def sos_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("sos")
    rng = np.random.default_rng(3)
    for mode in ("train", "val", "test"):
        d = root / mode
        d.mkdir()
        rows = []
        for i in range(8 if mode == "train" else 3):
            fname = f"trace_{mode}_{i}.npz"
            # On-disk layout: (L, 1); reader emits (1, L) via np.stack
            # (ref sos.py:74-77).
            np.savez(
                d / fname,
                data=_wave(rng, n_ch=1).reshape(L_TRACE, 1),
            )
            rows.append({"fname": fname, "itp": 300, "its": 420})
        pd.DataFrame(rows).to_csv(d / "_all_label.csv", index=False)
    return str(root)


# --------------------------------------------------------------------- helpers
def _one_train_step(loader, in_channels):
    import jax

    from seist_tpu.models import api
    from seist_tpu.train import (
        build_optimizer,
        create_train_state,
        jit_step,
        make_train_step,
    )

    model = api.create_model(
        "phasenet", in_channels=in_channels, in_samples=L_IN
    )
    variables = api.init_variables(
        model, in_samples=L_IN, in_channels=in_channels, batch_size=4
    )
    state = create_train_state(model, variables, build_optimizer("adam", 1e-3))
    spec = taskspec.get_task_spec("phasenet")
    loss_fn = taskspec.make_loss("phasenet")
    step = jit_step(make_train_step(spec, loss_fn), donate_state=False)
    batch = next(iter(loader))
    state, loss, out = step(
        state, batch.inputs, batch.loss_targets, jax.random.PRNGKey(0)
    )
    assert np.isfinite(float(loss))
    assert out.shape[0] == 4 and out.shape[1] == L_IN
    return batch


def _loader(dataset_name, data_dir, mode="train", **kw):
    spec = taskspec.get_task_spec("phasenet")
    sds = pipeline.from_task_spec(
        spec,
        dataset_name,
        mode,
        seed=11,
        data_dir=data_dir,
        in_samples=L_IN,
        augmentation=(mode == "train"),
        **kw,
    )
    return pipeline.Loader(sds, 4, shuffle=True, drop_last=True, num_workers=2)


# ----------------------------------------------------------------------- tests
class TestDiTing:
    def test_reader_and_train_step(self, diting_dir):
        loader = _loader("diting", diting_dir)
        batch = _one_train_step(loader, in_channels=3)
        assert batch.inputs.shape == (4, L_IN, 3)
        assert batch.inputs.dtype == np.float32

    def test_event_semantics(self, diting_dir):
        from seist_tpu.registry import DATASETS

        ds = DATASETS.create(
            "diting", seed=11, mode="train", data_dir=diting_dir
        )
        ev, meta = ds[0]
        assert ev["data"].shape == (3, L_TRACE)
        assert ev["ppks"] == [300] and ev["spks"] == [420]
        assert ev["baz"] and 0 <= ev["baz"][0] < 360  # 405 -> 45
        assert ev["pmp"][0] in (0, 1)
        assert ev["clr"][0] in (0, 1)
        assert 0 <= float(ev["emg"][0]) <= 8  # string "2.3" parsed + ml-converted
        assert len(ev["snr"]) == 3


class TestDiTingLight:
    def test_reader_roundtrip(self, diting_light_dir):
        loader = _loader("diting_light", diting_light_dir)
        batch = next(iter(loader))
        assert batch.inputs.shape == (4, L_IN, 3)
        assert np.isfinite(batch.inputs).all()


class TestPNW:
    def test_reader_and_train_step(self, pnw_dir):
        loader = _loader("pnw", pnw_dir)
        batch = _one_train_step(loader, in_channels=3)
        assert np.isfinite(batch.inputs).all()  # nan row was zeroed

    def test_event_semantics(self, pnw_dir):
        from seist_tpu.registry import DATASETS

        ds = DATASETS.create("pnw", seed=11, mode="train", data_dir=pnw_dir)
        ev, meta = ds[0]
        assert ev["data"].shape == (3, L_TRACE)
        assert ev["pmp"][0] in (0, 1, 2, 3)
        assert len(ev["snr"]) == 3 and ev["snr"][1] == 0.0  # 'nan' -> 0
        assert np.isfinite(ev["data"]).all()

    def test_mostly_nan_trace_is_corrupt_not_zeroed(self, tmp_path):
        """Sparse NaNs are zeroed (reference parity, ref pnw.py:110 —
        covered above); a trace that is MOSTLY non-finite is rotted and
        must classify as permanent corruption (data/io_guard.py) instead
        of silently becoming a near-all-zeros sample."""
        import shutil

        import h5py

        from seist_tpu.data.io_guard import CorruptSampleError
        from seist_tpu.registry import DATASETS

        src = tmp_path / "pnw_src"
        src.mkdir()
        root = tmp_path / "pnw_rot"
        shutil.copytree(_pnw_fixture(src, "comcat_metadata.csv"), root)
        with h5py.File(root / "comcat_waveforms.hdf5", "r+") as f:
            arr = f["data/bucket0"][...]
            arr[0] = np.nan  # whole first trace rotted
            del f["data/bucket0"]
            f.create_dataset("data/bucket0", data=arr)
        ds = DATASETS.create(
            "pnw", seed=11, mode="train", data_dir=str(root),
            data_split=False, shuffle=False,
        )
        rotted = next(
            i for i in range(len(ds))
            if ds._row_dict(i)["trace_name"].startswith("bucket0$0,")
        )
        with pytest.raises(CorruptSampleError, match="non-finite"):
            ds[rotted]


class TestPNWLight:
    def test_reader_roundtrip(self, pnw_light_dir):
        loader = _loader("pnw_light", pnw_light_dir)
        batch = next(iter(loader))
        assert batch.inputs.shape == (4, L_IN, 3)


class TestSOS:
    def test_reader_and_train_step(self, sos_dir):
        # SOS is single-channel: bypass the 3-channel model spec and wire
        # the pipeline explicitly (ref uses SOS for picking only).
        sds = pipeline.SeismicDataset(
            "sos",
            "train",
            seed=11,
            data_dir=sos_dir,
            input_names=[["z"]],
            label_names=[["non", "ppk", "spk"]],
            task_names=["ppk", "spk"],
            in_samples=L_IN,
            augmentation=True,
            data_split=False,
        )
        loader = pipeline.Loader(
            sds, 4, shuffle=True, drop_last=True, num_workers=2
        )
        batch = _one_train_step(loader, in_channels=1)
        assert batch.inputs.shape == (4, L_IN, 1)

    def test_presplit_modes(self, sos_dir):
        from seist_tpu.registry import DATASETS

        for mode, n in (("train", 8), ("val", 3), ("test", 3)):
            ds = DATASETS.create(
                "sos", seed=11, mode=mode, data_dir=sos_dir, data_split=False
            )
            assert len(ds) == n
            ev, meta = ds[0]
            assert ev["data"].shape == (1, L_TRACE)
            assert ev["ppks"] == [300]
