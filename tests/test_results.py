"""Tests for seist_tpu.ops.results.ResultSaver (ref postprocess.py:253-338)."""

import numpy as np
import pandas as pd
import pytest

from seist_tpu.ops.results import ResultSaver


def test_csv_roundtrip(tmp_path):
    saver = ResultSaver(item_names=["ppk", "spk"])
    meta = {"idx": [0, 1], "mag": [3.5, 4.2]}
    targets = {
        "ppk": np.array([[100, -(10**7)], [200, 300]]),
        "spk": np.array([[150, -(10**7)], [250, 400]]),
    }
    results = {
        "ppk": np.array([[102, -(10**7)], [205, 298]]),
        "spk": np.array([[149, -(10**7)], [260, 390]]),
    }
    saver.append(meta, targets, results)
    path = str(tmp_path / "out" / "results.csv")
    saver.save_as_csv(path)
    df = pd.read_csv(path)
    assert list(df["idx"]) == [0, 1]
    # padding stripped; multi values joined with commas
    assert str(df["pred_ppk"][0]) == "102"
    assert df["tgt_ppk"][1] == "200,300"


def test_onehot_argmax():
    saver = ResultSaver(item_names=["pmp"])
    meta = {"idx": [0]}
    targets = {"pmp": np.array([[0.0, 1.0]])}
    results = {"pmp": np.array([[0.7, 0.3]])}
    saver.append(meta, targets, results)
    assert saver._results_dict["pred_pmp"] == [0]
    assert saver._results_dict["tgt_pmp"] == [1]


def test_missing_item_raises():
    saver = ResultSaver(item_names=["ppk", "det"])
    with pytest.raises(AttributeError):
        saver.append({"idx": [0]}, {"ppk": np.array([[1]])}, {"ppk": np.array([[1]])})
