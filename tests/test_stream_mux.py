"""StationMux + Associator (seist_tpu/stream/mux.py, assoc.py): dedup,
backpressure accounting, association geometry, and the thousand-station
zero-compile pin — sessions are host state; the device sees only the
same warm bucketed forward regardless of how many stations stream.
"""

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from seist_tpu.serve.batcher import BatcherConfig, MicroBatcher
from seist_tpu.serve.protocol import QueueFull
from seist_tpu.stream.assoc import AssocConfig, Associator, StationPick
from seist_tpu.stream.mux import MuxConfig, StationLimit, StationMux
from seist_tpu.stream.session import SessionConfig

W = 32  # tiny window keeps these tests fast
SESS = SessionConfig(window=W, stride=16, channel0="non",
                     sampling_rate=50, min_peak_dist=0.1)


def _direct_submit(x):
    """Synchronous fake forward: P prob = normalized |ch0| envelope."""
    a = np.abs(x[:, 0])
    p = (a / (a.max() + 1e-9)).astype(np.float32)
    out = np.stack([1.0 - p, p, np.zeros_like(p)], axis=-1)
    return out


def _spiky(n=W, at=None):
    rec = np.random.default_rng(0).standard_normal((n, 3)).astype(np.float32) * 0.01
    if at is not None:
        rec[at : at + 3, 0] += 50.0
    return rec


class TestMux:
    def test_feed_runs_windows_and_picks(self):
        mux = StationMux(_direct_submit, MuxConfig(session=SESS))
        out = mux.feed({"id": "ST01"}, _spiky(64, at=10))
        assert out["windows"] == 3  # offsets 0, 16, 32
        assert out["picks"]["ppk"], "interior spike must surface mid-stream"
        assert mux.stats()["sessions"] == 1.0

    def test_duplicate_and_gap_accounting(self):
        mux = StationMux(_direct_submit, MuxConfig(session=SESS))
        st = {"id": "ST01"}
        mux.feed(st, _spiky(16), seq=1)
        dup = mux.feed(st, _spiky(16), seq=1)  # replayed packet
        assert dup["duplicate"] is True
        assert dup["windows"] == 0
        mux.feed(st, _spiky(16), seq=5)  # jumped 2..4
        s = mux.stats()
        assert s["duplicates"] == 1.0 and s["gaps"] == 1.0

    def test_end_closes_session(self):
        mux = StationMux(_direct_submit, MuxConfig(session=SESS))
        out = mux.feed({"id": "ST01"}, _spiky(40, at=5), end=True)
        assert out["closed"] is True
        assert mux.n_sessions == 0
        # tail window (offset 8) ran: 40 samples -> regular 0 + tail
        assert out["windows"] == 2

    def test_station_capacity(self):
        mux = StationMux(_direct_submit, MuxConfig(session=SESS, max_stations=2))
        mux.feed({"id": "A"}, _spiky(8))
        mux.feed({"id": "B"}, _spiky(8))
        with pytest.raises(StationLimit):
            mux.feed({"id": "C"}, _spiky(8))

    def test_backpressure_marks_degraded(self):
        calls = {"n": 0}

        def flaky(x):
            calls["n"] += 1
            if calls["n"] == 2:
                raise QueueFull("stream window", "queue full")
            return _direct_submit(x)

        mux = StationMux(flaky, MuxConfig(session=SESS))
        st = {"id": "ST01"}
        mux.feed(st, _spiky(32))
        with pytest.raises(QueueFull):
            mux.feed(st, _spiky(32))  # second window refused
        s = mux.stats()
        # The refused window AND the rest of that feed's due batch are
        # abandoned (zero-fill): a due window that never runs must not
        # wedge the finality frontier, and a retried packet is a
        # duplicate seq so those windows would never re-run.
        assert s["windows_dropped"] == 2.0
        assert s["degraded_sessions"] == 1.0
        # The stream survives: later packets keep working on the holey curve.
        out = mux.feed(st, _spiky(32))
        assert out["degraded"] is True and out["windows"] >= 1

    def test_reap_idle(self):
        t = [0.0]
        mux = StationMux(
            _direct_submit,
            MuxConfig(session=SESS, idle_timeout_s=10.0),
            clock=lambda: t[0],
        )
        mux.feed({"id": "A"}, _spiky(8))
        t[0] = 11.0
        assert mux.reap_idle() == 1
        assert mux.n_sessions == 0


class TestAssociator:
    GEOM = [("S1", 35.0, -117.0), ("S2", 35.2, -117.1), ("S3", 35.1, -116.8),
            ("S4", 34.9, -117.2), ("N1", 36.5, -118.5)]

    def _pick(self, sid, lat, lon, t, stamps=None):
        return StationPick(station_id=sid, network="CI", lat=lat, lon=lon,
                           t_s=t, stamps=stamps or {})

    def test_coherent_picks_alert_once(self):
        cfg = AssocConfig(min_stations=4, window_s=30.0, tolerance_s=2.0)
        a = Associator(cfg, clock=lambda: 123.0)
        # Event at (35.05, -117.05), t0=100: arrivals = t0 + dist/v.
        from seist_tpu.stream.assoc import _dist_km

        alerts = []
        for sid, lat, lon in self.GEOM[:4]:
            t = 100.0 + _dist_km(35.05, -117.05, lat, lon) / cfg.velocity_kms
            got = a.add(self._pick(sid, lat, lon, t))
            if got:
                alerts.append(got)
        assert len(alerts) == 1
        al = alerts[0]
        assert al.n_stations == 4
        assert abs(al.origin_t_s - 100.0) < 2.0
        assert abs(al.origin_lat - 35.05) < 0.5
        # Contributing picks consumed: the same event doesn't re-alert.
        assert a.stats()["pending_picks"] == 0.0

    def test_incoherent_noise_never_alerts(self):
        cfg = AssocConfig(min_stations=4, window_s=30.0, tolerance_s=1.0)
        a = Associator(cfg)
        # Same 4 stations but wildly incompatible arrival times.
        for i, (sid, lat, lon) in enumerate(self.GEOM[:4]):
            assert a.add(self._pick(sid, lat, lon, 100.0 + i * 20.0)) is None

    def test_distant_noise_station_excluded(self):
        cfg = AssocConfig(min_stations=4, window_s=30.0, tolerance_s=2.0)
        a = Associator(cfg, clock=lambda: 0.0)
        from seist_tpu.stream.assoc import _dist_km

        a.add(self._pick("N1", 36.5, -118.5, 101.0))  # incompatible outlier
        got = None
        for sid, lat, lon in self.GEOM[:4]:
            t = 100.0 + _dist_km(35.05, -117.05, lat, lon) / cfg.velocity_kms
            got = a.add(self._pick(sid, lat, lon, t)) or got
        assert got is not None
        assert all(p.station_id != "N1" for p in got.picks)

    def test_latency_stamps_flow_to_alert(self):
        cfg = AssocConfig(min_stations=2, window_s=30.0, tolerance_s=2.0)
        a = Associator(cfg, clock=lambda: 10.0)
        stamps = {"arrival": 1.0, "due": 1.1, "submitted": 1.2,
                  "returned": 1.5, "picked": 1.6}
        a.add(self._pick("S1", 35.0, -117.0, 100.0, stamps=stamps))
        al = a.add(self._pick("S2", 35.1, -117.1, 100.5, stamps=stamps))
        assert al is not None
        lm = al.latency_ms
        assert lm["sample_to_alert"] == pytest.approx((10.0 - 1.0) * 1000.0)
        assert lm["queue_device"] == pytest.approx(300.0)
        assert "association" in lm


class TestMuxAssociation:
    def test_network_codetection_alerts_through_mux(self):
        cfg = MuxConfig(session=SESS)
        assoc = Associator(AssocConfig(min_stations=3, window_s=60.0,
                                       tolerance_s=3.0))
        mux = StationMux(_direct_submit, cfg, assoc=assoc)
        stations = [
            {"id": "S1", "network": "CI", "lat": 35.0, "lon": -117.0},
            {"id": "S2", "network": "CI", "lat": 35.1, "lon": -117.1},
            {"id": "S3", "network": "CI", "lat": 35.05, "lon": -116.9},
        ]
        alerts = []
        for st in stations:
            out = mux.feed(st, _spiky(64, at=20))  # same spike position
            alerts.extend(out["alerts"])
        assert len(alerts) == 1
        assert alerts[0]["n_stations"] == 3
        assert mux.stats()["alerts"] == 1.0


@pytest.mark.slow  # ~1000 sessions x several packets through a real batcher
def test_thousand_station_mux_zero_post_warmup_compiles():
    """The acceptance pin: >= 1000 concurrent sessions multiplex through
    ONE jitted bucketed forward with ZERO XLA compiles after warmup —
    sessions are host-side state, invisible to the device."""
    import jax
    import jax.numpy as jnp

    from tools.jaxlint.runtime import CompileBudget

    buckets = (1, 2, 4, 8)

    @jax.jit
    def fwd(x):
        a = jnp.abs(x[..., 0])
        p = a / (a.max(axis=1, keepdims=True) + 1e-9)
        return jnp.stack([1.0 - p, p, jnp.zeros_like(p)], axis=-1)

    def forward(batch):
        return np.asarray(fwd(jnp.asarray(batch)))

    batcher = MicroBatcher(
        forward,
        BatcherConfig(max_batch=8, max_delay_ms=2.0, buckets=buckets,
                      max_queue=4096),
        name="stream-test",
    )

    def submit(x):
        return batcher.submit(x, timeout_ms=30_000.0)[0]

    n_stations = 1000
    mux = StationMux(submit, MuxConfig(session=SESS, max_stations=2048))
    rng = np.random.default_rng(0)
    packets = {
        f"T{i:04d}": rng.standard_normal((3, W + 8, 3)).astype(np.float32)
        for i in range(n_stations)
    }

    # Warmup: every bucket shape compiles once outside the budget.
    for b in buckets:
        forward(np.zeros((b, W, 3), np.float32))

    with CompileBudget() as budget:
        with ThreadPoolExecutor(16) as ex:
            for round_i in range(3):
                list(ex.map(
                    lambda kv: mux.feed({"id": kv[0]}, kv[1][round_i]),
                    packets.items(),
                ))
    assert mux.n_sessions == n_stations
    assert mux.stats()["windows"] >= n_stations  # windows actually flowed
    assert budget.total() == 0, (
        f"post-warmup compiles: {budget.signatures()}"
    )
    batcher.shutdown()


class TestMuxDurability:
    """Journal plane: periodic snapshots, failover restore, the
    close_all vs in-flight feed() contract (MuxClosed, never a freed
    session), and journal hygiene on clean close."""

    @staticmethod
    def _mux(tmp_path, clock=None, journal_every_s=0.0):
        from seist_tpu.stream.journal import StationJournal

        journal = StationJournal(str(tmp_path), model="m")
        kw = {"clock": clock} if clock is not None else {}
        mux = StationMux(
            _direct_submit,
            MuxConfig(session=SESS, journal_every_s=journal_every_s,
                      model="m"),
            journal=journal,
            **kw,
        )
        return mux, journal

    def test_journal_written_and_restored(self, tmp_path):
        mux, journal = self._mux(tmp_path)
        st = {"id": "ST01", "lat": 35.0, "lon": -117.0}
        mux.feed(st, _spiky(64, at=40), seq=1)
        assert journal.load("ST01") is not None
        assert mux.stats()["journal_writes"] >= 1.0

        # "Replica death": a brand-new mux over the same journal dir.
        mux2, _ = self._mux(tmp_path)
        out = mux2.feed(st, _spiky(32), seq=2)
        assert mux2.stats()["restores"] == 1.0
        # Sample count continues from the journal watermark, not zero.
        assert out["n_samples"] == 96
        assert out["duplicate"] is False

    def test_restore_parity_with_uninterrupted(self, tmp_path):
        """Picks from journal-restored continuation == picks from one
        uninterrupted session over the same packets."""
        rec = _spiky(192, at=150)
        pk = [rec[0:64], rec[64:128], rec[128:192]]
        st = {"id": "ST01"}

        ref = StationMux(_direct_submit, MuxConfig(session=SESS))
        ref_picks = []
        for i, data in enumerate(pk):
            r = ref.feed(st, data, seq=i + 1, end=(i == 2))
            ref_picks.append(r["picks"])

        mux, _ = self._mux(tmp_path)
        got_picks = [mux.feed(st, pk[0], seq=1)["picks"]]
        mux2, _ = self._mux(tmp_path)  # crash + failover after packet 1
        got_picks.append(mux2.feed(st, pk[1], seq=2)["picks"])
        got_picks.append(mux2.feed(st, pk[2], seq=3, end=True)["picks"])
        assert got_picks == ref_picks

    def test_corrupt_journal_falls_back_to_fresh(self, tmp_path):
        mux, journal = self._mux(tmp_path)
        st = {"id": "ST01"}
        mux.feed(st, _spiky(64), seq=1)
        path = journal._path("ST01")
        with open(path, "r+b") as f:
            f.truncate(16)  # torn write
        mux2, journal2 = self._mux(tmp_path)
        out = mux2.feed(st, _spiky(32), seq=2)
        # A torn file reads as "no journal" (corrupt_reads counter), not
        # a restore failure — restores_failed is for version/config skew.
        assert mux2.stats()["restores"] == 0.0
        assert journal2.corrupt_reads == 1
        assert out["n_samples"] == 32  # fresh session, gap-stitch re-warm

    def test_config_skew_falls_back_to_fresh(self, tmp_path):
        mux, _ = self._mux(tmp_path)
        mux.feed({"id": "ST01"}, _spiky(64), seq=1)
        from seist_tpu.stream.journal import StationJournal

        other = SessionConfig(window=W, stride=8, channel0="non",
                              sampling_rate=50, min_peak_dist=0.1)
        mux2 = StationMux(
            _direct_submit, MuxConfig(session=other, model="m"),
            journal=StationJournal(str(tmp_path), model="m"),
        )
        mux2.feed({"id": "ST01"}, _spiky(32), seq=2)
        assert mux2.stats()["restores_failed"] == 1.0

    def test_close_all_rejects_inflight_feed(self, tmp_path):
        from seist_tpu.stream.mux import MuxClosed

        mux, journal = self._mux(tmp_path)
        st = {"id": "ST01"}
        mux.feed(st, _spiky(64), seq=1)
        mux.close_all()
        with pytest.raises(MuxClosed):
            mux.feed(st, _spiky(32), seq=2)
        with pytest.raises(MuxClosed):
            mux.feed({"id": "NEW"}, _spiky(32), seq=1)
        # close_all journaled the final state for failover handoff.
        assert journal.load("ST01") is not None

    def test_close_all_concurrent_with_feeds(self, tmp_path):
        """Hammer feed() from threads while close_all() latches: every
        feed either completes normally or raises MuxClosed — never a
        session error, never an integrate into freed state."""
        from seist_tpu.stream.mux import MuxClosed

        mux, _ = self._mux(tmp_path)
        sids = [f"ST{i:02d}" for i in range(8)]
        for sid in sids:
            mux.feed({"id": sid}, _spiky(32), seq=1)
        errs = []
        done = threading.Event()

        def feeder(sid):
            seq = 2
            while not done.is_set():
                try:
                    mux.feed({"id": sid}, _spiky(16), seq=seq)
                except MuxClosed:
                    return
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)
                    return
                seq += 1

        threads = [threading.Thread(target=feeder, args=(sid,))
                   for sid in sids]
        for t in threads:
            t.start()
        mux.close_all()
        done.set()
        for t in threads:
            t.join(timeout=10)
        assert not errs
        assert mux.n_sessions == 0

    def test_clean_close_removes_journal(self, tmp_path):
        mux, journal = self._mux(tmp_path)
        st = {"id": "ST01"}
        mux.feed(st, _spiky(64), seq=1)
        assert journal.load("ST01") is not None
        mux.feed(st, _spiky(32), seq=2, end=True)
        # A cleanly finished stream needs no failover handoff.
        assert journal.load("ST01") is None
