"""Loss parity tests.

Each loss is checked against an independent torch-CPU computation of the
reference formulas (models/loss.py:8-210). Our arrays are channels-last
(N, L, C); the reference is channels-first (N, C, L) — the reductions are
equivalent, which these tests prove numerically.
"""

import numpy as np
import pytest
import torch

from seist_tpu.models import losses as L

N, C, SEQ = 4, 3, 64
EPS = 1e-6


@pytest.fixture
def dense_pair(rng):
    preds = rng.uniform(0.01, 0.99, size=(N, SEQ, C)).astype(np.float32)
    targets = rng.uniform(0, 1, size=(N, SEQ, C)).astype(np.float32)
    return preds, targets


def _t(x_channel_last):
    """channels-last numpy -> channels-first torch."""
    return torch.from_numpy(np.moveaxis(x_channel_last, -1, 1).copy())


def test_ce_loss_matches_reference_formula(dense_pair):
    preds, targets = dense_pair
    w = [0.5, 1.0, 2.0]
    ours = float(L.CELoss(weight=w)(preds, targets))
    tw = torch.tensor([[0.5], [1.0], [2.0]])
    ref = (-_t(targets) * torch.log(_t(preds) + EPS) * tw).sum(1).mean()
    assert ours == pytest.approx(float(ref), rel=1e-4)


def test_ce_loss_classes_shape(rng):
    preds = rng.uniform(0.01, 0.99, size=(N, 2)).astype(np.float32)
    targets = np.eye(2, dtype=np.float32)[rng.integers(0, 2, N)]
    ours = float(L.CELoss(weight=[1.0, 1.0])(preds, targets))
    ref = (
        (-torch.from_numpy(targets) * torch.log(torch.from_numpy(preds) + EPS))
        .sum(1)
        .mean()
    )
    assert ours == pytest.approx(float(ref), rel=1e-4)


def test_bce_loss_matches_reference_formula(dense_pair):
    preds, targets = dense_pair
    w = [0.5, 1.0, 1.0]
    ours = float(L.BCELoss(weight=w)(preds, targets))
    tp, tt = _t(preds), _t(targets)
    tw = torch.tensor([[0.5], [1.0], [1.0]])
    ref = (
        -(tt * torch.log(tp + EPS) + (1 - tt) * torch.log(1 - tp + EPS)) * tw
    ).mean()
    assert ours == pytest.approx(float(ref), rel=1e-4)


def test_focal_loss_matches_reference_formula(rng):
    logits = rng.normal(size=(N, 2)).astype(np.float32)
    targets = np.eye(2, dtype=np.float32)[rng.integers(0, 2, N)]
    ours = float(L.FocalLoss(gamma=2)(logits, targets))
    tp = torch.softmax(torch.from_numpy(logits), dim=1)
    tt = torch.from_numpy(targets)
    ref = (-tt * torch.log(tp + EPS) * (1 - tp) ** 2).sum(1).mean()
    assert ours == pytest.approx(float(ref), rel=1e-4)


def test_binary_focal_loss(dense_pair):
    preds, targets = dense_pair
    ours = float(L.BinaryFocalLoss(gamma=2, alpha=1)(preds, targets))
    tp, tt = _t(preds), _t(targets)
    ref = (-(1 * (1 - tp) ** 2 * tt * torch.log(tp + EPS))).mean()
    assert ours == pytest.approx(float(ref), rel=1e-4)


def test_mse_loss(dense_pair):
    preds, targets = dense_pair
    ours = float(L.MSELoss()(preds, targets))
    assert ours == pytest.approx(float(((preds - targets) ** 2).mean()), rel=1e-4)


def test_huber_loss_matches_torch(rng):
    preds = rng.normal(size=(N, 1)).astype(np.float32) * 3
    targets = rng.normal(size=(N, 1)).astype(np.float32) * 3
    ours = float(L.HuberLoss()(preds, targets))
    ref = torch.nn.HuberLoss()(torch.from_numpy(preds), torch.from_numpy(targets))
    assert ours == pytest.approx(float(ref), rel=1e-4)


def test_mousavi_loss(rng):
    preds = rng.normal(size=(N, 2)).astype(np.float32)
    targets = rng.normal(size=(N, 1)).astype(np.float32)
    ours = float(L.MousaviLoss()(preds, targets))
    tp, tt = torch.from_numpy(preds), torch.from_numpy(targets)
    y_hat, s = tp[:, 0].reshape(-1, 1), tp[:, 1].reshape(-1, 1)
    ref = torch.sum(0.5 * torch.exp(-s) * torch.square(torch.abs(tt - y_hat)) + 0.5 * s)
    assert ours == pytest.approx(float(ref), rel=1e-4)


def test_combination_loss(rng):
    p0 = rng.uniform(0.01, 0.99, size=(N, 2)).astype(np.float32)
    p1 = rng.uniform(0.01, 0.99, size=(N, 2)).astype(np.float32)
    t0 = np.eye(2, dtype=np.float32)[rng.integers(0, 2, N)]
    t1 = np.eye(2, dtype=np.float32)[rng.integers(0, 2, N)]
    comb = L.CombinationLoss(losses=[L.MSELoss, L.MSELoss], losses_weights=[0.3, 0.7])
    ours = float(comb((p0, p1), (t0, t1)))
    expected = 0.3 * ((p0 - t0) ** 2).mean() + 0.7 * ((p1 - t1) ** 2).mean()
    assert ours == pytest.approx(float(expected), rel=1e-4)


def test_combination_loss_rejects_single():
    with pytest.raises(ValueError):
        L.CombinationLoss(losses=[L.MSELoss])


def test_losses_are_jittable(dense_pair):
    import jax

    preds, targets = dense_pair
    loss = L.BCELoss(weight=[0.5, 1.0, 1.0])
    jitted = jax.jit(lambda p, t: loss(p, t))
    assert float(jitted(preds, targets)) == pytest.approx(
        float(loss(preds, targets)), rel=1e-6
    )


def test_losses_are_differentiable(dense_pair):
    import jax
    import jax.numpy as jnp

    preds, targets = dense_pair
    loss = L.CELoss(weight=[1.0, 1.0, 1.0])
    g = jax.grad(lambda p: loss(p, jnp.asarray(targets)))(jnp.asarray(preds))
    assert np.isfinite(np.asarray(g)).all()
