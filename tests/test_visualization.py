"""Smoke tests for the matplotlib figures (ref utils/visualization.py:18-186
— the reference exposes two plot entry points; these pin our signatures and
that real PNG files land on disk)."""

import numpy as np
import pytest

matplotlib = pytest.importorskip("matplotlib")
matplotlib.use("Agg", force=True)

from seist_tpu.utils.visualization import (  # noqa: E402
    vis_phase_picking,
    vis_waves_preds_targets,
)


def test_vis_phase_picking_writes_png(rng, tmp_path, monkeypatch):
    import matplotlib.pyplot as plt

    L = 256
    waves = rng.standard_normal((3, L)).astype(np.float32)
    preds = np.clip(
        rng.standard_normal((3, L)).astype(np.float32) * 0.1 + 0.2, 0, 1
    )
    # Capture the figure (instead of letting the function close it) so the
    # pick markers can be inspected; really closed at the end of the test.
    captured = []
    monkeypatch.setattr(plt, "close", lambda fig=None, *a, **k: captured.append(fig))
    paths = vis_phase_picking(
        waveforms=waves,
        waveforms_labels=["Z", "N", "E"],
        preds=preds,
        true_phase_idxs=[64, 128],
        true_phase_labels=["P", "S"],
        pred_phase_labels=["Detection", "P-phase", "S-phase"],
        sampling_rate=50,
        save_name="_test",
        save_dir=str(tmp_path),
    )
    assert paths
    for p in paths:
        assert p.endswith(".png")
        assert (tmp_path / p.split("/")[-1]).stat().st_size > 0
    # Units: pick indices are samples, the x axis is seconds — the vlines
    # must land at idx / fs, inside the waveform's 5.12 s extent.
    assert len(captured) == 1
    fig = captured[0]
    vline_xs = sorted(
        seg[0][0]
        for coll in fig.axes[0].collections
        for seg in coll.get_segments()
    )
    np.testing.assert_allclose(vline_xs, [64 / 50, 128 / 50])
    monkeypatch.undo()
    plt.close(fig)


def test_vis_waves_preds_targets_writes_png(rng, tmp_path):
    L = 256
    waves = rng.standard_normal((3, L)).astype(np.float32)
    preds = np.clip(rng.standard_normal((3, L)) * 0.1 + 0.3, 0, 1).astype(
        np.float32
    )
    targets = np.zeros((3, L), np.float32)
    targets[0, :] = 1.0
    targets[1, 64] = 1.0
    targets[2, 128] = 1.0
    path = vis_waves_preds_targets(
        waveforms=waves,
        preds=preds,
        targets=targets,
        sampling_rate=50,
        save_dir=str(tmp_path),
    )
    assert path.endswith(".png")
    assert (tmp_path / path.split("/")[-1]).stat().st_size > 0
