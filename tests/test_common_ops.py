"""Geometry-parity helpers in models/common.py: the gather-free integer
upsampling must match both the generic gather path and torch
F.interpolate exactly (the dpk head's pick alignment depends on it —
SURVEY.md hard-part #3)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seist_tpu.models import common
from seist_tpu.models.common import (
    _interpolate_linear_intscale,
    interpolate_linear,
)


def _gather_reference(x, out_size):
    """The generic (gather) formula, inlined so the fast path can't shadow it."""
    L_in = x.shape[-2]
    scale = L_in / out_size
    dst = np.arange(out_size, dtype=np.float32)
    src = np.clip((dst + 0.5) * scale - 0.5, 0.0, L_in - 1)
    lo = np.floor(src).astype(np.int32)
    hi = np.minimum(lo + 1, L_in - 1)
    w = (src - lo)[None, :, None].astype(np.float32)
    return x[:, lo, :] * (1.0 - w) + x[:, hi, :] * w


@pytest.mark.parametrize("r", [2, 4, 8, 64])
def test_intscale_matches_gather_dyadic_exact(rng, r):
    # Power-of-two factors (the only ones the dpk ladder uses): the static
    # phase weights are exact binary fractions -> bit-identical results.
    x = rng.standard_normal((2, 16, 3)).astype(np.float32)
    want = _gather_reference(x, 16 * r)
    got = np.asarray(_interpolate_linear_intscale(jnp.asarray(x), r))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("r", [3, 5, 6])
def test_intscale_matches_gather_odd(rng, r):
    # Non-dyadic factors: the gather path rounds its weights through
    # fp32 `(d+0.5)*scale`, ours are exact doubles -> ~1e-6 fp noise.
    x = rng.standard_normal((2, 16, 3)).astype(np.float32)
    want = _gather_reference(x, 16 * r)
    got = np.asarray(_interpolate_linear_intscale(jnp.asarray(x), r))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=5e-6)


@pytest.mark.parametrize("out", [24, 40, 100])
def test_non_integer_ratio_uses_gather(rng, out):
    x = rng.standard_normal((2, 16, 3)).astype(np.float32)
    want = _gather_reference(x, out)
    got = np.asarray(interpolate_linear(jnp.asarray(x), out))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("out", [32, 48, 100, 1024])
def test_matches_torch_interpolate(rng, out):
    torch = pytest.importorskip("torch")
    x = rng.standard_normal((2, 16, 3)).astype(np.float32)
    want = (
        torch.nn.functional.interpolate(
            torch.from_numpy(x.transpose(0, 2, 1)),
            size=out,
            mode="linear",
            align_corners=False,
        )
        .numpy()
        .transpose(0, 2, 1)
    )
    got = np.asarray(interpolate_linear(jnp.asarray(x), out))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=5e-6)


def test_identity_when_same_size(rng):
    x = jnp.asarray(rng.standard_normal((1, 8, 2)).astype(np.float32))
    assert interpolate_linear(x, 8) is x


class TestNoScatterBackward:
    """HLO regression locks for the round-2 lowering work: the backward
    passes of the conv lowerings must not contain scatter ops (XLA lowers
    the transpose of a strided slice to scatter-adds — the pathology the
    phase-split and composed lowerings exist to remove; BASELINE.md)."""

    def _grad_hlo(self, fn, *args):
        g = jax.jit(jax.grad(fn))
        return g.lower(*args).compile().as_text()

    @pytest.mark.parametrize("s", [2, 4])
    def test_depthwise_shift_stride_backward(self, rng, s):
        x = jnp.asarray(rng.standard_normal((2, 64, 8)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((11, 8)), jnp.float32)

        def loss(x):
            return jnp.sum(common.depthwise_shift_fma(x, w, s) ** 2)

        assert " scatter(" not in self._grad_hlo(loss, x)

    def test_dsconv_backward(self, rng):
        # impl='composed' only: the 'paths' impl lowers to grouped conv on
        # the CPU CI backend, so a scatter lock there would be vacuous.
        from seist_tpu.models.seist import DSConvNormAct

        m = DSConvNormAct(
            in_dim=8, out_dim=16, kernel_size=11, stride=2, impl="composed"
        )
        x = jnp.asarray(rng.standard_normal((2, 64, 3)), jnp.float32)
        v = m.init(jax.random.PRNGKey(0), x, True)

        def loss(x):
            y, _ = m.apply(v, x, True, mutable=["batch_stats"])
            return jnp.sum(y**2)

        assert " scatter(" not in self._grad_hlo(loss, x)

    def test_fused_stem_backward(self, rng):
        from seist_tpu.models.seist import StemBlock

        m = StemBlock(
            in_dim=8, out_dim=16, kernel_size=11, stride=2, impl="fused"
        )
        x = jnp.asarray(rng.standard_normal((2, 64, 3)), jnp.float32)
        v = m.init(jax.random.PRNGKey(0), x, True)

        def loss(x):
            y, _ = m.apply(v, x, True, mutable=["batch_stats"])
            return jnp.sum(y**2)

        assert " scatter(" not in self._grad_hlo(loss, x)


def test_lstm_unroll_is_pure_scheduling(rng, monkeypatch):
    """SEIST_LSTM_UNROLL must not change LSTM math (fwd or grad) — it only
    unrolls the scan body so XLA can pipeline the tiny per-step matmuls
    (common._lstm_unroll). Odd L exercises the remainder handling."""
    x = jnp.asarray(rng.standard_normal((2, 37, 5)), jnp.float32)
    m = common.BiLSTM(hidden=7)
    v = m.init(jax.random.PRNGKey(0), x)

    def fwd_and_grad(unroll):
        monkeypatch.setenv("SEIST_LSTM_UNROLL", unroll)
        o, h = m.apply(v, x)

        def loss(v):
            o, h = m.apply(v, x)
            return (o**2).sum() + (h**2).sum()

        return o, h, jax.grad(loss)(v)

    o1, h1, g1 = fwd_and_grad("1")
    o8, h8, g8 = fwd_and_grad("8")
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o8), atol=1e-6)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h8), atol=1e-6)
    fa = jax.tree_util.tree_flatten_with_path(g1)[0]
    fb = jax.tree_util.tree_flatten_with_path(g8)[0]
    for (p, a), (_, b) in zip(fa, fb):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, err_msg=str(p)
        )


@pytest.mark.parametrize("out", [16, 32, 48, 100, 37])
def test_nearest_matches_torch_interpolate(rng, out):
    """Both the integer-factor repeat path and the gather path must match
    torch F.interpolate(mode='nearest') (ditingmotion's upsampler)."""
    torch = pytest.importorskip("torch")
    x = rng.standard_normal((2, 16, 3)).astype(np.float32)
    want = (
        torch.nn.functional.interpolate(
            torch.from_numpy(x.transpose(0, 2, 1)), size=out, mode="nearest"
        )
        .numpy()
        .transpose(0, 2, 1)
    )
    got = np.asarray(common.interpolate_nearest(jnp.asarray(x), out))
    np.testing.assert_allclose(got, want, rtol=0, atol=0)


class TestConvLowerings:
    """DepthwiseConv1D / GroupedConv1D: every lowering must match the
    nn.Conv(feature_group_count=...) it replaces, on the same param tree
    (checkpoint compatibility is the contract — models/common.py)."""

    @pytest.mark.parametrize(
        "k,s,C,L",
        [
            (11, 2, 16, 64),
            (5, 1, 8, 33),
            # phase-split stride path (common.depthwise_shift_fma s>1):
            # odd L, k<s taps empty phases, k%s==0, stride>2
            (10, 2, 3, 57),
            (3, 2, 5, 33),
            (4, 4, 8, 41),
            (7, 3, 4, 50),
        ],
    )
    @pytest.mark.parametrize("impl", ["shift", "grouped"])
    def test_depthwise_matches_nn_conv(self, rng, k, s, C, L, impl):
        from flax import linen as nn

        x = jnp.asarray(rng.standard_normal((2, L, C)), jnp.float32)
        ref = nn.Conv(
            C, (k,), strides=(s,), padding="VALID",
            feature_group_count=C, use_bias=False,
        )
        v = ref.init(jax.random.PRNGKey(0), x)
        want = ref.apply(v, x)
        got = common.DepthwiseConv1D(C, k, stride=s, impl=impl).apply(
            {"params": {"kernel": v["params"]["kernel"]}}, x
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-6
        )

    @pytest.mark.parametrize("k,s,C,L", [(10, 2, 3, 57), (7, 3, 4, 50)])
    def test_depthwise_shift_gradients_match_grouped(self, rng, k, s, C, L):
        """The phase-split stride path must be gradient-exact vs the
        lax grouped-conv lowering (both d/dx and d/dw) — the backward is
        exactly what the phase-split reshape exists to reroute."""
        x = jnp.asarray(rng.standard_normal((2, L, C)), jnp.float32)
        kern = jnp.asarray(rng.standard_normal((k, 1, C)), jnp.float32)

        def loss(impl, x, kern):
            y = common.DepthwiseConv1D(C, k, stride=s, impl=impl).apply(
                {"params": {"kernel": kern}}, x
            )
            return jnp.sum(jnp.sin(y) * y)

        gx_s, gw_s = jax.grad(lambda x, w: loss("shift", x, w), (0, 1))(x, kern)
        gx_g, gw_g = jax.grad(lambda x, w: loss("grouped", x, w), (0, 1))(x, kern)
        np.testing.assert_allclose(np.asarray(gx_s), np.asarray(gx_g), atol=2e-5)
        np.testing.assert_allclose(np.asarray(gw_s), np.asarray(gw_g), atol=2e-5)

    @pytest.mark.parametrize(
        "k,cin,cout,g", [(3, 24, 24, 3), (7, 96, 96, 12), (5, 32, 64, 4)]
    )
    @pytest.mark.parametrize("impl", ["grouped", "einsum", "dense"])
    def test_grouped_matches_nn_conv(self, rng, k, cin, cout, g, impl):
        from flax import linen as nn

        x = jnp.asarray(rng.standard_normal((2, 40, cin)), jnp.float32)
        ref = nn.Conv(
            cout, (k,), padding="VALID",
            feature_group_count=g, use_bias=False,
        )
        v = ref.init(jax.random.PRNGKey(0), x)
        want = ref.apply(v, x)
        got = common.GroupedConv1D(cout, g, k, impl=impl).apply(
            {"params": {"kernel": v["params"]["kernel"]}}, x
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-6
        )

    def test_dense_grouped_no_cross_group_leak(self, rng):
        """The dense lowering's block-diagonal expansion must keep groups
        independent: output features of group 0 cannot depend on input
        channels of group 1 (falsifiable via input-gradient support)."""
        x = jnp.asarray(rng.standard_normal((1, 16, 8)), jnp.float32)
        m = common.GroupedConv1D(8, 2, 3, impl="dense")
        v = m.init(jax.random.PRNGKey(0), x)

        def group0_sum(xin):
            return m.apply(v, xin)[..., :4].sum()

        gx = np.asarray(jax.grad(group0_sum)(x))
        assert np.abs(gx[..., :4]).max() > 0  # own group: real dependence
        np.testing.assert_array_equal(gx[..., 4:], 0.0)  # other group: none
