"""Geometry-parity helpers in models/common.py: the gather-free integer
upsampling must match both the generic gather path and torch
F.interpolate exactly (the dpk head's pick alignment depends on it —
SURVEY.md hard-part #3)."""

import numpy as np
import pytest

import jax.numpy as jnp

from seist_tpu.models.common import (
    _interpolate_linear_intscale,
    interpolate_linear,
)


def _gather_reference(x, out_size):
    """The generic (gather) formula, inlined so the fast path can't shadow it."""
    L_in = x.shape[-2]
    scale = L_in / out_size
    dst = np.arange(out_size, dtype=np.float32)
    src = np.clip((dst + 0.5) * scale - 0.5, 0.0, L_in - 1)
    lo = np.floor(src).astype(np.int32)
    hi = np.minimum(lo + 1, L_in - 1)
    w = (src - lo)[None, :, None].astype(np.float32)
    return x[:, lo, :] * (1.0 - w) + x[:, hi, :] * w


@pytest.mark.parametrize("r", [2, 4, 8, 64])
def test_intscale_matches_gather_dyadic_exact(rng, r):
    # Power-of-two factors (the only ones the dpk ladder uses): the static
    # phase weights are exact binary fractions -> bit-identical results.
    x = rng.standard_normal((2, 16, 3)).astype(np.float32)
    want = _gather_reference(x, 16 * r)
    got = np.asarray(_interpolate_linear_intscale(jnp.asarray(x), r))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("r", [3, 5, 6])
def test_intscale_matches_gather_odd(rng, r):
    # Non-dyadic factors: the gather path rounds its weights through
    # fp32 `(d+0.5)*scale`, ours are exact doubles -> ~1e-6 fp noise.
    x = rng.standard_normal((2, 16, 3)).astype(np.float32)
    want = _gather_reference(x, 16 * r)
    got = np.asarray(_interpolate_linear_intscale(jnp.asarray(x), r))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=5e-6)


@pytest.mark.parametrize("out", [24, 40, 100])
def test_non_integer_ratio_uses_gather(rng, out):
    x = rng.standard_normal((2, 16, 3)).astype(np.float32)
    want = _gather_reference(x, out)
    got = np.asarray(interpolate_linear(jnp.asarray(x), out))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("out", [32, 48, 100, 1024])
def test_matches_torch_interpolate(rng, out):
    torch = pytest.importorskip("torch")
    x = rng.standard_normal((2, 16, 3)).astype(np.float32)
    want = (
        torch.nn.functional.interpolate(
            torch.from_numpy(x.transpose(0, 2, 1)),
            size=out,
            mode="linear",
            align_corners=False,
        )
        .numpy()
        .transpose(0, 2, 1)
    )
    got = np.asarray(interpolate_linear(jnp.asarray(x), out))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=5e-6)


def test_identity_when_same_size(rng):
    x = jnp.asarray(rng.standard_normal((1, 8, 2)).astype(np.float32))
    assert interpolate_linear(x, 8) is x
