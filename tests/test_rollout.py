"""Live-model flywheel units + in-process e2e: versioned pool, hot reload
failure ladder, canary auto-rollback, shadow decision diffs, and the
fleet rollout state machine (docs/SERVING.md "Live rollout").

The jax-free classes (canary/shadow/diff/rollout-cmd/validation) are
smoke-marked; the real-model reload ladder runs a phasenet pool and
stays tier-1-only. The subprocess fleet e2e lives in
tests/test_serve_fleet.py (fake replicas) and tests/test_serve_chaos.py
(real replicas under load).
"""

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, os.path.join(REPO, "tools"))


# ----------------------------------------------- checkpoint compatibility
@pytest.mark.smoke
class TestCheckpointValidation:
    def _expected(self):
        return {
            "params": {
                "conv": {"kernel": np.zeros((3, 3, 8), np.float32),
                         "bias": np.zeros((8,), np.float32)},
            },
        }

    def _restored(self):
        return json.loads(json.dumps(None)) or {  # deep copy via literals
            "params": {
                "conv": {"kernel": np.zeros((3, 3, 8), np.float32),
                         "bias": np.zeros((8,), np.float32)},
            },
        }

    def _check(self, restored):
        from seist_tpu.serve.pool import validate_checkpoint_tree

        validate_checkpoint_tree(
            self._expected(), restored, model_name="m", checkpoint="ck"
        )

    def test_matching_tree_passes(self):
        self._check(self._restored())

    def test_missing_key_named(self):
        from seist_tpu.serve.protocol import IncompatibleCheckpoint

        bad = self._restored()
        del bad["params"]["conv"]["bias"]
        with pytest.raises(IncompatibleCheckpoint) as ei:
            self._check(bad)
        assert "missing key" in str(ei.value)
        assert "params/conv/bias" in str(ei.value)

    def test_unexpected_key_named(self):
        from seist_tpu.serve.protocol import IncompatibleCheckpoint

        bad = self._restored()
        bad["params"]["extra_head"] = {"w": np.zeros((2,), np.float32)}
        with pytest.raises(IncompatibleCheckpoint) as ei:
            self._check(bad)
        assert "unexpected key" in str(ei.value)
        assert "params/extra_head" in str(ei.value)

    def test_shape_mismatch_named(self):
        from seist_tpu.serve.protocol import IncompatibleCheckpoint

        bad = self._restored()
        bad["params"]["conv"]["kernel"] = np.zeros((3, 3, 16), np.float32)
        with pytest.raises(IncompatibleCheckpoint) as ei:
            self._check(bad)
        msg = str(ei.value)
        assert "shape mismatch" in msg and "params/conv/kernel" in msg
        assert "(3, 3, 8)" in msg and "(3, 3, 16)" in msg

    def test_dtype_mismatch_named(self):
        from seist_tpu.serve.protocol import IncompatibleCheckpoint

        bad = self._restored()
        bad["params"]["conv"]["bias"] = np.zeros((8,), np.float64)
        with pytest.raises(IncompatibleCheckpoint) as ei:
            self._check(bad)
        assert "dtype mismatch" in str(ei.value)

    def test_leaf_vs_subtree_named(self):
        from seist_tpu.serve.protocol import IncompatibleCheckpoint

        bad = self._restored()
        bad["params"]["conv"] = np.zeros((4,), np.float32)
        with pytest.raises(IncompatibleCheckpoint) as ei:
            self._check(bad)
        assert "subtree/leaf mismatch" in str(ei.value)

    def test_missing_collection(self):
        from seist_tpu.serve.protocol import IncompatibleCheckpoint

        with pytest.raises(IncompatibleCheckpoint) as ei:
            self._check({})
        assert "missing collection" in str(ei.value)

    def test_empty_expected_collection_is_optional(self):
        from seist_tpu.serve.pool import validate_checkpoint_tree

        expected = dict(self._expected(), batch_stats={})
        validate_checkpoint_tree(
            expected, self._restored(), model_name="m", checkpoint="ck"
        )

    def test_error_is_a_400_serve_error(self):
        from seist_tpu.serve.protocol import IncompatibleCheckpoint

        e = IncompatibleCheckpoint("x")
        assert e.status == 400 and e.code == "incompatible_checkpoint"


# ------------------------------------------------------------ canary units
@pytest.mark.smoke
class TestCanaryController:
    def _canary(self, percent=20.0, **budget):
        from seist_tpu.serve.canary import CanaryBudget, CanaryController

        c = CanaryController()
        c.start(2, percent, CanaryBudget(**budget))
        return c

    def test_weighted_share_is_exact(self):
        c = self._canary(percent=20.0)
        picks = [c.routing_cohort(True) for _ in range(200)]
        assert picks.count("candidate") == 40  # deterministic counter

    def test_retries_never_route_candidate(self):
        c = self._canary(percent=100.0)
        assert c.routing_cohort(True) == "candidate"
        assert all(
            c.routing_cohort(False) == "incumbent" for _ in range(20)
        )

    def test_inactive_means_version_blind(self):
        from seist_tpu.serve.canary import CanaryController

        c = CanaryController()
        assert c.routing_cohort(True) is None
        assert c.observe("candidate", True, None) is None

    def test_error_delta_trips_rollback_once(self):
        c = self._canary(percent=50.0, max_error_delta=0.2, min_requests=5)
        for _ in range(20):
            c.observe("incumbent", False, 10.0)
        reasons = [c.observe("candidate", True, None) for _ in range(5)]
        fired = [r for r in reasons if r]
        assert len(fired) == 1 and "error-rate delta" in fired[0]
        assert c.state == "rolled_back" and c.percent == 0.0
        # Drained: the candidate cohort gets exactly 0% from now on.
        assert all(
            c.routing_cohort(True) == "incumbent" for _ in range(20)
        )
        # Post-rollback observations are inert (no double rollback).
        assert c.observe("candidate", True, None) is None

    def test_min_requests_guards_small_samples(self):
        c = self._canary(percent=50.0, max_error_delta=0.1, min_requests=10)
        for _ in range(9):
            assert c.observe("candidate", True, None) is None
        assert c.state == "active"

    def test_latency_delta_trips(self):
        c = self._canary(
            percent=50.0, max_error_delta=1.1,  # error path disabled
            max_latency_delta_ms=50.0, min_requests=5,
        )
        for _ in range(10):
            c.observe("incumbent", False, 10.0)
        reason = None
        for _ in range(10):
            reason = reason or c.observe("candidate", False, 200.0)
        assert reason and "latency delta" in reason
        assert c.state == "rolled_back"

    def test_healthy_canary_never_rolls_back(self):
        c = self._canary(percent=50.0, max_error_delta=0.1, min_requests=5)
        for _ in range(50):
            assert c.observe("candidate", False, 12.0) is None
            assert c.observe("incumbent", False, 10.0) is None
        assert c.state == "active"

    def test_cohort_of_uses_versions(self):
        c = self._canary()
        assert c.cohort_of({"m": 2}) == "candidate"
        assert c.cohort_of({"m": 1}) == "incumbent"
        assert c.cohort_of({}) == "incumbent"

    def test_model_scoped_cohort_ignores_other_models(self):
        """Multi-model pools: model A already AT version 2 fleet-wide
        must not make every replica 'candidate' when model B's version 2
        is the canary."""
        from seist_tpu.serve.canary import CanaryController

        c = CanaryController()
        c.start(2, 50.0, model="b")
        # Serves a@2 but b@1: NOT the candidate.
        assert c.cohort_of({"a": 2, "b": 1}) == "incumbent"
        assert c.cohort_of({"a": 2, "b": 2}) == "candidate"
        assert c.cohort_of({"a": 2}) == "incumbent"  # no b at all
        assert c.status()["model"] == "b"

    def test_serves_version_helper(self):
        from seist_tpu.serve.canary import serves_version

        assert serves_version({"m": 2}, 2)
        assert not serves_version({"m": 1}, 2)
        assert not serves_version({}, 2)
        assert not serves_version(None, 2)
        assert serves_version({"a": 2, "b": 1}, 2, model="a")
        assert not serves_version({"a": 2, "b": 1}, 2, model="b")
        assert not serves_version({"a": "junk"}, 2)

    def test_stop_clears(self):
        c = self._canary()
        c.stop()
        assert c.state == "inactive" and c.routing_cohort(True) is None

    def test_bad_percent_rejected(self):
        from seist_tpu.serve.canary import CanaryController

        c = CanaryController()
        with pytest.raises(ValueError):
            c.start(2, 0.0)
        with pytest.raises(ValueError):
            c.start(2, 101.0)

    def test_status_shape(self):
        c = self._canary(percent=25.0)
        s = c.status()
        assert s["state"] == "active" and s["version"] == 2
        assert s["percent"] == 25.0
        assert set(s["cohorts"]) == {"candidate", "incumbent"}


# ------------------------------------------------------------ shadow units
@pytest.mark.smoke
class TestShadowMirror:
    def test_sample_one_mirrors_everything(self):
        from seist_tpu.serve.canary import ShadowMirror

        s = ShadowMirror()
        s.start(2, 1.0)
        assert s.should_mirror("deadbeef" * 4)
        s.stop()
        assert not s.should_mirror("deadbeef" * 4)

    def test_sampling_is_deterministic(self):
        import hashlib

        from seist_tpu.serve.canary import ShadowMirror

        s = ShadowMirror()
        s.start(2, 0.5)
        ids = [
            hashlib.md5(str(i).encode()).hexdigest() for i in range(200)
        ]
        first = [s.should_mirror(t) for t in ids]
        assert first == [s.should_mirror(t) for t in ids]
        assert 0 < sum(first) < 200

    def test_record_counts_and_jsonl_report(self, tmp_path):
        from seist_tpu.serve.canary import ShadowMirror

        report = str(tmp_path / "shadow.jsonl")
        s = ShadowMirror()
        s.start(2, 1.0, report)
        s.record("t1", "match", {"diff": {"match": True}})
        s.record("t2", "mismatch", {"diff": {"match": False}})
        s.record("t3", "no_candidate", {"reason": "none"})
        counts = s.status()["counts"]
        assert counts["mirrored"] == 2 and counts["mismatch"] == 1
        assert counts["no_candidate"] == 1
        lines = [json.loads(x) for x in open(report)]
        assert [x["verdict"] for x in lines] == [
            "match", "mismatch", "no_candidate"
        ]
        assert lines[1]["trace_id"] == "t2"

    def test_bad_sample_rejected(self):
        from seist_tpu.serve.canary import ShadowMirror

        with pytest.raises(ValueError):
            ShadowMirror().start(2, 1.5)


# ---------------------------------------------------------- decision diffs
@pytest.mark.smoke
class TestDecisionDiff:
    def test_picks_within_tolerance_match(self):
        from seist_tpu.serve.canary import decision_diff

        a = {"task": "picking", "ppk": [{"sample": 100}], "spk": [],
             "det": [{"onset": 90, "offset": 300}]}
        b = {"task": "picking", "ppk": [{"sample": 105}], "spk": [],
             "det": [{"onset": 95, "offset": 305}]}
        assert decision_diff(a, b)["match"]

    def test_moved_pick_mismatches(self):
        from seist_tpu.serve.canary import decision_diff

        a = {"task": "picking", "ppk": [{"sample": 100}], "spk": []}
        b = {"task": "picking", "ppk": [{"sample": 200}], "spk": []}
        d = decision_diff(a, b)
        assert not d["match"] and not d["fields"]["ppk"]["match"]

    def test_pick_count_mismatch(self):
        from seist_tpu.serve.canary import decision_diff

        a = {"task": "picking", "ppk": [{"sample": 100}], "spk": []}
        b = {"task": "picking", "ppk": [], "spk": []}
        assert not decision_diff(a, b)["match"]

    def test_classifier_argmax(self):
        from seist_tpu.serve.canary import decision_diff

        a = {"task": "classification",
             "pmp": {"class": 1, "scores": [0.1, 0.9]}}
        same = {"task": "classification",
                "pmp": {"class": 1, "scores": [0.4, 0.6]}}
        flip = {"task": "classification",
                "pmp": {"class": 0, "scores": [0.6, 0.4]}}
        assert decision_diff(a, same)["match"]
        assert not decision_diff(a, flip)["match"]

    def test_regression_tolerance_scales(self):
        from seist_tpu.serve.canary import decision_diff

        a = {"task": "regression", "emg": 4.0}
        assert decision_diff(a, {"task": "regression", "emg": 4.1})["match"]
        assert not decision_diff(
            a, {"task": "regression", "emg": 5.0}
        )["match"]

    def test_version_fields_ignored(self):
        from seist_tpu.serve.canary import decision_diff

        a = {"task": "regression", "emg": 4.0, "model_version": 1}
        b = {"task": "regression", "emg": 4.0, "model_version": 2}
        assert decision_diff(a, b)["match"]

    def test_shape_divergence_is_a_mismatch_not_a_crash(self):
        """A head whose output SHAPE changed between versions (dict vs
        scalar, garbage pick lists) must report as a decision mismatch —
        not crash the mirror thread into 'mirror_errors'."""
        from seist_tpu.serve.canary import decision_diff

        a = {"task": "classification",
             "pmp": {"class": 1, "scores": [0.1, 0.9]}}
        b = {"task": "classification", "pmp": 0.9}
        d = decision_diff(a, b)
        assert not d["match"]
        assert "shape mismatch" in d["fields"]["pmp"]["detail"]
        # Unparseable pick lists likewise.
        d2 = decision_diff(
            {"task": "picking", "ppk": [{"sample": 3}], "spk": []},
            {"task": "picking", "ppk": [0.5], "spk": []},
        )
        assert not d2["match"]

    def test_multitask_recurses_and_missing_task_fails(self):
        from seist_tpu.serve.canary import decision_diff

        a = {"tasks": {"dpk": {"task": "picking", "ppk": [], "spk": []},
                       "emg": {"task": "regression", "emg": 4.0}}}
        b_ok = {"tasks": {"dpk": {"task": "picking", "ppk": [], "spk": []},
                          "emg": {"task": "regression", "emg": 4.02}}}
        b_missing = {"tasks": {"dpk": {"task": "picking", "ppk": [],
                                       "spk": []}}}
        assert decision_diff(a, b_ok)["match"]
        assert not decision_diff(a, b_missing)["match"]


# ----------------------------------------------------- rollout cmd rewrite
@pytest.mark.smoke
class TestRolloutCmd:
    def test_strips_and_appends_model_version(self):
        from supervise_fleet import rollout_cmd

        cmd = ["serve", "--model-version", "1", "--window", "256"]
        out = rollout_cmd(cmd, 2)
        assert out == ["serve", "--window", "256", "--model-version", "2"]

    def test_checkpoint_substitution_all_forms(self):
        from supervise_fleet import rollout_cmd

        cmd = ["serve", "--model", "phasenet=old.ck", "--checkpoint", "o2",
               "--model-group", "seist_s=dpk:a,emg:b"]
        out = rollout_cmd(cmd, 3, "new.ck")
        assert "--model" in out and "phasenet=new.ck" in out
        assert out[out.index("--checkpoint") + 1] == "new.ck"
        assert "seist_s=dpk:new.ck,emg:new.ck" in out
        assert out[-2:] == ["--model-version", "3"]

    def test_no_checkpoint_leaves_model_flags(self):
        from supervise_fleet import rollout_cmd

        cmd = ["serve", "--model", "phasenet=old.ck"]
        out = rollout_cmd(cmd, 4)
        assert "phasenet=old.ck" in out
        assert out[-2:] == ["--model-version", "4"]


# ------------------------------------------- fleet rollout state machine
class _FakeProc:
    _next_pid = [1000]

    def __init__(self):
        self.pid = self._next_pid[0]
        self._next_pid[0] += 1
        self.signals = []
        self._rc = None

    def poll(self):
        return self._rc

    def send_signal(self, sig):
        self.signals.append(sig)


class _FakeSlot:
    def __init__(self, index, port):
        self.index = index
        self.port = port
        self.url = f"127.0.0.1:{port}"
        self.cmd = ["serve", "--model", "phasenet=", "--host", "127.0.0.1",
                    "--port", str(port)]
        self.proc = _FakeProc()
        self.retired = False


class _FakeRegistry:
    def __init__(self, slots):
        self._slots = slots
        self.ready = {s.url: True for s in slots}

    def replicas(self):
        class R:
            def __init__(self, url, ready):
                self.url, self.probe_ready = url, ready

        return [R(u, r) for u, r in self.ready.items()]


@pytest.mark.smoke
class TestFleetRolloutStateMachine:
    def _roll(self, n=2, **kw):
        from supervise_fleet import FleetRollout

        slots = [_FakeSlot(i, 18100 + i) for i in range(n)]
        return slots, FleetRollout(slots, version=2, **kw), _FakeRegistry(
            slots
        )

    def test_one_replica_at_a_time_drain_relaunch_ready(self):
        import signal as _signal

        slots, roll, reg = self._roll(2, ready_timeout_s=30.0)
        state = {s.index: (False, {}) for s in slots}

        def probe(slot):
            return state[slot.index]

        # Tick 1: slot 0 drained (SIGTERM), slot 1 untouched.
        roll.advance(reg, probe)
        assert slots[0].proc.signals == [_signal.SIGTERM]
        assert slots[1].proc.signals == []
        assert slots[0].cmd[-2:] == ["--model-version", "2"]
        assert slots[1].cmd[-2:] != ["--model-version", "2"]
        # Simulate the monitor reaping 75 + respawning slot 0.
        slots[0].proc = _FakeProc()
        roll.advance(reg, probe)  # sees the new pid -> wait_ready
        roll.advance(reg, probe)  # not ready yet: stays on slot 0
        assert slots[1].proc.signals == []
        # Slot 0 converges; next tick must move on and drain slot 1.
        state[0] = (True, {"phasenet": 2})
        roll.advance(reg, probe)
        assert roll.rolled == [0]
        roll.advance(reg, probe)
        assert slots[1].proc.signals == [_signal.SIGTERM]
        slots[1].proc = _FakeProc()
        state[1] = (True, {"phasenet": 2})
        roll.advance(reg, probe)  # relaunch seen
        roll.advance(reg, probe)  # ready
        assert roll.done and not roll.aborted
        assert roll.rolled == [0, 1]

    def test_stale_version_does_not_count_as_ready(self):
        slots, roll, reg = self._roll(1, ready_timeout_s=30.0)
        roll.advance(reg, lambda s: (True, {"phasenet": 1}))
        slots[0].proc = _FakeProc()
        roll.advance(reg, lambda s: (True, {"phasenet": 1}))
        for _ in range(5):
            roll.advance(reg, lambda s: (True, {"phasenet": 1}))
        assert not roll.done  # still waiting: old version keeps serving

    def test_ready_timeout_aborts(self, monkeypatch):
        slots, roll, reg = self._roll(2, ready_timeout_s=0.05)
        roll.advance(reg, lambda s: (False, {}))
        slots[0].proc = _FakeProc()
        roll.advance(reg, lambda s: (False, {}))  # enters wait_ready
        time.sleep(0.06)
        roll.advance(reg, lambda s: (False, {}))
        assert roll.done and "not ready" in roll.aborted
        # The roll stopped BEFORE touching slot 1: capacity floor held.
        assert slots[1].proc.signals == []

    def test_wedged_drain_aborts_instead_of_hanging(self):
        """A replica that ignores SIGTERM (wedged flush thread): the
        SAME per-slot deadline covers the drain, so the roll aborts
        loudly instead of waiting on the old pid forever."""
        slots, roll, reg = self._roll(2, ready_timeout_s=0.05)
        roll.advance(reg, lambda s: (False, {}))  # SIGTERM sent
        time.sleep(0.06)
        # The old process never exited: same proc, same pid.
        roll.advance(reg, lambda s: (False, {}))
        assert roll.done and "never relaunched" in roll.aborted
        assert slots[1].proc.signals == []

    def test_retired_slot_mid_roll_aborts_and_skipped_upfront(self):
        # Retired while being waited on -> abort.
        slots, roll, reg = self._roll(2, ready_timeout_s=30.0)
        roll.advance(reg, lambda s: (False, {}))
        slots[0].retired = True
        roll.advance(reg, lambda s: (False, {}))
        assert roll.done and "retired mid-roll" in roll.aborted
        # Retired before its turn -> skipped, roll completes on the rest.
        slots2, roll2, reg2 = self._roll(2, ready_timeout_s=30.0)
        slots2[0].retired = True
        roll2.advance(reg2, lambda s: (True, {"phasenet": 2}))
        assert slots2[0].proc.signals == []  # corpse never drained
        slots2[1].proc = _FakeProc()
        roll2.advance(reg2, lambda s: (True, {"phasenet": 2}))
        roll2.advance(reg2, lambda s: (True, {"phasenet": 2}))
        assert roll2.done and roll2.rolled == [1] and not roll2.aborted

    def test_subset_rolls_only_named_replicas(self):
        slots, roll, reg = self._roll(3, subset=[1])
        assert [s.index for s in roll.queue] == [1]

    def test_not_in_rotation_blocks_completion(self):
        slots, roll, reg = self._roll(1, ready_timeout_s=30.0)
        roll.advance(reg, lambda s: (True, {"phasenet": 2}))
        slots[0].proc = _FakeProc()
        roll.advance(reg, lambda s: (True, {"phasenet": 2}))
        reg.ready[slots[0].url] = False  # router hasn't readmitted yet
        roll.advance(reg, lambda s: (True, {"phasenet": 2}))
        assert not roll.done
        reg.ready[slots[0].url] = True
        roll.advance(reg, lambda s: (True, {"phasenet": 2}))
        assert roll.done and roll.rolled == [0]


# --------------------------------------- router canary/shadow over sockets
class _CannedReplica:
    """Minimal scriptable replica: /healthz/ready with a version,
    /predict answering a canned (status, body)."""

    def __init__(self, version, status=200, body=None):
        self.version = version
        self.reply_status = status
        self.reply_body = body or {"task": "regression", "emg": 4.0,
                                   "model_version": version}
        self.predicts = 0
        outer = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, status, payload):
                raw = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def do_GET(self):
                self._send(200, {"status": "ok", "ready": True,
                                 "versions": {"m": outer.version}})

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                outer.predicts += 1
                self._send(outer.reply_status, dict(outer.reply_body))

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.server.daemon_threads = True
        threading.Thread(
            target=self.server.serve_forever, daemon=True
        ).start()
        self.url = "127.0.0.1:%d" % self.server.server_address[1]

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture
def two_cohorts():
    incumbent = _CannedReplica(1)
    candidate = _CannedReplica(2)
    yield incumbent, candidate
    incumbent.close()
    candidate.close()


def _router_for(*replicas, **config_kw):
    from seist_tpu.obs.bus import MetricsBus
    from seist_tpu.serve.router import Router, RouterConfig

    config = RouterConfig(
        retries=2, request_timeout_s=5.0,
        breaker_failures=100,  # the canary, not the breaker, must act
        **config_kw,
    )
    router = Router(config=config, bus=MetricsBus())
    for r in replicas:
        rep = router.registry.add(r.url)
        rep.versions = {"m": r.version}  # what the prober would learn
    return router

BODY = json.dumps({"data": [[0.0] * 3] * 8,
                   "options": {"timeout_ms": 5000.0}}).encode()


class TestRouterCanary:
    def test_canary_percent_routes_and_healthy_stays_active(
        self, two_cohorts
    ):
        from seist_tpu.serve.canary import CanaryBudget

        incumbent, candidate = two_cohorts
        router = _router_for(incumbent, candidate)
        try:
            router.canary.start(2, 50.0, CanaryBudget(min_requests=1000))
            for _ in range(20):
                status, _, _ = router.forward("/predict", BODY)
                assert status == 200
            assert candidate.predicts == 10  # exact weighted share
            assert incumbent.predicts == 10
            assert router.canary.state == "active"
        finally:
            router.stop()

    def test_bad_candidate_rolls_back_and_drains(self, two_cohorts):
        from seist_tpu.serve.canary import CanaryBudget

        incumbent, candidate = two_cohorts
        candidate.reply_status = 500
        candidate.reply_body = {"error": "bad_candidate"}
        router = _router_for(incumbent, candidate)
        try:
            router.canary.start(
                2, 50.0,
                CanaryBudget(max_error_delta=0.3, min_requests=4),
            )
            statuses = [
                router.forward("/predict", BODY)[0] for _ in range(30)
            ]
            # Clients never failed: candidate 500s were retried on the
            # incumbent within the request.
            assert statuses == [200] * 30
            assert router.canary.state == "rolled_back"
            assert router.canary.percent == 0.0
            n_at_rollback = candidate.predicts
            for _ in range(20):
                assert router.forward("/predict", BODY)[0] == 200
            # Drained to 0%: not one more request reached the candidate.
            assert candidate.predicts == n_at_rollback
            # The event is on the bus and on a trace flag.
            snap = router._bus.snapshot()
            rollbacks = [
                k for k in snap.get("counters", {})
                if k.startswith("router_canary_rollback")
            ]
            assert rollbacks, snap.get("counters")
            from seist_tpu.obs import trace as obs_trace

            flagged = [
                t for t in obs_trace.index_payload()["traces"]
                if "canary_rollback" in t["flags"]
            ]
            assert flagged
        finally:
            router.stop()

    def test_rollback_reason_in_status(self, two_cohorts):
        from seist_tpu.serve.canary import CanaryBudget

        incumbent, candidate = two_cohorts
        candidate.reply_status = 500
        router = _router_for(incumbent, candidate)
        try:
            router.canary.start(
                2, 100.0, CanaryBudget(max_error_delta=0.1, min_requests=3)
            )
            for _ in range(10):
                router.forward("/predict", BODY)
            status = router.status()["canary"]
            assert status["state"] == "rolled_back"
            assert "error-rate delta" in status["rollback_reason"]
        finally:
            router.stop()


class TestRouterShadow:
    def test_shadow_mirrors_and_diffs_without_client_impact(
        self, two_cohorts, tmp_path
    ):
        incumbent, candidate = two_cohorts
        candidate.reply_body = {"task": "regression", "emg": 9.0,
                                "model_version": 2}  # a decision flip
        report = str(tmp_path / "shadow.jsonl")
        router = _router_for(incumbent, candidate)
        try:
            router.shadow.start(2, 1.0, report)
            for _ in range(6):
                status, _, payload = router.forward("/predict", BODY)
                assert status == 200
                # The client always gets the INCUMBENT's answer.
                assert json.loads(payload.decode())["emg"] == 4.0
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if router.shadow.status()["counts"]["mirrored"] >= 6:
                    break
                time.sleep(0.05)
            counts = router.shadow.status()["counts"]
            assert counts["mirrored"] == 6
            assert counts["mismatch"] == 6  # emg 4.0 vs 9.0 flips
            assert candidate.predicts == 6
            lines = [json.loads(x) for x in open(report)]
            assert len(lines) == 6
            assert all(not x["diff"]["match"] for x in lines)
            assert all(
                not x["diff"]["fields"]["emg"]["match"] for x in lines
            )
        finally:
            router.stop()

    def test_mirror_concurrency_is_bounded(self, two_cohorts):
        """With every mirror slot busy (slow candidate), further mirrors
        are dropped and counted — never an unbounded thread pile."""
        incumbent, candidate = two_cohorts
        router = _router_for(incumbent, candidate)
        try:
            router.shadow.start(2, 1.0)
            taken = 0
            while router._mirror_slots.acquire(blocking=False):
                taken += 1
            assert taken > 0
            status, _, _ = router.forward("/predict", BODY)
            assert status == 200  # the client is unaffected
            assert router.shadow.status()["counts"]["skipped_busy"] == 1
            assert candidate.predicts == 0
            for _ in range(taken):
                router._mirror_slots.release()
        finally:
            router.stop()

    def test_shadow_primary_traffic_stays_incumbent(self, two_cohorts):
        incumbent, candidate = two_cohorts
        router = _router_for(incumbent, candidate)
        try:
            router.shadow.start(2, 0.0001)  # mirror ~nothing
            for _ in range(10):
                assert router.forward("/predict", BODY)[0] == 200
            # All primaries went incumbent despite round-robin.
            assert incumbent.predicts == 10
            assert candidate.predicts <= 1
        finally:
            router.stop()


# ----------------------------------------------- real-model reload ladder
WINDOW = 256


@pytest.fixture(scope="module")
def reload_service():
    from seist_tpu.serve import BatcherConfig, ModelPool, ServeService

    pool = ModelPool([("phasenet", "")], window=WINDOW)
    svc = ServeService(
        pool, BatcherConfig(max_batch=2, max_delay_ms=10.0, max_queue=32)
    )
    yield svc
    svc.shutdown()


def _predict_version(svc):
    rng = np.random.default_rng(0)
    out = svc.predict(
        rng.standard_normal((WINDOW, 3)).astype(np.float32).tolist(),
        options={"ppk_threshold": 0.05, "spk_threshold": 0.05},
    )
    return out["model_version"], out


class TestReloadLadder:
    def test_version_stamped_in_response_and_healthz(self, reload_service):
        version, out = _predict_version(reload_service)
        assert version == 1 and out["model"] == "phasenet"
        hz = reload_service.healthz()
        assert hz["entries"]["phasenet"]["version"] == 1
        assert hz["entries"]["phasenet"]["variants"] == ["fp32"]
        assert reload_service.model_versions() == {"phasenet": 1}

    def test_reload_success_swaps_and_bumps_version(self, reload_service):
        from seist_tpu.obs.bus import BUS

        before = reload_service.pool.get("phasenet")
        res = reload_service.reload(version=2)
        assert res["version"] == 2 and res["previous_version"] == 1
        assert res["programs"] > 0
        version, _ = _predict_version(reload_service)
        assert version == 2
        assert reload_service.pool.get("phasenet") is not before
        assert BUS.gauge("serve_model_version", model="phasenet").value == 2
        # The reload's compile report is visible on /healthz.
        assert any(
            r.get("reload_version") == 2
            for r in reload_service.pool.warmup_report
        )

    def test_version_must_be_monotonic(self, reload_service):
        from seist_tpu.serve.protocol import BadRequest

        current = reload_service.model_versions()["phasenet"]
        with pytest.raises(BadRequest, match="monotonic"):
            reload_service.reload(version=current)

    def test_incompatible_checkpoint_leaves_incumbent(
        self, reload_service, monkeypatch
    ):
        from seist_tpu.serve.protocol import IncompatibleCheckpoint
        from seist_tpu.train import checkpoint as ckpt_mod

        # A wrong-architecture checkpoint: phasenet (BN) expects
        # batch_stats + its own param tree; this has neither.
        monkeypatch.setattr(
            ckpt_mod, "load_checkpoint",
            lambda path: {"params": {"bogus": np.zeros((3, 3), np.float32)}},
        )
        before, _ = _predict_version(reload_service)
        with pytest.raises(IncompatibleCheckpoint) as ei:
            reload_service.reload(checkpoint="/fake/wrong-arch.ckpt")
        msg = str(ei.value)
        assert ei.value.code == "incompatible_checkpoint"
        assert "does not fit model 'phasenet'" in msg
        # Named first mismatch, not a flax traceback.
        assert "missing collection at 'batch_stats'" in msg
        after, _ = _predict_version(reload_service)
        assert after == before  # incumbent serving, version pinned

    def test_injected_parity_gate_failure_leaves_incumbent(
        self, reload_service, monkeypatch
    ):
        from seist_tpu.serve.protocol import ParityGateFailed
        from seist_tpu.utils.faults import (
            ServeFaultInjector,
            ServeFaultPlan,
        )

        before, _ = _predict_version(reload_service)
        target = before + 1
        monkeypatch.setattr(
            reload_service, "_faults",
            ServeFaultInjector(
                ServeFaultPlan(bad_candidate_version=target)
            ),
        )
        with pytest.raises(ParityGateFailed) as ei:
            reload_service.reload(version=target)
        assert ei.value.code == "parity_gate_failed"
        assert ei.value.status == 409
        after, _ = _predict_version(reload_service)
        assert after == before

    def test_mid_reload_crash_leaves_incumbent(
        self, reload_service, monkeypatch
    ):
        from seist_tpu.serve.protocol import ReloadFailed

        before, _ = _predict_version(reload_service)

        def boom(entry, buckets):
            raise RuntimeError("XLA compile exploded mid-reload")

        monkeypatch.setattr(reload_service.pool, "warm_entry", boom)
        with pytest.raises(ReloadFailed) as ei:
            reload_service.reload(version=before + 1)
        assert ei.value.code == "reload_failed"
        assert "exploded" in str(ei.value)
        after, _ = _predict_version(reload_service)
        assert after == before

    def test_bad_candidate_version_errors_requests(
        self, reload_service, monkeypatch
    ):
        from seist_tpu.serve.protocol import ServeError
        from seist_tpu.utils.faults import (
            ServeFaultInjector,
            ServeFaultPlan,
        )

        current = reload_service.model_versions()["phasenet"]
        monkeypatch.setattr(
            reload_service, "_faults",
            ServeFaultInjector(
                ServeFaultPlan(bad_candidate_version=current)
            ),
        )
        with pytest.raises(ServeError) as ei:
            _predict_version(reload_service)
        assert ei.value.code == "bad_candidate" and ei.value.status == 500

    def test_group_reload_needs_checkpoints_not_checkpoint(self):
        from seist_tpu.serve.pool import ModelPool
        from seist_tpu.serve.protocol import BadRequest

        pool = ModelPool.__new__(ModelPool)
        pool._window, pool._seed, pool._variants = 256, 0, ("fp32",)
        pool._reload_lock = threading.Lock()
        pool._entries_lock = threading.Lock()
        pool._entries = {
            "seist_s": type("E", (), {
                "name": "seist_s", "is_group": True, "version": 1,
                "task_checkpoints": {"dpk": ""}, "tasks": ("dpk",),
            })()
        }
        with pytest.raises(BadRequest, match="checkpoints"):
            pool.reload("seist_s", buckets=[1], checkpoint="x", version=2)


class TestReloadOverHTTP:
    def test_admin_reload_roundtrip(self, reload_service):
        import http.client

        from seist_tpu.serve.server import start_http_server

        server = start_http_server(reload_service, port=0)
        host, port = server.server_address[:2]
        try:
            current = reload_service.model_versions()["phasenet"]
            target = current + 1

            def post(payload):
                conn = http.client.HTTPConnection(host, port, timeout=120)
                try:
                    raw = json.dumps(payload).encode()
                    conn.request("POST", "/admin/reload", raw,
                                 {"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    return resp.status, json.loads(resp.read().decode())
                finally:
                    conn.close()

            status, out = post({"version": target})
            assert status == 200, out
            assert out["version"] == target
            assert out["previous_version"] == current

            # /healthz reflects the new version + variant surface.
            conn = http.client.HTTPConnection(host, port, timeout=10)
            try:
                conn.request("GET", "/healthz")
                hz = json.loads(conn.getresponse().read().decode())
            finally:
                conn.close()
            assert hz["entries"]["phasenet"]["version"] == target

            # Non-monotonic target: structured 400, version untouched.
            status, out = post({"version": target})
            assert status == 400 and out["error"] == "bad_request"
            assert reload_service.model_versions()["phasenet"] == target
        finally:
            server.shutdown()
