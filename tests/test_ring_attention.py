"""Ring attention == dense attention, on an 8-device virtual seq mesh."""

import jax
import numpy as np
import pytest

from seist_tpu.ops.ring_attention import (
    dense_attention,
    ring_attention,
    ring_attention_local,
)
from seist_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh(data=1, model=1, seq=8)


def _qkv(rng, n=2, l=64, h=2, e=8):
    q = rng.normal(size=(n, l, h, e)).astype(np.float32)
    k = rng.normal(size=(n, l, h, e)).astype(np.float32)
    v = rng.normal(size=(n, l, h, e)).astype(np.float32)
    return q, k, v


def test_matches_dense(seq_mesh, rng):
    q, k, v = _qkv(rng)
    want = np.asarray(dense_attention(q, k, v))
    got = np.asarray(ring_attention(q, k, v, seq_mesh))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_matches_dense_jitted(seq_mesh, rng):
    q, k, v = _qkv(rng, l=128)

    @jax.jit
    def run(q, k, v):
        return ring_attention(q, k, v, seq_mesh)

    want = np.asarray(dense_attention(q, k, v))
    np.testing.assert_allclose(np.asarray(run(q, k, v)), want, rtol=2e-5, atol=2e-5)


def test_single_device_axis(rng):
    # seq axis of size 1 degenerates to dense attention.
    mesh = make_mesh(data=8, model=1, seq=1)
    q, k, v = _qkv(rng, l=32)
    want = np.asarray(dense_attention(q, k, v))
    got = np.asarray(ring_attention(q, k, v, mesh))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_extreme_logits_stable(seq_mesh, rng):
    # Online-softmax must survive large score magnitudes.
    q, k, v = _qkv(rng, l=64)
    q *= 30.0
    want = np.asarray(dense_attention(q, k, v))
    got = np.asarray(ring_attention(q, k, v, seq_mesh))
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_gradients_flow(seq_mesh, rng):
    q, k, v = _qkv(rng, l=32)

    def loss_ring(q, k, v):
        return ring_attention(q, k, v, seq_mesh).sum()

    def loss_dense(q, k, v):
        return dense_attention(q, k, v).sum()

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_dense = jax.grad(loss_dense)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(g_ring), np.asarray(g_dense), rtol=1e-4, atol=1e-4
    )


def test_pooled_kv_block_shapes(seq_mesh, rng):
    # SeisT attention pools K/V (M = L/r != L); the ring must handle
    # unequal Q and K/V block lengths.
    q = rng.normal(size=(2, 128, 2, 8)).astype(np.float32)
    k = rng.normal(size=(2, 16, 2, 8)).astype(np.float32)
    v = rng.normal(size=(2, 16, 2, 8)).astype(np.float32)
    want = np.asarray(dense_attention(q, k, v))
    got = np.asarray(ring_attention(q, k, v, seq_mesh))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_dp_plus_sp_batch_axis(rng):
    # batch_axis='data' composes the ring with data parallelism.
    mesh = make_mesh(data=4, model=1, seq=2)
    q, k, v = _qkv(rng, n=4, l=64)
    want = np.asarray(dense_attention(q, k, v))
    got = np.asarray(ring_attention(q, k, v, mesh, batch_axis="data"))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


# ------------------------------------------------ probability dropout parity
import jax.numpy as jnp

from seist_tpu.ops.pallas_attention import _einsum_attention


def _seed(v=1234):
    return jnp.asarray([v], jnp.int32)


def _dense_dropout(q, k, v, rate, seed):
    scale = 1.0 / np.sqrt(q.shape[-1])
    return np.asarray(
        _einsum_attention(q, k, v, scale, dropout_rate=rate, dropout_seed=seed)
    )


def test_dropout_matches_dense_mask_exactly(seq_mesh, rng):
    # Same seed => the ring regenerates the dense path's mask slice per
    # block, so outputs agree to fp tolerance (same math, same mask).
    q, k, v = _qkv(rng, l=64)
    want = _dense_dropout(q, k, v, 0.3, _seed())
    got = np.asarray(
        ring_attention(
            q, k, v, seq_mesh, dropout_rate=0.3, dropout_seed=_seed()
        )
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_dropout_matches_dense_pooled_kv(seq_mesh, rng):
    # Pooled K/V (M != L): mask column space is the global M.
    q = rng.normal(size=(2, 128, 2, 8)).astype(np.float32)
    k = rng.normal(size=(2, 16, 2, 8)).astype(np.float32)
    v = rng.normal(size=(2, 16, 2, 8)).astype(np.float32)
    want = _dense_dropout(q, k, v, 0.25, _seed(7))
    got = np.asarray(
        ring_attention(
            q, k, v, seq_mesh, dropout_rate=0.25, dropout_seed=_seed(7)
        )
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_dropout_matches_dense_with_batch_axis(rng):
    # dp x sp: the global batch offset must enter the mask stream so each
    # data-shard regenerates its own rows of the dense mask.
    mesh = make_mesh(data=4, model=1, seq=2)
    q, k, v = _qkv(rng, n=4, l=64)
    want = _dense_dropout(q, k, v, 0.3, _seed(3))
    got = np.asarray(
        ring_attention(
            q,
            k,
            v,
            mesh,
            batch_axis="data",
            dropout_rate=0.3,
            dropout_seed=_seed(3),
        )
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_dropout_grads_match_dense(seq_mesh, rng):
    q, k, v = _qkv(rng, l=32)

    def loss_ring(q, k, v):
        return (
            ring_attention(
                q, k, v, seq_mesh, dropout_rate=0.3, dropout_seed=_seed()
            )
            ** 2
        ).sum()

    def loss_dense(q, k, v):
        scale = 1.0 / np.sqrt(q.shape[-1])
        return (
            _einsum_attention(
                q, k, v, scale, dropout_rate=0.3, dropout_seed=_seed()
            )
            ** 2
        ).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ring, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name}",
        )


def test_dropout_requires_seed(seq_mesh, rng):
    q, k, v = _qkv(rng, l=32)
    with pytest.raises(ValueError, match="dropout_seed"):
        ring_attention(q, k, v, seq_mesh, dropout_rate=0.3)


# -------------------------------------------------- model path (--seq-shards)
def test_seist_forward_matches_dense_under_seq_mesh(rng):
    """seist forward with an active seq-sharded mesh (the --seq-shards CLI
    path) routes attention through the ring and matches the single-device
    forward to fp tolerance."""
    import jax.numpy as jnp

    import seist_tpu
    from seist_tpu.models import api
    from seist_tpu.parallel import mesh as mesh_lib

    seist_tpu.load_all()
    L = 512
    model = api.create_model("seist_s_dpk", in_samples=L)
    variables = api.init_variables(model, in_samples=L, batch_size=4)
    # batch must divide the data axis (4): shard_map shards it explicitly.
    x = jnp.asarray(rng.standard_normal((4, L, 3)), jnp.float32)

    want = np.asarray(model.apply(variables, x, train=False))

    mesh = make_mesh(data=4, model=1, seq=2)
    with mesh_lib.use_mesh(mesh):
        got = np.asarray(
            jax.jit(lambda v, x: model.apply(v, x, train=False))(variables, x)
        )
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_seist_train_step_under_seq_mesh(rng):
    """One jitted train step (fwd+bwd+opt) with data x seq mesh shardings —
    the full --seq-shards training path compiles and produces finite loss."""
    import jax.numpy as jnp

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.models import api
    from seist_tpu.parallel import mesh as mesh_lib
    from seist_tpu.parallel.mesh import replicate, shard_batch
    from seist_tpu.train import (
        build_optimizer,
        create_train_state,
        jit_step,
        make_train_step,
    )

    seist_tpu.load_all()
    L = 512
    mesh = make_mesh(data=4, model=1, seq=2)
    model = api.create_model("seist_s_dpk", in_samples=L)
    variables = api.init_variables(model, in_samples=L, batch_size=4)
    state = replicate(
        mesh, create_train_state(model, variables, build_optimizer("adam", 1e-3))
    )
    x = rng.standard_normal((4, L, 3)).astype(np.float32)
    y = np.zeros((4, L, 3), np.float32)
    y[:, 64, 1] = 1.0
    y[:, 128, 2] = 1.0
    y[..., 0] = 1.0 - y[..., 1] - y[..., 2]
    xb, yb = shard_batch(mesh, (jnp.asarray(x), jnp.asarray(y)))

    spec = taskspec.get_task_spec("seist_s_dpk")
    loss_fn = taskspec.make_loss("seist_s_dpk")
    with mesh_lib.use_mesh(mesh):
        step = jit_step(make_train_step(spec, loss_fn), mesh=mesh)
        state, loss, _ = step(state, xb, yb, jax.random.PRNGKey(0))
        jax.block_until_ready(state.params)
    assert np.isfinite(float(loss))
