"""Ring attention == dense attention, on an 8-device virtual seq mesh."""

import jax
import numpy as np
import pytest

from seist_tpu.ops.ring_attention import (
    dense_attention,
    ring_attention,
    ring_attention_local,
)
from seist_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh(data=1, model=1, seq=8)


def _qkv(rng, n=2, l=64, h=2, e=8):
    q = rng.normal(size=(n, l, h, e)).astype(np.float32)
    k = rng.normal(size=(n, l, h, e)).astype(np.float32)
    v = rng.normal(size=(n, l, h, e)).astype(np.float32)
    return q, k, v


def test_matches_dense(seq_mesh, rng):
    q, k, v = _qkv(rng)
    want = np.asarray(dense_attention(q, k, v))
    got = np.asarray(ring_attention(q, k, v, seq_mesh))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_matches_dense_jitted(seq_mesh, rng):
    q, k, v = _qkv(rng, l=128)

    @jax.jit
    def run(q, k, v):
        return ring_attention(q, k, v, seq_mesh)

    want = np.asarray(dense_attention(q, k, v))
    np.testing.assert_allclose(np.asarray(run(q, k, v)), want, rtol=2e-5, atol=2e-5)


def test_single_device_axis(rng):
    # seq axis of size 1 degenerates to dense attention.
    mesh = make_mesh(data=8, model=1, seq=1)
    q, k, v = _qkv(rng, l=32)
    want = np.asarray(dense_attention(q, k, v))
    got = np.asarray(ring_attention(q, k, v, mesh))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_extreme_logits_stable(seq_mesh, rng):
    # Online-softmax must survive large score magnitudes.
    q, k, v = _qkv(rng, l=64)
    q *= 30.0
    want = np.asarray(dense_attention(q, k, v))
    got = np.asarray(ring_attention(q, k, v, seq_mesh))
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_gradients_flow(seq_mesh, rng):
    q, k, v = _qkv(rng, l=32)

    def loss_ring(q, k, v):
        return ring_attention(q, k, v, seq_mesh).sum()

    def loss_dense(q, k, v):
        return dense_attention(q, k, v).sum()

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_dense = jax.grad(loss_dense)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(g_ring), np.asarray(g_dense), rtol=1e-4, atol=1e-4
    )
