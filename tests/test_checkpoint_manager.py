"""TrainCheckpointManager: step-granular async checkpointing, retention,
atomic finalize, overwrite protection, and full-resume-state round-trips
(the tentpole of the fault-tolerance layer; docs/FAULT_TOLERANCE.md)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp
import pytest
from flax import linen as nn

import seist_tpu
from seist_tpu import taskspec
from seist_tpu.train import (
    PREEMPT_EXIT_CODE,
    TrainCheckpointManager,
    build_optimizer,
    create_train_state,
    load_checkpoint,
    make_train_step,
    restore_into_state,
    save_checkpoint,
)

seist_tpu.load_all()

L = 64


class TinyBN(nn.Module):
    """Smallest state shape that exercises every checkpoint field: Dense
    params, BatchNorm running stats, Adam moments. (A real-model state is
    structurally identical — tests/test_train.py covers that round trip —
    and the multi-second phasenet compile would dominate this file.)"""

    @nn.compact
    def __call__(self, x, train=False):
        h = nn.Dense(8)(x)
        h = nn.BatchNorm(use_running_average=not train)(h)
        return jax.nn.softmax(nn.Dense(3)(h), axis=-1)


def fresh_state():
    model = TinyBN()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, L, 3)))
    return create_train_state(model, variables, build_optimizer("adam", 1e-3))


@pytest.fixture(scope="module")
def trained_state():
    """A state advanced one step (so BN stats and Adam moments are
    non-trivial), shared across the module."""
    state = fresh_state()
    spec = taskspec.get_task_spec("phasenet")  # CE on (N, L, 3) probs
    loss_fn = taskspec.make_loss("phasenet")
    step = jax.jit(make_train_step(spec, loss_fn))
    rng = np.random.default_rng(0)
    x = np.asarray(rng.standard_normal((4, L, 3)), np.float32)
    ppk = np.zeros((4, L), np.float32)
    ppk[:, 16] = 1.0
    spk = np.zeros((4, L), np.float32)
    spk[:, 32] = 1.0
    y = np.stack([1.0 - ppk - spk, ppk, spk], axis=-1)
    state, _, _ = step(state, x, y, jax.random.PRNGKey(0))
    return state


# ------------------------------------------------------------ round trips
def test_manager_roundtrip_full_resume_state(tmp_path, trained_state):
    mgr = TrainCheckpointManager(str(tmp_path / "c"), keep_last=3)
    mgr.save(
        7, trained_state, epoch=1, data_epoch=1, data_batch_offset=3,
        seed=42, wait=True,
    )
    fresh = fresh_state()
    restored = mgr.restore(fresh)
    meta = restored["meta"]
    assert int(meta["data_epoch"]) == 1
    assert int(meta["data_batch_offset"]) == 3
    assert int(meta["seed"]) == 42
    assert int(meta["total_batches"]) == 7
    resumed = restore_into_state(fresh, restored)
    # The LR-schedule position rides on state.step + the opt_state count.
    assert int(resumed.step) == int(trained_state.step)
    for a, b in zip(
        jax.tree_util.tree_leaves(trained_state.params),
        jax.tree_util.tree_leaves(resumed.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Satellite: opt_state flat-leaves restore into a live TrainState —
    # Adam moments must round-trip exactly, not just params.
    for a, b in zip(
        jax.tree_util.tree_leaves(trained_state.opt_state),
        jax.tree_util.tree_leaves(resumed.opt_state),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


def test_legacy_load_checkpoint_reads_manager_step_dir(tmp_path, trained_state):
    """tools/supervise.py hands `--checkpoint <...>/model_<step>` to the
    CLI; load_checkpoint must descend into the manager's item layout."""
    mgr = TrainCheckpointManager(str(tmp_path / "c"), keep_last=2)
    path = mgr.save(
        4, trained_state, epoch=0, data_epoch=0, data_batch_offset=4,
        wait=True,
    )
    mgr.close()
    fresh = fresh_state()
    restored = load_checkpoint(path, fresh)
    assert int(restored["meta"]["data_batch_offset"]) == 4
    resumed = restore_into_state(fresh, restored)
    for a, b in zip(
        jax.tree_util.tree_leaves(trained_state.params),
        jax.tree_util.tree_leaves(resumed.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Raw (state-free) read works too — the serve/pool.py path.
    raw = load_checkpoint(path)
    assert "params" in raw and "opt_state" in raw


def test_params_only_checkpoint_restores_with_fresh_opt_state(
    tmp_path, trained_state
):
    """Satellite: params(+stats)-only restore — the import_pretrained
    layout. Weights adopted, optimizer state left fresh, epoch -1."""
    path = str(tmp_path / "params_only")
    with ocp.StandardCheckpointer() as saver:
        saver.save(
            path,
            {
                "params": jax.tree.map(np.asarray, trained_state.params),
                "batch_stats": jax.tree.map(
                    np.asarray, trained_state.batch_stats
                ),
            },
        )
    fresh = fresh_state()
    restored = load_checkpoint(path, fresh)
    assert int(restored["meta"]["epoch"]) == -1
    resumed = restore_into_state(fresh, restored)
    assert int(resumed.step) == 0
    for a, b in zip(
        jax.tree_util.tree_leaves(trained_state.params),
        jax.tree_util.tree_leaves(resumed.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Optimizer state left exactly as the live (fresh) one.
    for a, b in zip(
        jax.tree_util.tree_leaves(fresh.opt_state),
        jax.tree_util.tree_leaves(resumed.opt_state),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -------------------------------------------------------------- retention
def test_retention_keeps_last_k_plus_best(tmp_path, trained_state):
    mgr = TrainCheckpointManager(str(tmp_path / "c"), keep_last=2)
    kw = dict(epoch=0, data_epoch=0)
    mgr.save(1, trained_state, data_batch_offset=1, **kw)
    mgr.save(2, trained_state, data_batch_offset=2, val_loss=0.1, **kw)  # best
    mgr.save(3, trained_state, data_batch_offset=3, val_loss=0.5, **kw)
    mgr.save(4, trained_state, data_batch_offset=4, **kw)
    mgr.save(5, trained_state, data_batch_offset=5, **kw)
    mgr.wait()
    # Last 2 (4, 5) + the best-val step (2); 1 and 3 GC'd.
    assert mgr.all_steps() == [2, 4, 5]
    assert mgr.best_step == 2
    assert not os.path.exists(mgr.step_path(1))
    assert os.path.exists(mgr.step_path(2))
    mgr.close()


def test_best_step_survives_manager_reopen(tmp_path, trained_state):
    """Preempt/relaunch scenario: the best-val step is tracked in a
    best.json sidecar, so a reopened manager's GC still protects it
    (code-review finding: in-memory-only tracking deleted the run's best
    checkpoint a few saves after resume)."""
    root = str(tmp_path / "c")
    kw = dict(epoch=0, data_epoch=0)
    mgr = TrainCheckpointManager(root, keep_last=2)
    mgr.save(2, trained_state, data_batch_offset=2, val_loss=0.1, **kw)
    mgr.wait()
    mgr.close()

    mgr2 = TrainCheckpointManager(root, keep_last=2)
    assert mgr2.best_step == 2  # recovered from the sidecar
    mgr2.save(4, trained_state, data_batch_offset=4, **kw)
    mgr2.save(6, trained_state, data_batch_offset=6, **kw)
    mgr2.save(8, trained_state, data_batch_offset=8, val_loss=0.5, **kw)
    mgr2.wait()
    # Last 2 (6, 8) + the PRE-RESTART best (2); 0.5 never displaces 0.1.
    assert mgr2.all_steps() == [2, 6, 8]
    assert mgr2.best_step == 2
    mgr2.close()


def test_overwrite_is_an_explicit_error(tmp_path, trained_state):
    mgr = TrainCheckpointManager(str(tmp_path / "c"), keep_last=3)
    mgr.save(
        3, trained_state, epoch=0, data_epoch=0, data_batch_offset=3,
        wait=True,
    )
    with pytest.raises(FileExistsError):
        mgr.save(3, trained_state, epoch=0, data_epoch=0, data_batch_offset=3)
    # on_exists='skip' tolerates (epoch-end save after an interval save).
    path = mgr.save(
        3, trained_state, epoch=0, data_epoch=0, data_batch_offset=3,
        val_loss=0.25, on_exists="skip",
    )
    assert os.path.exists(path)
    assert mgr.best_step == 3  # skip still records the metric
    mgr.close()


def test_legacy_save_checkpoint_refuses_overwrite(tmp_path, trained_state):
    """Satellite: the old force=True silently clobbered model-<epoch>."""
    p = save_checkpoint(str(tmp_path / "c"), trained_state, epoch=2, loss=1.0)
    assert os.path.exists(p)
    with pytest.raises(FileExistsError):
        save_checkpoint(str(tmp_path / "c"), trained_state, epoch=2, loss=0.5)


# ------------------------------------------------------- atomic finalize
def test_interrupted_save_layout_is_ignored_and_swept(tmp_path, trained_state):
    """A crash mid-save leaves `model_<s>.orbax-checkpoint-tmp-<n>`: the
    committed step stays the latest, and reopening the manager sweeps the
    debris (cleanup_tmp_directories)."""
    root = str(tmp_path / "c")
    mgr = TrainCheckpointManager(root, keep_last=3)
    mgr.save(
        5, trained_state, epoch=0, data_epoch=0, data_batch_offset=5,
        wait=True,
    )
    mgr.close()
    fake_tmp = os.path.join(root, "model_6.orbax-checkpoint-tmp-1234567")
    os.makedirs(fake_tmp)
    with open(os.path.join(fake_tmp, "junk"), "w") as f:
        f.write("partial write")
    mgr2 = TrainCheckpointManager(root, keep_last=3)
    assert mgr2.latest_step() == 5
    assert not os.path.exists(fake_tmp), "tmp debris must be swept on open"
    mgr2.close()


def test_preempt_exit_code_is_ex_tempfail():
    assert PREEMPT_EXIT_CODE == 75  # sysexits EX_TEMPFAIL, documented


# ------------------------------------------------ data-pipeline position
def test_loader_mid_epoch_position_resume():
    """Satellite: restoring (epoch, batch_offset) must continue the exact
    sample sequence — no replay, no skips — because the shuffle order is
    a pure function of (seed, epoch)."""
    from seist_tpu.data import pipeline

    spec = taskspec.get_task_spec("phasenet")
    sds = pipeline.from_task_spec(
        spec, "synthetic", "train", seed=3, in_samples=512,
        dataset_kwargs={"num_events": 30, "trace_samples": 1024},
    )
    def make_loader():
        return pipeline.Loader(
            sds, batch_size=4, shuffle=True, drop_last=True,
            num_workers=2, seed=3,
        )

    full = make_loader()
    full.set_epoch(2)
    all_batches = list(full)
    assert len(all_batches) >= 3

    resumed = make_loader()
    resumed.set_epoch(2)
    resumed.set_start_batch(2)
    rest = list(resumed)
    assert len(rest) == len(all_batches) - 2
    for want, got in zip(all_batches[2:], rest):
        np.testing.assert_array_equal(want.inputs, got.inputs)
        assert want.meta == got.meta
    # One-shot: the next epoch starts from batch 0 again.
    resumed.set_epoch(3)
    assert len(list(resumed)) == len(all_batches)
    full.close()
    resumed.close()


def test_loader_rejects_negative_start_batch():
    from seist_tpu.data import pipeline

    spec = taskspec.get_task_spec("phasenet")
    sds = pipeline.from_task_spec(
        spec, "synthetic", "train", seed=0, in_samples=512,
        dataset_kwargs={"num_events": 12, "trace_samples": 1024},
    )
    loader = pipeline.Loader(sds, batch_size=4)
    with pytest.raises(ValueError):
        loader.set_start_batch(-1)
    loader.close()
