"""Chaos-lane e2e for the telemetry plane: the obs smoke (tools/
obs_smoke.py) — a real CPU train run with --metrics-port serving
Prometheus text, then an injected data-plane stall whose watchdog trip
must exit 75 AND leave a flight-recorder dump holding the final steps'
spans (ISSUE 6 acceptance)."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_obs_smoke_end_to_end():
    r = subprocess.run(
        [sys.executable, os.path.join("tools", "obs_smoke.py")],
        cwd=_REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=600,
    )
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no result line; stdout={r.stdout[-2000:]} stderr={r.stderr[-2000:]}"
    result = json.loads(lines[-1])
    assert r.returncode == 0 and result["ok"], result
    # The acceptance specifics, re-asserted from the dump itself.
    assert result["rc"] == 75
    dump = json.load(open(result["dump"]))
    assert dump["reason"] == "stall_watchdog"
    assert len(dump["steps"]) >= 1
    assert {"host_wait", "step_dispatch"} <= {
        s["name"] for s in dump["spans"]
    }
    assert dump["metrics"]["collectors"]["data_plane_stall_trips"] >= 1
