"""Benchmark: SeisT-L dpk training throughput (waveforms/sec/chip).

Runs the full jitted training step (forward + BCE loss + backward + Adam +
BatchNorm stat update) of the flagship ``seist_l_dpk`` model on synthetic
8192-sample 3-channel waveforms — the north-star metric from BASELINE.md
(DiTing waveforms/sec/chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` compares against the torch reference measured on this host's
CPU via tools/bench_reference.py (the reference publishes no numbers and no
GPU is available here — see BASELINE.md); the measured value is stored in
tools/reference_baseline.json.
"""

from __future__ import annotations

import json
import os
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.models import api
    from seist_tpu.train import (
        build_cyclic_schedule,
        build_optimizer,
        create_train_state,
        jit_step,
        make_train_step,
    )

    seist_tpu.load_all()

    model_name = os.environ.get("BENCH_MODEL", "seist_l_dpk")
    in_samples = int(os.environ.get("BENCH_SAMPLES", 8192))
    batch = int(os.environ.get("BENCH_BATCH", 256))
    warmup_steps = 5
    bench_steps = int(os.environ.get("BENCH_STEPS", 30))

    model = api.create_model(model_name, in_samples=in_samples)
    variables = api.init_variables(
        model, in_samples=in_samples, batch_size=batch
    )
    sched = build_cyclic_schedule(8e-5, 1e-3, total_steps=10_000)
    state = create_train_state(model, variables, build_optimizer("adam", sched))

    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((batch, in_samples, 3)), dtype=jnp.float32
    )
    y = np.zeros((batch, in_samples, 3), np.float32)
    y[:, in_samples // 4, 1] = 1.0
    y[:, in_samples // 2, 2] = 1.0
    y[..., 0] = 1.0 - y[..., 1] - y[..., 2]
    y = jnp.asarray(y)

    spec = taskspec.get_task_spec(model_name)
    loss_fn = taskspec.make_loss(model_name)
    step = jit_step(make_train_step(spec, loss_fn), donate_state=False)
    key = jax.random.PRNGKey(0)

    for _ in range(warmup_steps):
        state, loss, _ = step(state, x, y, key)
    jax.block_until_ready(state.params)

    t0 = time.perf_counter()
    for _ in range(bench_steps):
        state, loss, _ = step(state, x, y, key)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    wfs = batch * bench_steps / dt

    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "tools",
        "reference_baseline.json",
    )
    vs_baseline = 0.0
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            ref = json.load(f)
        ref_wfs = ref.get("waveforms_per_sec", 0.0)
        if ref_wfs:
            vs_baseline = wfs / ref_wfs

    print(
        json.dumps(
            {
                "metric": f"{model_name}_train_throughput",
                "value": round(wfs, 2),
                "unit": "waveforms/sec/chip",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
