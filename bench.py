"""Benchmark: SeisT-L dpk training throughput (waveforms/sec/chip).

Runs the full jitted training step (forward + BCE loss + backward + Adam +
BatchNorm stat update) of the flagship ``seist_l_dpk`` model on synthetic
8192-sample 3-channel waveforms — the north-star metric from BASELINE.md
(DiTing waveforms/sec/chip; reference training shape `main.py:119-149`
batch 500 x 8192).

Prints ONE JSON line on stdout:
  {"metric", "value", "unit", "vs_baseline", ...diagnostics}
Diagnostic extras: step_time_ms, mfu, flops_per_waveform, dtype, device,
batch. Progress/diagnostics go to stderr so stdout stays one parseable line
even on failure (value=0 + "error" key instead of a traceback).

Robustness (a transient TPU-tunnel hiccup must not lose the round):
the backend is probed in a short-timeout *subprocess* (a wedged backend
init can hang uninterruptibly in-process), retried with backoff before the
model is ever built.

``vs_baseline`` (train mode) = measured wf/s divided by the FROZEN
analytical A100 anchor: one A100 (312 TFLOP/s bf16) assumed to reach 3%
MFU on this workload — the midpoint of BASELINE.md's "A100 analytical
anchor" band (~4k-7k wf/s at seist_l_dpk's 1.70 GFLOP/wf). The frozen
denominator makes the ratio move linearly with our measured throughput
(VERDICT r3 #8; the round-3 formulation was measurement-invariant).
Diagnostics: ``a100_analytical_wfs`` = what one A100 would do at OUR
measured MFU (equal-MFU construction, reduces to the peak-FLOPs ratio);
``vs_torch_cpu_1core`` = ratio vs the torch reference timed on this
host's single CPU core (tools/reference_baseline.json) — a magnitude
sanity check, NOT a chip-class comparison. Missing comparators are
``null`` in success payloads; the failure path emits ``vs_baseline: 0``
for driver-schema compatibility.

Env knobs: BENCH_MODEL, BENCH_BATCH, BENCH_SAMPLES, BENCH_STEPS,
BENCH_DTYPE (fp32|bf16), BENCH_MODE (train|eval|loader|stream;
stream = ops/stream.py continuous-record annotate, record-seconds/sec,
knobs BENCH_RECORD_SECONDS/BENCH_STRIDE), BENCH_STEPS_PER_CALL
(k>1 scans k optimizer updates inside one jitted call — dispatch
amortization; see train/step.py make_multi_train_step), BENCH_DONATE,
BENCH_BREAKDOWN(=0 disables the step_breakdown section)/
BENCH_BREAKDOWN_TOPK, BENCH_REGRESSION_TOL (default 0.10) /
BENCH_FAIL_ON_REGRESSION=1 (exit 4 on a step-time regression vs the
previous JSON for the same config).

Every payload carries top-level ``schema_version`` and ``cached``; a
cached replay additionally prints a loud CACHED REPLAY banner on stderr
(docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Optional

_REPO = os.path.dirname(os.path.abspath(__file__))

# BENCH JSON schema version, stamped top-level on every payload (fresh
# AND cached replays). Bump when a consumer-visible field changes shape.
# v2: adds schema_version/cached stamps + the step_breakdown section.
_SCHEMA_VERSION = 2

# Frozen analytical A100 anchor (see module docstring): 312 TFLOP/s bf16
# at an assumed 3% MFU on this workload — the midpoint of BASELINE.md's
# ~4k-7k wf/s band. Frozen so vs_baseline scales with OUR measurement.
_A100_ANCHOR_FLOPS = 0.03 * 312e12

# bf16 dense peak FLOP/s per chip, keyed by substring of device_kind.
_PEAK_BF16 = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6": 918e12,
}

# HBM bandwidth per chip (bytes/s), same keys. Used for the roofline
# context: ridge intensity = peak_flops / bw; a program whose
# arithmetic intensity sits below the ridge is memory-bound and its MFU
# ceiling is intensity/ridge, not 1.0.
_HBM_BW = {
    "v4": 1.2e12,
    "v5 lite": 0.82e12,
    "v5e": 0.82e12,
    "v5p": 2.77e12,
    "v6": 1.64e12,
}


def _eprint(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def _emit(payload: dict) -> None:
    # Every emitted line carries the schema version and an EXPLICIT
    # cached flag (VERDICT: the 2,799 wf/s headline was a silent
    # three-round-old cached replay — absence of a marker must never
    # read as freshness). setdefault: the replay path stamps cached=True
    # before reaching here.
    payload.setdefault("schema_version", _SCHEMA_VERSION)
    payload.setdefault("cached", False)
    print(json.dumps(payload), flush=True)


# Written after every successful run (logs/ is gitignored); the tracked
# tools/ copy is the round's committed seed so a dead tunnel at round end
# can still report the last verified measurement, marked as cached.
_CACHE_WRITE = os.path.join(_REPO, "logs", "last_bench.json")
_CACHE_READ = (_CACHE_WRITE, os.path.join(_REPO, "tools", "last_bench.json"))


def env_config() -> dict:
    """The benchmark configuration from the BENCH_* env knobs — the ONE
    place defaults live, shared by bench_train() and the cache-key config
    so a cached replay can never be attributed to a different
    dtype/batch/length than what actually ran.

    Batch default 512: closest power of 2 to the reference's headline
    batch 500 (ref main.py:119-149). Dtype default bf16 since round 2's
    dense conv lowerings: with the grouped convs lowered as
    block-diagonal-dense/shift-FMA matmul work, bf16 compute (fp32
    params/BN-stats/loss — train/precision.py) measured +46% over fp32 in
    a same-session A/B (seist_l_dpk b256: 2,678 vs 1,834 wf/s,
    BASELINE.md). The torch reference trains fp32 with at most a TF32
    matmul hint (ref main.py:224-226); bf16-compute training is this
    framework's mixed-precision lever (tolerance-tested in
    tests/test_train.py::test_bf16_train_step_tracks_fp32).
    """
    return {
        "model": os.environ.get("BENCH_MODEL", "seist_l_dpk"),
        "dtype": os.environ.get("BENCH_DTYPE", "bf16"),
        "batch": int(os.environ.get("BENCH_BATCH", 512)),
        "in_samples": int(os.environ.get("BENCH_SAMPLES", 8192)),
        # Micro-steps scanned inside one jitted call (amortizes
        # per-dispatch cost; see train/step.py make_multi_train_step).
        "steps_per_call": int(os.environ.get("BENCH_STEPS_PER_CALL", 1)),
        # Active kernel-lowering overrides (SEIST_GCONV_IMPL,
        # SEIST_CHANNEL_PAD, ...). Part of the cache key: an A/B sweep
        # that forces a non-default lowering must never overwrite — nor
        # later replay as — the default-lowering headline entry
        # (observed 2026-08-02: iso_chanpad_128 landed under the
        # headline's key). Empty dict for a plain default run.
        "lowering_overrides": _lowering_overrides(),
    }


def _lowering_overrides() -> dict:
    """Every SEIST_* env knob that changes the compiled program."""
    return {
        k: os.environ[k]
        for k in sorted(os.environ)
        if k.startswith("SEIST_") and os.environ[k] != ""
    }


def stream_config() -> dict:
    """Stream-mode knobs (BENCH_MODE=stream) — shared by bench_stream()
    and main()'s cache-key config so a cached replay is always attributed
    to the stride/record-length that actually ran."""
    cfg = env_config()
    window = cfg["in_samples"]
    return {
        "batch": cfg["batch"],
        "in_samples": window,
        "stride": int(os.environ.get("BENCH_STRIDE", window // 2)),
        "record_seconds": int(os.environ.get("BENCH_RECORD_SECONDS", 600)),
        "lowering_overrides": _lowering_overrides(),
    }


def _config_key(metric: str, config: dict) -> str:
    """Cache key for one (metric, configuration) pair. Sweeps at other
    batches/dtypes write under their own keys, so the headline config's
    entry can never be overwritten by a later sweep (VERDICT r4 #5)."""
    import hashlib

    digest = hashlib.sha1(
        json.dumps(config, sort_keys=True).encode()
    ).hexdigest()[:10]
    return f"{metric}@{digest}"


def _rekey_cached(cached: dict) -> dict:
    """Re-emit a cached payload under the CURRENT schema (VERDICT r4 #4):
    a replay recorded before a schema change must not lead with a retired
    ratio or silently lack the fields the judge reads. Recomputes
    ``vs_baseline`` against the frozen A100 anchor from the cached wf/s,
    refreshes ``vs_torch_cpu_1core``, attaches ``kernel_status`` ("unknown
    (cached)" when the entry predates kernel-status recording) and a
    ``stale_since``/``age_hours`` staleness marker."""
    cached = dict(cached)
    metric = cached.get("metric", "")
    measured_at = cached.get("measured_at")
    if measured_at:
        cached["stale_since"] = measured_at
        try:
            cached["age_hours"] = round(
                (time.time() - _utc_seconds(measured_at)) / 3600, 1
            )
        except ValueError:
            pass
    if metric.endswith("_train_throughput"):
        flops_per_wf = cached.get("flops_per_waveform") or 0
        wfs = cached.get("value") or 0
        if flops_per_wf and wfs:
            cached["vs_baseline"] = round(
                wfs * flops_per_wf / _A100_ANCHOR_FLOPS, 3
            )
            cached["baseline"] = (
                "one A100 at a frozen 3% MFU analytical anchor "
                "(312 TFLOP/s bf16; BASELINE.md ~4k-7k wf/s band midpoint)"
            )
            mfu = cached.get("mfu")
            cached["a100_analytical_wfs"] = (
                round(mfu * 312e12 / flops_per_wf, 1) if mfu else None
            )
        else:
            # Cannot recompute the anchor ratio — NEVER leave a
            # possibly-retired ratio in the leading field.
            cached["vs_baseline_legacy"] = cached.get("vs_baseline")
            cached["vs_baseline"] = None
        model = metric[: -len("_train_throughput")]
        cached["vs_torch_cpu_1core"] = _vs_baseline(
            wfs, model, cached.get("in_samples")
        )
    if "kernel_status" not in cached:
        cached["kernel_status"] = "unknown(cached)"
    # Re-emitted under the CURRENT schema — stamp the current version
    # (the cached flag itself is stamped by the replay caller).
    cached["schema_version"] = _SCHEMA_VERSION
    return cached


def _utc_seconds(stamp: str) -> float:
    import calendar

    return calendar.timegm(time.strptime(stamp, "%Y-%m-%dT%H:%M:%SZ"))


def _lookup_cached(metric: str, config: Optional[dict]) -> Optional[dict]:
    """THE cache-resolution algorithm, shared by the failure replay
    (_fail) and the step_breakdown regression baseline
    (_load_prev_payload) — two copies once diverged on the legacy
    single-payload layout. Exact (metric, config-hash) key first, then
    the legacy metric key / single-payload layouts; every hit is
    config-field filtered so a batch-64 entry can neither replay for nor
    gate a batch-256 run."""
    for path in _CACHE_READ:
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except Exception:  # noqa: BLE001 - unreadable cache, try next
            continue
        if "metric" in data:  # legacy single-payload file
            data = {data.get("metric"): data}
        cached = data.get(_config_key(metric, config)) if config else None
        if cached is None:
            cached = data.get(metric)
        if not cached or cached.get("metric") != metric:
            continue
        if config and any(cached.get(k) != v for k, v in config.items()):
            continue  # different dtype/batch/... — do not misattribute
        return cached
    return None


def _fail(
    metric: str, unit: str, error: str, config: Optional[dict] = None
) -> None:
    """Emit a failure line — or, if a previous successful run of the same
    metric AND configuration is cached, replay it clearly marked as
    cached: the TPU tunnel here goes down for long stretches (it cost
    round 1 its number), and a marked stale measurement is strictly more
    informative than a 0. Replays are re-emitted under the CURRENT schema
    (see _rekey_cached)."""
    cached = _lookup_cached(metric, config)
    if cached is not None:
        cached = _rekey_cached(cached)
        cached["cached"] = True
        cached["error"] = error
        # LOUD human-summary banner (VERDICT: a silent cached replay ran
        # as the headline for three rounds) — the driver's log shows this
        # even when nobody inspects the JSON flags.
        _eprint("=" * 72)
        _eprint(
            f"*** CACHED REPLAY *** {metric}: NOT a fresh measurement — "
            f"re-emitting the entry measured at "
            f"{cached.get('measured_at', '?')} "
            f"({cached.get('age_hours', '?')} h old) because this run "
            f"failed: {error}"
        )
        _eprint("=" * 72)
        _emit(cached)
        return
    _emit(
        {
            "metric": metric,
            "value": 0,
            "unit": unit,
            "vs_baseline": 0,
            "error": error,
        }
    )


def _tunnel_known_down(max_age_s: int = 600) -> bool:
    """True when a probe-loop/watcher log shows the tunnel failing
    RECENTLY (last line is a ``probe N down`` within ``max_age_s``). The
    probe loops write one line every ~4 min, so a fresh 'down' line is a
    stronger signal than anything a 3x180 s probe ladder could add —
    fail fast instead of spending 10+ min of the capture window
    (VERDICT r4 #9)."""
    import glob

    import re

    now = time.time()
    for path in glob.glob(os.path.join(_REPO, "tools", "*watch*.log")) + glob.glob(
        os.path.join(_REPO, "tools", "*probe*.log")
    ):
        try:
            if now - os.path.getmtime(path) > max_age_s:
                continue
            with open(path) as f:
                lines = [ln.strip() for ln in f if ln.strip()]
        except OSError:
            continue
        if not (lines and " down " in f" {lines[-1]} " and "probe" in lines[-1]):
            continue
        # mtime alone is forgeable by a git checkout of the tracked log —
        # require the line's OWN timestamp to be within the window, and
        # only trust FULL-date stamps (tools/tpu_probe_loop.sh emits
        # %FT%TZ; an HH:MM:SS-only line from an old log would match the
        # same wall-clock window on any later day).
        m = re.search(r"\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}Z", lines[-1])
        if not m:
            continue
        try:
            if now - _utc_seconds(m.group(0)) > max_age_s:
                continue
        except ValueError:
            continue
        _eprint(f"fresh 'tunnel down' signal in {path}: {lines[-1]!r}")
        return True
    return False


def probe_backend(attempts: Optional[int] = None, timeout: Optional[int] = None):
    """Bring up the accelerator in a subprocess under a hard timeout.

    Returns device_kind on success, None after all retries. Round 1 lost its
    number to an in-process backend-init hang (BENCH_r01.json rc=1); a
    subprocess can always be killed. When a probe-loop log shows the tunnel
    down within the last 10 min, the default ladder collapses to one 60 s
    attempt (explicit BENCH_PROBE_* env always wins).
    """
    env_attempts = os.environ.get("BENCH_PROBE_ATTEMPTS")
    env_timeout = os.environ.get("BENCH_PROBE_TIMEOUT")
    if attempts is None:
        attempts = int(env_attempts) if env_attempts else 3
    if timeout is None:
        timeout = int(env_timeout) if env_timeout else 180
    if not (env_attempts or env_timeout) and _tunnel_known_down():
        attempts, timeout = 1, 60
    probe_backend.last_attempts = attempts  # for main()'s failure message
    code = (
        # The sandbox sitecustomize registers the TPU backend at interpreter
        # start, so JAX_PLATFORMS in the env alone is not honored — force it
        # via jax.config before any device query (same pattern as main.py).
        "import os, jax, jax.numpy as jnp;"
        "os.environ.get('JAX_PLATFORMS') and "
        "jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS']);"
        "d = jax.devices();"
        "r = jax.jit(lambda a, b: a @ b)"
        "(jnp.ones((128, 128)), jnp.ones((128, 128)));"
        "r.block_until_ready();"
        "print('KIND=' + d[0].device_kind)"
    )
    for i in range(attempts):
        t0 = time.time()
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=timeout,
            )
            if r.returncode == 0:
                for line in r.stdout.splitlines():
                    if line.startswith("KIND="):
                        kind = line[5:]
                        _eprint(
                            f"probe ok ({time.time() - t0:.1f}s): {kind}"
                        )
                        return kind
            _eprint(
                f"probe attempt {i + 1}/{attempts} rc={r.returncode}: "
                f"{r.stderr.strip()[-400:]}"
            )
        except subprocess.TimeoutExpired:
            _eprint(f"probe attempt {i + 1}/{attempts} timed out ({timeout}s)")
        if i + 1 < attempts:
            delay = 15 * (i + 1)
            _eprint(f"retrying in {delay}s")
            time.sleep(delay)
    return None


def _peak_flops(device_kind: str) -> float:
    dk = device_kind.lower()
    for key, peak in _PEAK_BF16.items():
        if key in dk:
            return peak
    if "tpu" in dk:
        return _PEAK_BF16["v5e"]  # conservative default for unlisted TPUs
    return 0.0  # non-TPU (cpu debug run): MFU-vs-TPU-peak is meaningless


def _vs_baseline(
    wfs: float,
    model_name: Optional[str] = None,
    in_samples: Optional[int] = None,
) -> float:
    """Ratio vs the torch reference's CPU-measured number for the SAME
    model when available (tools/bench_reference.py --models ... writes
    per_model entries), else the legacy flagship number. wf/s scales
    inversely with sequence length, so a baseline recorded at a different
    in_samples is NOT comparable -> 0.0 (batch may differ: throughput is
    already per-waveform)."""
    path = os.path.join(_REPO, "tools", "reference_baseline.json")
    if os.path.exists(path):
        with open(path) as f:
            ref = json.load(f)
        entry = ref.get("per_model", {}).get(model_name) if model_name else None
        if entry is None:
            entry = ref  # legacy flat layout
        ref_wfs = entry.get("waveforms_per_sec", 0.0)
        ref_len = entry.get("in_samples")
        if ref_wfs and (
            in_samples is None or ref_len is None or ref_len == in_samples
        ):
            return round(wfs / ref_wfs, 3)
    return 0.0


def _synthetic_batch(spec, batch: int, in_samples: int, k: int = 1):
    """(inputs, loss_targets) via the real input pipeline on the synthetic
    dataset, so every registered model config benches with its true label
    shapes (dpk soft curves, pmp one-hot, emg/baz/dis values...).

    ``k > 1`` returns ``k`` distinct batches stacked on a leading axis (for
    the multi-step scan path).
    """
    import jax
    import numpy as np
    from seist_tpu.data.pipeline import Loader, from_task_spec

    ds = from_task_spec(
        spec,
        "synthetic",
        "train",
        seed=0,
        in_samples=in_samples,
        augmentation=False,
        data_split=False,
        dataset_kwargs={
            "num_events": batch * k,
            "trace_samples": max(12_000, in_samples + in_samples // 2),
        },
    )
    loader = Loader(ds, batch_size=batch, shuffle=False, num_workers=1)
    try:
        batches = []
        for b in loader:
            batches.append((b.inputs, b.loss_targets))
            if len(batches) == k:
                break
    finally:
        loader.close()
    if k == 1:
        stacked = batches[0]
    else:
        stacked = jax.tree.map(lambda *xs: np.stack(xs), *batches)
    return jax.tree.map(jax.device_put, stacked)


def _cost_analysis(step) -> tuple:
    """(flops, bytes_accessed) of a compiled executable (best-effort;
    zeros if the backend doesn't expose cost analysis)."""
    try:
        cost = step.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return (
            float(cost.get("flops", 0.0)),
            float(cost.get("bytes accessed", 0.0)),
        )
    except Exception as e:  # noqa: BLE001 - cost analysis is best-effort
        _eprint(f"cost_analysis unavailable: {e!r}")
        return 0.0, 0.0


def _roofline(flops: float, bytes_accessed: float, device_kind: str):
    """Roofline context for the compiled step (VERDICT r3 #2: 'a written
    roofline proof of the bound' needs the program's actual arithmetic
    intensity, which XLA's cost analysis exposes as bytes-accessed).

    Returns None when either input is unavailable. ``mfu_bound`` is the
    ceiling the MEMORY system imposes: intensity/ridge, capped at 1.0 —
    measured MFU far below it means the gap is overhead (layout copies,
    dispatch, serialization), not bandwidth."""
    peak = _peak_flops(device_kind)
    dk = device_kind.lower()
    bw = next((v for k, v in _HBM_BW.items() if k in dk), None)
    if not (flops and bytes_accessed and peak and bw):
        return None
    intensity = flops / bytes_accessed
    ridge = peak / bw
    return {
        "bytes_accessed": round(bytes_accessed),
        "arithmetic_intensity": round(intensity, 2),
        "ridge_intensity": round(ridge, 2),
        "memory_bound": intensity < ridge,
        "mfu_bound": round(min(1.0, intensity / ridge), 4),
    }


def _emit_and_cache(payload: dict, config: Optional[dict] = None) -> None:
    """Emit the JSON line and persist it for _fail's marked cached replay
    (the metric+config keys in the payload make a replay attributable).

    The cache file maps metric -> payload so an eval-mode run cannot
    evict the train entry the driver's round-end bench.py relies on
    (legacy single-payload files are upgraded in place). With ``config``
    the payload is ALSO stored under the (metric, config-hash) key, which
    a later sweep at a different batch/dtype can never overwrite — the
    headline entry survives the sweeps (VERDICT r4 #5)."""
    entries = {}
    try:
        with open(_CACHE_WRITE) as f:
            prev = json.load(f)
        entries = prev if "metric" not in prev else {prev["metric"]: prev}
    except (OSError, ValueError):
        pass
    entries[payload["metric"]] = payload
    if config:
        entries[_config_key(payload["metric"], config)] = payload
    try:
        os.makedirs(os.path.dirname(_CACHE_WRITE), exist_ok=True)
        with open(_CACHE_WRITE, "w") as f:
            json.dump(entries, f)
    except OSError as e:
        _eprint(f"could not cache result: {e}")
    _emit(payload)


def _degraded(device_kind: str, kernel_status: dict) -> bool:
    """True when a TPU run fell back to the einsum attention path — the
    fused-kernel guarantee the silicon runner used to assert out-of-band
    (VERDICT r4 #5). ``unprobed`` is NOT degraded: attention-free models
    (phasenet etc.) never probe."""
    return (
        "tpu" in device_kind.lower()
        and kernel_status.get("overall") == "einsum-fallback"
    )


def _enforce_fused(payload: dict) -> None:
    """Loud failure on a degraded TPU run: always a stderr banner; exit
    non-zero under BENCH_REQUIRE_FUSED=1 (the silicon runner sets it for
    the headline step, making its config-matching assert redundant)."""
    if not payload.get("degraded"):
        return
    _eprint(
        "ERROR: TPU run fell back to the einsum attention path "
        f"(kernel_status={json.dumps(payload.get('kernel_status'))}); "
        "the measurement is valid but NOT the fused-kernel configuration."
    )
    if os.environ.get("BENCH_REQUIRE_FUSED") == "1":
        sys.exit(3)


def _setup_model(cfg: dict, tx=None):
    """Shared bench scaffolding: registry load, task spec, model, and an
    initialized TrainState at the benchmark batch shape. ``tx`` defaults
    to plain Adam (fine for eval, where the optimizer is never applied);
    bench_train passes its cyclic-schedule optimizer so the LR-schedule
    cost stays inside the timed step like production."""
    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.models import api
    from seist_tpu.train import build_optimizer, create_train_state

    seist_tpu.load_all()
    model_name, in_samples = cfg["model"], cfg["in_samples"]
    spec = taskspec.get_task_spec(model_name)
    loss_fn = taskspec.make_loss(model_name)
    in_channels = taskspec.get_num_inchannels(model_name)
    model = api.create_model(
        model_name, in_channels=in_channels, in_samples=in_samples
    )
    variables = api.init_variables(
        model,
        in_samples=in_samples,
        in_channels=in_channels,
        batch_size=cfg["batch"],
    )
    state = create_train_state(
        model, variables, tx if tx is not None else build_optimizer("adam", 1e-3)
    )
    return spec, loss_fn, state


def measure_input_split(spec, loss_fn, cfg: dict, steps: int) -> dict:
    """Host-wait vs device-compute split of the training input pipeline,
    measured BOTH ways in the same run (BENCH_PIPELINE_STEPS knob):

    * ``host_path`` — the classic loop: per-sample numpy augmentation +
      Python stacking on the host, ``device_put``, then the jitted step.
      ``host_wait`` is everything before the device can start.
    * ``device_aug_cached`` — raw epochs resident on device
      (data/pipeline.DeviceEpochCache), augmentation + label synthesis
      inside the jitted step; the only per-step host work is handing over
      a (1, B) int32 index array.

    The per-path ``input_bound_fraction`` (utils/profiling.StepTimeSplit)
    is the input-bound→compute-bound evidence the r05 silicon run needs:
    host_path ~1 and cached ~0 means the chip was idling behind the input
    pipeline and no longer is.
    """
    from seist_tpu.utils.logger import logger as _logger

    # Dataset/loader construction logs to the console handler, which
    # writes to stdout — keep the BENCH stdout contract (one JSON line)
    # from picking up more noise than it already tolerates.
    _logger.enable_console(False)
    try:
        return _measure_input_split(spec, loss_fn, cfg, steps)
    finally:
        _logger.enable_console(True)


def _measure_input_split(spec, loss_fn, cfg: dict, steps: int) -> dict:
    import jax
    import jax.numpy as jnp

    from seist_tpu import taskspec as _ts
    from seist_tpu.data import device_aug as da
    from seist_tpu.data import pipeline as pl
    from seist_tpu.train import make_cached_train_call, make_train_step
    from seist_tpu.utils.profiling import StepTimeSplit

    batch, in_samples = cfg["batch"], cfg["in_samples"]
    dtype = cfg["dtype"]
    label_kinds = {
        _ts.get_kind(n) for n in _ts.flatten_io_names(spec.labels)
    }
    aug_rates = dict(
        shift_event_rate=0.2,
        add_noise_rate=0.4,
        add_gap_rate=0.4,
        drop_channel_rate=0.4,
        scale_amplitude_rate=0.4,
        pre_emphasis_rate=0.4,
        # generate_noise clears VALUE/ONEHOT labels (host path crashes,
        # device path refuses) — only enable it for soft-label specs.
        generate_noise_rate=(
            0.05 if label_kinds == {_ts.SOFT} else 0.0
        ),
    )
    n_events = max(batch, batch * (steps + 2) // 2)
    sds = pl.from_task_spec(
        spec,
        "synthetic",
        "train",
        seed=0,
        in_samples=in_samples,
        augmentation=True,
        data_split=False,
        shuffle=True,
        dataset_kwargs={
            "num_events": n_events,
            "trace_samples": in_samples + in_samples // 2,
        },
        **aug_rates,
    )
    key = jax.random.PRNGKey(0)

    def fresh_state():
        # Same construction as the headline bench (_setup_model), so the
        # split measures the program the bench actually times.
        return _setup_model(cfg)[2]

    # -- host path --------------------------------------------------------
    split_host = StepTimeSplit(skip_first=1)
    state = fresh_state()
    step = jax.jit(make_train_step(spec, loss_fn, compute_dtype=dtype))
    loader = pl.Loader(
        sds, batch_size=batch, shuffle=True, drop_last=True, num_workers=8
    )
    try:
        it, epoch = iter(loader), 0
        # Timing via StepTimeSplit's span helpers (the obs stopwatch —
        # the ONE interval clock, satellite dedup): host() covers
        # fetch/stack/stage, device() dispatch→block.
        for _ in range(steps + 1):
            with split_host.host():
                b = next(it, None)
                if b is None:
                    epoch += 1
                    loader.set_epoch(epoch)
                    it = iter(loader)
                    b = next(it)
                x = jax.device_put(b.inputs)
                y = jax.device_put(b.loss_targets)
                jax.block_until_ready((x, y))
            with split_host.device():
                state, loss, _ = step(state, x, y, key)
                jax.block_until_ready(loss)
    finally:
        loader.close()

    # -- cached device-aug path -------------------------------------------
    store = pl.RawStore.build(sds)
    cache = pl.DeviceEpochCache(store)
    acfg = da.AugConfig.from_preprocessor(
        sds.preprocessor,
        seed=0,
        raw_len=store.raw_len,
        phase_slots=store.phase_slots,
    )
    proc = da.make_cache_processor(
        acfg, sds.input_names, sds.label_names,
        n_raw=store.n_raw, augmentation=store.augmentation,
    )
    call = jax.jit(
        make_cached_train_call(
            spec, loss_fn, proc, steps_per_call=1, compute_dtype=dtype
        )
    )
    split_cached = StepTimeSplit(skip_first=1)
    state = fresh_state()

    def chunk_stream():
        epoch = 0
        while True:
            yield from (
                (epoch, c)
                for c in cache.epoch_index_chunks(
                    epoch, seed=0, shuffle=True,
                    batch_size=batch, steps_per_call=1,
                )
            )
            epoch += 1

    chunks = chunk_stream()
    for _ in range(steps + 1):
        with split_cached.host():
            epoch, idx = next(chunks)
            idx_dev = jax.block_until_ready(jnp.asarray(idx))
        with split_cached.device():
            state, loss, _ = call(
                state, cache.arrays, idx_dev, jnp.int32(epoch), key
            )
            jax.block_until_ready(loss)

    host = split_host.summary()
    cached = split_cached.summary()
    return {
        "steps": steps,
        "batch": batch,
        "cache_mib": round(cache.nbytes / 2**20, 1),
        "host_path": host,
        "device_aug_cached": cached,
        # The tentpole claim, decided from numbers measured in THIS run.
        "host_stack_removed": (
            (host["host_wait_ms_per_step"] or 0.0)
            > (cached["host_wait_ms_per_step"] or 0.0)
        ),
    }


def measure_data_plane(spec, cfg: dict, batches: int) -> dict:
    """Clean-path overhead of the data-plane I/O guard (data/io_guard.py):
    the same per-sample pipeline is timed with the guard active (retry
    wrapper + ingest validation + quarantine bookkeeping) and bypassed
    (``io_guard.disabled()``), on an already-warm synthetic dataset so
    both passes price the *pipeline*, not wavelet synthesis. Reported as
    per-batch loader-stage medians + ``overhead_frac`` — the BENCH
    evidence that self-healing reads cost a negligible slice of loader
    stage time on the fault-free path. Counters ride along so a bench run
    that DID hit faults (retries/quarantines > 0) is self-describing."""
    from seist_tpu.utils.logger import logger as _logger

    _logger.enable_console(False)
    try:
        return _measure_data_plane(spec, cfg, batches)
    finally:
        _logger.enable_console(True)


def _measure_data_plane(spec, cfg: dict, passes: int) -> dict:
    from seist_tpu.data import io_guard
    from seist_tpu.data import pipeline as pl

    in_samples = cfg["in_samples"]
    batch = min(cfg["batch"], 32)  # stage cost is per-sample; keep it cheap

    def make_sds(cache: bool):
        return pl.from_task_spec(
            spec,
            "synthetic",
            "train",
            seed=0,
            in_samples=in_samples,
            augmentation=True,
            data_split=False,
            shuffle=True,
            dataset_kwargs={
                "num_events": 2 * batch,
                "trace_samples": in_samples + in_samples // 2,
                "cache": cache,
            },
        )

    # Stage denominator: UNCACHED events — a cached synthetic "read" is a
    # ~10 us memcpy, two orders cheaper than any real dataset's decode
    # (data/base.py profile: ~1 ms/sample read stage), which would
    # overstate the guard's share of stage time ~100x; wavelet synthesis
    # is the same order as a real read and stands in for it.
    sds = make_sds(cache=False)
    n = len(sds)
    for i in range(n):  # one warm pass (page cache, numpy internals)
        sds[i]

    def full_pass_s() -> float:
        t0 = time.perf_counter()
        for i in range(n):
            sds[i]
        return time.perf_counter() - t0

    # min-of-passes, order alternated per round: a full pipeline pass is
    # hundreds of us/sample with >10% run-to-run scheduler noise, and
    # back-to-back passes over the same objects warm CPU caches for
    # whichever runs second — min() over alternated rounds strips both
    # biases and compares the two paths at their respective best.
    on_s, off_s = [], []
    for r in range(max(passes, 2)):
        if r % 2 == 0:
            on_s.append(full_pass_s())
            with io_guard.disabled():
                off_s.append(full_pass_s())
        else:
            with io_guard.disabled():
                off_s.append(full_pass_s())
            on_s.append(full_pass_s())
    stage_us = min(off_s) / n * 1e6

    # The guard delta itself, measured directly (read+validate vs bare
    # read, no preprocessing in the loop) on a CACHED clone — cheap
    # (~10 us) raw reads make the subtraction precise, where an uncached
    # read's synthesis noise would drown a microsecond-scale delta. This
    # is the number the <2%-of-stage claim rests on.
    micro = make_sds(cache=True)
    for i in range(micro.raw_size):
        micro._fetch_event(i, idx=i)  # warm: event cache + guard internals

    def micro_pass_us(guarded: bool) -> float:
        t0 = time.perf_counter()
        if guarded:
            for i in range(micro.raw_size):
                micro._fetch_event(i, idx=i)
        else:
            for i in range(micro.raw_size):
                micro._dataset[i]
        return (time.perf_counter() - t0) / micro.raw_size * 1e6

    # min over alternating rounds, same reasoning as the stage passes: a
    # single pass over a small dataset is one scheduler hiccup away from
    # a 5x-overstated delta.
    g_us, r_us = [], []
    for r in range(8):
        if r % 2 == 0:
            g_us.append(micro_pass_us(True))
            r_us.append(micro_pass_us(False))
        else:
            r_us.append(micro_pass_us(False))
            g_us.append(micro_pass_us(True))
    guard_us = max(min(g_us) - min(r_us), 0.0)

    return {
        "passes": max(passes, 2),
        "samples_per_pass": n,
        "stage_us_per_sample": round(stage_us, 1),
        "guard_us_per_sample": round(guard_us, 2),
        "overhead_frac_of_stage": round(guard_us / max(stage_us, 1e-9), 4),
        # Whole-pipeline cross-check (min-of-passes); negative = below
        # measurement noise. The claim is "small", not "exactly zero".
        "pass_overhead_frac": round(
            (min(on_s) - min(off_s)) / max(min(off_s), 1e-9), 4
        ),
        "counters": io_guard.COUNTERS.snapshot(),
    }


def _load_prev_payload(metric: str, config: Optional[dict]) -> Optional[dict]:
    """The previous successful payload for (metric, config) from the
    bench cache — the regression baseline for step_breakdown deltas.
    Read BEFORE _emit_and_cache overwrites the entry; resolution and
    config-field filtering are _lookup_cached, the same algorithm the
    failure replay uses, so baseline and replay can never diverge."""
    return _lookup_cached(metric, config)


def measure_telemetry_overhead(step_ms: float) -> dict:
    """Clean-path cost of the per-step telemetry the train worker runs
    (two spans + a flight-recorder record + two gauge sets), measured the
    same way the io-guard overhead is (PR 5): min over repeated passes so
    a scheduler hiccup can't overstate a microsecond-scale number. The
    <1%-of-step-time acceptance figure comes from here."""
    from seist_tpu.obs.bus import MetricsBus
    from seist_tpu.obs.flight import FlightRecorder

    bus = MetricsBus()
    rec = FlightRecorder(capacity=256)
    bus.add_span_sink(rec.on_span)
    g_step = bus.gauge("global_step")
    g_loss = bus.gauge("train_loss")
    n = 2000

    def one_pass_us() -> float:
        t0 = time.perf_counter()
        for i in range(n):
            with bus.span("host_wait"):
                pass
            with bus.span("step_dispatch"):
                pass
            rec.record_step(i)
            g_step.set(i)
            g_loss.set(0.5)
        return (time.perf_counter() - t0) / n * 1e6

    one_pass_us()  # warm (dict entries, deque, histogram buckets)
    us = min(one_pass_us() for _ in range(5))
    return {
        "us_per_step": round(us, 2),
        "frac_of_step": (
            round(us / (step_ms * 1e3), 6) if step_ms else None
        ),
    }


def measure_step_breakdown(
    step_fn,
    example_args: tuple,
    device_kind: str,
    call_ms: float,
    compiled=None,
    prev: Optional[dict] = None,
    updates_per_call: int = 1,
) -> dict:
    """The BENCH ``step_breakdown`` section (ISSUE 6 tentpole): per-op
    attribution of the measured step time.

    * analytic jaxpr walk (obs/attribution.py): top-k ops by
      roofline-modeled time with exact dot/conv FLOPs, bytes moved, and
      the per-class MFU decomposition;
    * the compiled executable's ``cost_analysis()``/``memory_analysis()``
      for the XLA-side cross-check (``model_vs_xla_flops`` ~1 means the
      analytic model and XLA agree on the FLOP count);
    * measured telemetry overhead (must stay <1% of step time);
    * fail-loud regression deltas against the previous BENCH JSON for the
      same (metric, config) — see ``_enforce_no_regression``.

    ``call_ms`` is the wall time of ONE jitted call (= steps_per_call
    optimizer updates), matching what ``step_fn`` traces to.
    """
    from seist_tpu.obs.attribution import attribute_step

    peak = _peak_flops(device_kind) or None
    dk = device_kind.lower()
    bw = next((v for k, v in _HBM_BW.items() if k in dk), None)
    bd = attribute_step(
        step_fn,
        example_args,
        peak_flops=peak,
        hbm_bw=bw,
        measured_step_ms=call_ms,
        top_k=int(os.environ.get("BENCH_BREAKDOWN_TOPK", 8)),
    )
    bd["call_time_ms"] = round(call_ms, 3)

    if compiled is not None:
        flops_x, bytes_x = _cost_analysis(compiled)
        mem = {}
        try:
            ma = compiled.memory_analysis()
            for k in (
                "argument_size_in_bytes",
                "output_size_in_bytes",
                "temp_size_in_bytes",
                "generated_code_size_in_bytes",
            ):
                v = getattr(ma, k, None)
                if v is not None:
                    mem[k] = int(v)
        except Exception as e:  # noqa: BLE001 - memory analysis is
            # backend-dependent diagnostics, like _cost_analysis
            _eprint(f"memory_analysis unavailable: {e!r}")
        bd["xla"] = {
            "flops": flops_x or None,
            "bytes_accessed": bytes_x or None,
            # XLA's cost_analysis counts a scan body ONCE regardless of
            # trip count (verified in bench_train's normalization note),
            # while the analytic walk multiplies by it — normalize the
            # model side back to one update so ~1 really means agreement
            # on the packed (steps_per_call > 1) path too.
            "model_vs_xla_flops": (
                round(
                    bd["flops_total"] / max(updates_per_call, 1) / flops_x, 3
                )
                if flops_x
                else None
            ),
            "memory_analysis": mem or None,
        }

    bd["telemetry"] = measure_telemetry_overhead(call_ms)
    bd["regression"] = _breakdown_regression(call_ms, bd, prev)
    return bd


def _breakdown_regression(
    call_ms: float, bd: dict, prev: Optional[dict]
) -> dict:
    """Deltas vs the previous JSON for the same (metric, config): step
    time and per-op time shares. ``regressed`` goes true past the
    tolerance (BENCH_REGRESSION_TOL, default 10%) so a step-time
    regression fails loudly like the data-plane bench does.

    The comparison baseline is STICKY: a regressed run carries the
    previous baseline forward (``baseline_call_time_ms``) instead of
    becoming the baseline itself — otherwise the cache overwrite after a
    regressed run would make the retry compare the slow measurement
    against itself and pass green, ratcheting the baseline down to
    exactly the regression the gate exists to block. A run back inside
    tolerance resets the baseline to its own time."""
    tol = float(os.environ.get("BENCH_REGRESSION_TOL", 0.10))
    out: dict = {"tolerance_frac": tol, "regressed": False}
    prev_bd = (prev or {}).get("step_breakdown") or {}
    prev_reg = prev_bd.get("regression") or {}
    prev_ms = prev_bd.get("call_time_ms")
    baseline_ms = (
        prev_reg.get("baseline_call_time_ms")
        if prev_reg.get("regressed")
        else prev_ms
    ) or prev_ms
    if not baseline_ms:
        out["baseline_call_time_ms"] = round(call_ms, 3)  # first v2 run
        return out
    delta = (call_ms - baseline_ms) / baseline_ms
    regressed = bool(delta > tol)
    out.update(
        prev_call_time_ms=prev_ms,
        baseline_call_time_ms=(
            round(baseline_ms, 3) if regressed else round(call_ms, 3)
        ),
        prev_measured_at=(prev or {}).get("measured_at"),
        call_time_delta_frac=round(delta, 4),
        regressed=regressed,
    )
    prev_ops = {
        o["op"]: o for o in prev_bd.get("top_ops", []) if "op" in o
    }
    op_deltas = {}
    for o in bd.get("top_ops", []):
        po = prev_ops.get(o["op"])
        if po and po.get("time_frac"):
            op_deltas[o["op"]] = round(
                o["time_frac"] - po["time_frac"], 4
            )
    if op_deltas:
        out["top_op_time_frac_delta"] = op_deltas
    return out


def _enforce_no_regression(payload: dict) -> None:
    """Loud failure on a step-time regression vs the previous JSON:
    always a stderr banner; exit 4 under BENCH_FAIL_ON_REGRESSION=1 (the
    silicon runner's gate), mirroring _enforce_fused."""
    reg = (payload.get("step_breakdown") or {}).get("regression") or {}
    if not reg.get("regressed"):
        return
    _eprint(
        "ERROR: step-time REGRESSION vs previous bench "
        f"({reg.get('prev_measured_at')}): call time "
        f"{payload['step_breakdown'].get('call_time_ms')} ms vs baseline "
        f"{reg.get('baseline_call_time_ms')} ms "
        f"({reg.get('call_time_delta_frac', 0) * 100:+.1f}%, tolerance "
        f"{reg.get('tolerance_frac', 0) * 100:.0f}%)."
    )
    if os.environ.get("BENCH_FAIL_ON_REGRESSION") == "1":
        sys.exit(4)


def bench_train(device_kind: str) -> None:
    import jax

    from seist_tpu.utils.misc import enable_compile_cache

    # The seist_l train step costs ~4 min to compile on this host; across
    # bench/matrix/A-B invocations of identical programs that dominates
    # wall time.
    enable_compile_cache(verbose=True)

    from seist_tpu.train import (
        build_cyclic_schedule,
        build_optimizer,
        make_multi_train_step,
        make_train_step,
    )

    cfg = env_config()
    model_name = cfg["model"]
    in_samples = cfg["in_samples"]
    batch = cfg["batch"]
    dtype = cfg["dtype"]
    spc = cfg["steps_per_call"]
    warmup_steps = 5
    bench_steps = int(os.environ.get("BENCH_STEPS", 30))
    metric = f"{model_name}_train_throughput"
    unit = "waveforms/sec/chip"

    sched = build_cyclic_schedule(8e-5, 1e-3, total_steps=10_000)
    spec, loss_fn, state = _setup_model(cfg, tx=build_optimizer("adam", sched))

    x, y = _synthetic_batch(spec, batch, in_samples, k=spc)
    step_fn = (
        make_multi_train_step(
            spec, loss_fn, compute_dtype=dtype, steps_per_call=spc
        )
        if spc > 1
        else make_train_step(spec, loss_fn, compute_dtype=dtype)
    )
    key = jax.random.PRNGKey(0)

    # AOT-compile ONCE; the same executable serves cost analysis (FLOPs for
    # MFU) and the timed loop — a second jit compile of this model costs
    # minutes on a busy host and once lost the round to a timeout. State
    # donation matches the production step (train/worker.py): the optimizer
    # update reuses the old state's HBM.
    donate = os.environ.get("BENCH_DONATE", "1") != "0"
    t0 = time.time()
    step = (
        jax.jit(step_fn, donate_argnums=(0,) if donate else ())
        .lower(state, x, y, key)
        .compile()
    )
    _eprint(f"compiled in {time.time() - t0:.1f}s (donate={donate})")
    flops_per_step, bytes_per_step = _cost_analysis(step)

    t0 = time.time()
    for _ in range(warmup_steps):
        state, loss, _ = step(state, x, y, key)
    jax.block_until_ready(state.params)
    _eprint(f"warmup done ({time.time() - t0:.1f}s), loss={float(loss):.4f}")

    t0 = time.perf_counter()
    for _ in range(bench_steps):
        state, loss, _ = step(state, x, y, key)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    # With steps_per_call > 1, each call is `spc` optimizer updates on
    # `spc` distinct micro-batches; normalize everything to ONE update.
    # XLA cost_analysis counts a scan body ONCE regardless of trip count
    # (verified: the k=8 program reports the same total flops as k=1), so
    # the per-waveform divisor is `batch`, not `batch * spc`.
    wfs = batch * spc * bench_steps / dt
    step_ms = dt / (bench_steps * spc) * 1e3
    flops_per_wf = flops_per_step / batch if flops_per_step else 0.0
    peak = _peak_flops(device_kind)
    mfu = wfs * flops_per_wf / peak if (flops_per_wf and peak) else 0.0

    # Comparators (VERDICT r3 #8: lead with the honest figure of merit).
    # vs_baseline = wfs / (frozen A100 anchor wf/s); the anchor's wf/s =
    # _A100_ANCHOR_FLOPS / flops_per_wf, so the ratio scales linearly
    # with measured throughput (a 10x regression shows as 10x here).
    # a100_analytical_wfs (diagnostic) = one A100 at OUR measured MFU —
    # the equal-MFU construction that reduces to the peak-FLOPs ratio.
    vs_anchor = (
        round(wfs * flops_per_wf / _A100_ANCHOR_FLOPS, 3)
        if flops_per_wf
        else None
    )
    a100_wfs = (
        mfu * 312e12 / flops_per_wf if flops_per_wf and mfu else None
    )
    from seist_tpu.ops.pallas_attention import kernel_status_summary

    ks = kernel_status_summary()

    # Input-pipeline split (BENCH_PIPELINE_STEPS=0 disables): host-path
    # vs cached-device-aug host-wait/device-time per step, measured in
    # THIS run so the input_bound_fraction claim is self-contained.
    split = None
    psteps = int(os.environ.get("BENCH_PIPELINE_STEPS", 4))
    if psteps > 0:
        t_split = time.time()
        try:
            split = measure_input_split(spec, loss_fn, cfg, psteps)
            _eprint(f"input-split measured in {time.time() - t_split:.1f}s")
        except Exception as e:  # noqa: BLE001 - split is diagnostics only
            _eprint(f"input-split measurement failed: {e!r}")

    # Data-plane guard overhead (BENCH_DATA_PLANE_BATCHES=0 disables):
    # guarded vs bypassed loader stage time, measured in THIS run.
    data_plane = None
    dp_batches = int(os.environ.get("BENCH_DATA_PLANE_BATCHES", 6))
    if dp_batches > 0:
        t_dp = time.time()
        try:
            data_plane = measure_data_plane(spec, cfg, dp_batches)
            _eprint(f"data-plane overhead measured in {time.time() - t_dp:.1f}s")
        except Exception as e:  # noqa: BLE001 - diagnostics only
            _eprint(f"data-plane measurement failed: {e!r}")

    # Per-op step-time attribution (BENCH_BREAKDOWN=0 disables): the
    # step_breakdown section — top-k ops, MFU decomposition, telemetry
    # overhead, regression deltas vs the previous cached entry for this
    # exact config (read before _emit_and_cache overwrites it).
    breakdown_cfg = {k: v for k, v in cfg.items() if k != "model"}
    breakdown = None
    if int(os.environ.get("BENCH_BREAKDOWN", "1")):
        t_bd = time.time()
        try:
            breakdown = measure_step_breakdown(
                step_fn,
                (state, x, y, key),
                device_kind,
                call_ms=step_ms * spc,
                compiled=step,
                prev=_load_prev_payload(metric, breakdown_cfg),
                updates_per_call=spc,
            )
            _eprint(f"step breakdown traced in {time.time() - t_bd:.1f}s")
        except Exception as e:  # noqa: BLE001 - diagnostics only
            _eprint(f"step-breakdown measurement failed: {e!r}")

    payload = {
        "metric": metric,
        "value": round(wfs, 2),
        "unit": unit,
        "input_pipeline": split,
        "data_plane": data_plane,
        "input_bound_fraction": (
            (split or {}).get("host_path", {}).get("input_bound_fraction")
        ),
        "vs_baseline": vs_anchor,  # null when cost analysis gave no FLOPs
        "baseline": (
            "one A100 at a frozen 3% MFU analytical anchor "
            "(312 TFLOP/s bf16; BASELINE.md ~4k-7k wf/s band midpoint)"
        ),
        "a100_analytical_wfs": round(a100_wfs, 1) if a100_wfs else None,
        "vs_torch_cpu_1core": _vs_baseline(wfs, model_name, in_samples),
        "step_time_ms": round(step_ms, 2),
        "step_breakdown": breakdown,
        "mfu": round(mfu, 4),
        "mfu_note": "vs bf16 dense peak",
        "flops_per_waveform": round(flops_per_wf),
        "roofline": _roofline(flops_per_step, bytes_per_step, device_kind),
        "kernel_status": ks,
        "degraded": _degraded(device_kind, ks),
        "dtype": dtype,
        "device": device_kind,
        "batch": batch,
        "in_samples": in_samples,
        "steps_per_call": spc,
        # Part of the replay config-match contract: without this field a
        # later _fail(config=...) comparison reads None != {} and refuses
        # EVERY replay (observed live; the @config-hash key alone is not
        # enough because the field filter also runs on exact-key hits).
        "lowering_overrides": cfg["lowering_overrides"],
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    _emit_and_cache(payload, config=breakdown_cfg)
    _enforce_fused(payload)
    _enforce_no_regression(payload)


def bench_eval(device_kind: str) -> None:
    """Inference/eval throughput: the jitted no-grad eval step (forward +
    masked loss, running BN stats — train/step.py make_eval_step, the body
    the reference's validate.py:54-127 runs per batch). The deployment
    half of the story (tools/predict.py, demo_predict.py) runs this same
    forward; BENCH_MODE=eval gives it a measured number."""
    import jax

    from seist_tpu.utils.misc import enable_compile_cache

    enable_compile_cache(verbose=True)

    import jax.numpy as jnp

    from seist_tpu.train import make_eval_step

    cfg = env_config()
    model_name, in_samples = cfg["model"], cfg["in_samples"]
    batch, dtype = cfg["batch"], cfg["dtype"]
    warmup_steps = 5
    bench_steps = int(os.environ.get("BENCH_STEPS", 30))

    spec, loss_fn, state = _setup_model(cfg)
    x, y = _synthetic_batch(spec, batch, in_samples)
    mask = jnp.ones((batch,), jnp.float32)

    step_fn = make_eval_step(spec, loss_fn, compute_dtype=dtype)
    t0 = time.time()
    step = jax.jit(step_fn).lower(state, x, y, mask).compile()
    _eprint(f"compiled in {time.time() - t0:.1f}s")
    flops_per_step, bytes_per_step = _cost_analysis(step)

    for _ in range(warmup_steps):
        loss, _outputs = step(state, x, y, mask)
    jax.block_until_ready(loss)
    _eprint(f"warmup done, loss={float(loss):.4f}")

    t0 = time.perf_counter()
    for _ in range(bench_steps):
        loss, _outputs = step(state, x, y, mask)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    wfs = batch * bench_steps / dt
    flops_per_wf = flops_per_step / batch if flops_per_step else 0.0
    from seist_tpu.ops.pallas_attention import kernel_status_summary

    ks = kernel_status_summary()
    payload = {
            "metric": f"{model_name}_eval_throughput",
            "value": round(wfs, 2),
            "unit": "waveforms/sec/chip",
            # No comparator: tools/reference_baseline.json records train
            # throughput only.
            "vs_baseline": None,
            "kernel_status": ks,
            "degraded": _degraded(device_kind, ks),
            "step_time_ms": round(dt / bench_steps * 1e3, 2),
            "mfu": round(wfs * flops_per_wf / _peak_flops(device_kind), 4)
            if flops_per_wf and _peak_flops(device_kind)
            else 0.0,
            "mfu_note": "vs bf16 dense peak",
            "flops_per_waveform": round(flops_per_wf),
            "roofline": _roofline(
                flops_per_step, bytes_per_step, device_kind
            ),
            "dtype": dtype,
            "device": device_kind,
            "batch": batch,
            "in_samples": in_samples,
            # Replay config-match contract (see bench_train's note).
            "lowering_overrides": cfg["lowering_overrides"],
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    _emit_and_cache(
        payload,
        config={
            k: v
            for k, v in cfg.items()
            if k not in ("model", "steps_per_call")
        },
    )
    _enforce_fused(payload)


def bench_stream(device_kind: str) -> None:
    """Continuous-record serving throughput (VERDICT r3 #3): ops/stream.py
    ``annotate`` — sliding-window forward + on-device overlap stitch +
    fixed-shape peak picking — over a synthetic record, reported as
    record-seconds annotated per wall-second. The reference's deployment
    surface scores one fixed window at a time (demo_predict.py:59-97);
    this is the path a real deployment runs.

    Env: BENCH_MODEL (dpk family / phasenet), BENCH_RECORD_SECONDS (600),
    BENCH_STRIDE (window//2), BENCH_SAMPLES = window (8192).
    """
    import jax
    import numpy as np

    from seist_tpu.utils.misc import enable_compile_cache

    enable_compile_cache(verbose=True)

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.models import api
    from seist_tpu.ops.stream import annotate

    seist_tpu.load_all()
    cfg = env_config()
    scfg = stream_config()
    model_name, window = cfg["model"], scfg["in_samples"]
    batch = scfg["batch"]
    fs = 100
    rec_seconds = scfg["record_seconds"]
    stride = scfg["stride"]
    spec = taskspec.get_task_spec(model_name)
    channel0 = spec.labels[0][0]

    model = api.create_model(model_name, in_samples=window)
    variables = api.init_variables(
        model, in_samples=window, batch_size=batch
    )

    def apply_fn(x):
        return model.apply(variables, x, train=False)

    rng = np.random.default_rng(0)
    record = rng.standard_normal((rec_seconds * fs, 3)).astype(np.float32)

    kw = dict(
        window=window,
        stride=stride,
        batch_size=batch,
        sampling_rate=fs,
        channel0=channel0,
    )
    t0 = time.time()
    annotate(apply_fn, record, **kw)  # compile + warmup
    _eprint(f"stream warmup (incl. compile) {time.time() - t0:.1f}s")
    steps = int(os.environ.get("BENCH_STEPS", 3))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = annotate(apply_fn, record, **kw)
    dt = time.perf_counter() - t0
    rss = rec_seconds * steps / dt
    from seist_tpu.ops.pallas_attention import kernel_status_summary

    ks = kernel_status_summary()
    payload = {
            "metric": f"{model_name}_stream_throughput",
            "value": round(rss, 2),
            "unit": "record-seconds/sec",
            "vs_baseline": None,  # the reference has no continuous path
            "kernel_status": ks,
            "degraded": _degraded(device_kind, ks),
            "record_seconds": rec_seconds,
            # cache-key field (_fail matches on it): the window IS the
            # model's in_samples.
            "in_samples": window,
            "window": window,
            "stride": stride,
            "batch": batch,
            "sampling_rate_hz": fs,
            "n_picks": int(out["ppk"].size + out["spk"].size),
            "device": device_kind,
            "dtype": "fp32",
            # Replay config-match contract (see bench_train's note).
            "lowering_overrides": scfg["lowering_overrides"],
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    _emit_and_cache(payload, config=scfg)
    _enforce_fused(payload)


def bench_loader() -> None:
    """Input-pipeline-only throughput: full augmentation, no device."""
    from tools.bench_loader import run

    run()


def _warn_stale_watcher_queues(log_dir: Optional[str] = None) -> None:
    """A queued-measurement log that starts but never reaches a terminal
    marker means a watcher died silently — round 2 lost its most important
    numbers that way. Report it ONCE, then quarantine the queue by
    APPENDING an ``ABANDONED`` terminal marker (the same marker a human
    abandoning a queue writes): a warning that fires on every run forever
    is ambient noise nobody acts on, while a one-shot warning + in-band
    marker is a discrete event the round's operator has to notice exactly
    when it happens. Appending — rather than renaming — keeps the file
    where every consumer (ab_summary, humans tailing it) expects it, is
    safe even if the watcher turns out to be alive and appends later, and
    a NEW ``start`` line after the marker re-arms detection for the next
    watcher automatically."""
    import glob
    import re

    terminal_re = re.compile(r"ALL DONE|REFRESH DONE|DONE \(|ABANDONED")
    for path in glob.glob(
        os.path.join(log_dir or os.path.join(_REPO, "tools"), "ab_*.log")
    ):
        try:
            # A watcher mid-run legitimately has no terminal marker yet —
            # only call it stale once the log has sat untouched for 30 min
            # (every runner step appends, refreshing mtime).
            import time as _time

            if _time.time() - os.path.getmtime(path) < 1800:
                continue
            with open(path) as f:
                text = f.read()
        except OSError:
            continue
        # Stale iff the LAST start marker has no terminal marker after it —
        # catches a dead second watcher appending to a log whose first
        # watcher finished (the exact round-2 failure mode).
        last_start = None
        for m in re.finditer(r"\bstart\b", text):
            last_start = m.end()
        if last_start is not None and not terminal_re.search(text, last_start):
            stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
            # NB: the marker must not itself contain the word `start` —
            # the detector above would read it as a new watcher beginning
            # after the ABANDONED and warn forever again.
            marker = (
                f"ABANDONED {stamp} — auto-quarantined by bench.py: the "
                f"watcher never reached a terminal status; its "
                f"measurements likely never ran\n"
            )
            try:
                with open(path, "a") as f:
                    if not text.endswith("\n"):
                        f.write("\n")
                    f.write(marker)
                how = "quarantined with an ABANDONED marker"
            except OSError as e:
                how = f"could not quarantine: {e}"
            _eprint(
                f"WARNING: stale watcher queue {path} — started but has no "
                f"terminal status; its measurements likely never ran "
                f"({how}; a new 'start' line re-arms detection)"
            )


def main() -> None:
    from seist_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()
    _warn_stale_watcher_queues()
    mode = os.environ.get("BENCH_MODE", "train")
    model_name = env_config()["model"]
    kind_suffix = {"eval": "eval", "stream": "stream"}.get(mode, "train")
    metric = f"{model_name}_{kind_suffix}_throughput"
    unit = (
        "record-seconds/sec" if mode == "stream" else "waveforms/sec/chip"
    )

    if mode == "loader":
        try:
            bench_loader()
        except Exception as e:  # noqa: BLE001 - one JSON line, not a traceback
            import traceback

            _eprint(traceback.format_exc())
            _fail(
                "input_pipeline_throughput",
                "waveforms/sec/host",
                f"{type(e).__name__}: {e}",
            )
        return

    # A cached replay must match this run's exact configuration — never
    # attribute another dtype/batch/length's number to this one. Each
    # mode matches only the keys its payload actually carries: stream
    # runs fp32 regardless of BENCH_DTYPE and has no steps_per_call;
    # eval has no steps_per_call.
    config = {k: v for k, v in env_config().items() if k != "model"}
    if mode == "stream":
        config = stream_config()
    elif mode == "eval":
        config.pop("steps_per_call", None)
    # Resolve the cache BEFORE probing (BENCH_r04 burned 3x180 s probe
    # timeouts + backoff only to then emit a cached replay): when a
    # matching replay exists, a probe failure costs nothing — so if the
    # tunnel is ALSO known down, skip the probe entirely and replay now;
    # otherwise still try for a fresh number but collapse the ladder to
    # one short attempt. Explicit BENCH_PROBE_* env always wins over
    # BOTH shortcuts — an operator forcing a fresh measurement gets the
    # ladder they asked for, replay or not.
    explicit_probe_env = bool(
        os.environ.get("BENCH_PROBE_ATTEMPTS")
        or os.environ.get("BENCH_PROBE_TIMEOUT")
    )
    have_replay = _lookup_cached(metric, config) is not None
    if have_replay and not explicit_probe_env and _tunnel_known_down():
        _eprint(
            "tunnel known down and a matching cached replay exists: "
            "skipping the backend probe entirely"
        )
        _fail(metric, unit, "tunnel known down; probe skipped", config=config)
        return
    if have_replay and not explicit_probe_env:
        _eprint(
            "cached replay available: collapsing probe ladder to 1x60 s"
        )
        kind = probe_backend(attempts=1, timeout=60)
    else:
        kind = probe_backend()
    if kind is None:
        n = getattr(probe_backend, "last_attempts", "?")
        _fail(
            metric,
            unit,
            f"backend unavailable after {n} probe attempt(s)",
            config=config,
        )
        return
    try:
        if mode == "eval":
            bench_eval(kind)
        elif mode == "stream":
            bench_stream(kind)
        else:
            bench_train(kind)
    except Exception as e:  # noqa: BLE001 - one JSON line, not a traceback
        import traceback

        _eprint(traceback.format_exc())
        _fail(metric, unit, f"{type(e).__name__}: {e}", config=config)


if __name__ == "__main__":
    main()
