# seist_tpu build targets.

NATIVE_DIR := seist_tpu/native
CXX ?= g++
CXXFLAGS ?= -O3 -fPIC -shared -std=c++17 -Wall

.PHONY: native test clean

native: $(NATIVE_DIR)/libwavekit.so

$(NATIVE_DIR)/libwavekit.so: $(NATIVE_DIR)/wavekit.cpp
	$(CXX) $(CXXFLAGS) -o $@ $<

test:
	python -m pytest tests/ -x -q

clean:
	rm -f $(NATIVE_DIR)/libwavekit.so
