# seist_tpu build targets.

NATIVE_DIR := seist_tpu/native
CXX ?= g++
CXXFLAGS ?= -O3 -fPIC -shared -std=c++17 -Wall

.PHONY: native test serve-smoke clean

native: $(NATIVE_DIR)/libwavekit.so

$(NATIVE_DIR)/libwavekit.so: $(NATIVE_DIR)/wavekit.cpp
	$(CXX) $(CXXFLAGS) -o $@ $<

test:
	python -m pytest tests/ -x -q

# Checkpoint-free serving smoke: warm-compile, micro-batch 24 requests,
# print a BENCH-style latency/throughput/fill-ratio JSON line.
serve-smoke:
	JAX_PLATFORMS=cpu python tools/bench_serve.py --model-name phasenet \
		--window 256 --requests 24 --concurrency 6 --max-batch 4

clean:
	rm -f $(NATIVE_DIR)/libwavekit.so
