# seist_tpu build targets.

NATIVE_DIR := seist_tpu/native
CXX ?= g++
CXXFLAGS ?= -O3 -fPIC -shared -std=c++17 -Wall

.PHONY: native test t1 lint lint-baseline irlint-report lockgraph \
	replay-smoke serve-smoke serve-chaos obs-smoke trace-smoke \
	rollout-smoke chaos pack-smoke bench-loader repick-smoke \
	bench-repick quant-smoke stream-smoke twin-smoke stream-chaos \
	batch-chaos bench-batch-fleet clean

native: $(NATIVE_DIR)/libwavekit.so

$(NATIVE_DIR)/libwavekit.so: $(NATIVE_DIR)/wavekit.cpp
	$(CXX) $(CXXFLAGS) -o $@ $<

test:
	python -m pytest tests/ -x -q

# Static-analysis gate, ALL FOUR analyzers through one shared frontend
# invocation (docs/STATIC_ANALYSIS.md; single interpreter startup, one
# file walk feeding the three AST passes, one manifest walk, combined
# exit code): jaxlint — JAX hot-path hazards (host syncs, PRNG key
# reuse, missing donate_argnums, retraces, wall-clock intervals, broad
# excepts); threadlint — concurrency/lifecycle hazards (unguarded
# shared attrs, unsafe signal handlers, silent thread death, untimed
# waits, SYN-drop backlogs, exit-code contract); detlint — determinism
# hazards (unsorted dir enumeration, unseeded/global RNG, wall-clock or
# unregistered env reads in det-critical modules, set/dict iteration
# order, float reduction order); irlint — IR-level properties of the
# LOWERED programs the repo ships (fp32 matmuls under the bf16 policy,
# donation aliasing, in-program host transfers, bucket padding waste,
# replicated data args on meshes). Each fails only on findings not
# grandfathered in its tools/<tool>_baseline.json.
lint:
	python -m tools.lint

# Re-accept the current jaxlint findings (review the diff before
# committing!). Deliberately does NOT touch tools/threadlint_baseline.json,
# tools/detlint_baseline.json, or tools/irlint_baseline.json: all three
# are empty by construction — fix the code or add a rationale'd
# `# threadlint: disable` / `# detlint: disable` / `# irlint: disable`
# instead of grandfathering (detlint and irlint --update-baseline
# additionally REFUSE to write while their baselines are empty).
lint-baseline:
	python -m tools.jaxlint seist_tpu --update-baseline

# Machine-readable IR audit (docs/STATIC_ANALYSIS.md "IR-level
# analysis"): per-program bf16 matmul-FLOPs coverage, donation-aliasing
# table, bucket padding waste, host-transfer counts — the numbers bench
# and CI trend across commits.
irlint-report:
	python -m tools.irlint --report irlint_report.json

# threadlint runtime audit lane (docs/STATIC_ANALYSIS.md): the smoke
# lane with every in-test lock instrumented — fails on lock-order
# cycles (potential deadlocks) and locks held across blocking calls.
lockgraph:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m smoke --lock-graph \
	  -p no:cacheprovider -p no:xdist -p no:randomly

# detlint runtime audit lane (docs/STATIC_ANALYSIS.md "Determinism
# analysis"): the whole det-critical pipeline — pack -> resume ->
# repick -> journal-restore + alert WAL — run twice under perturbation
# (PYTHONHASHSEED 0 vs 1, 1 vs 2 workers, reversed directory inode
# order via the relink shim) with every digest pinned byte-identical.
# One JSON verdict line (digests + perturbations tried); non-zero on
# any divergence.
replay-smoke:
	JAX_PLATFORMS=cpu python -m tools.replay_smoke

# Tier-1 verify: the exact line from ROADMAP.md (fast lane, CPU backend,
# slow-marked kill/resume e2e excluded). Prints DOTS_PASSED for the driver.
t1: SHELL := /bin/bash
t1:
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

# Fault-injection suite (docs/FAULT_TOLERANCE.md): the faults unit lane
# plus the chaos e2e lane — real training runs under injected NaN/kill/
# SIGTERM/flaky-read/corrupt-sample/loader-stall faults.
chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'chaos or faults' \
	  -p no:cacheprovider -p no:xdist -p no:randomly

# Packed data-plane smoke (docs/DATA.md): 2-worker shard-parallel pack
# of the synthetic dataset (cross-checked bit-identical against a serial
# pack), then 2 training epochs on packed vs unpacked at the same seed
# with the loss curves pinned equal. One JSON verdict line; non-zero on
# any parity failure.
pack-smoke:
	JAX_PLATFORMS=cpu python -m tools.pack_smoke

# Packed-ingest throughput ladder (docs/DATA.md "Benchmarks"): hdf5
# per-sample reads vs packed per-sample reads vs packed+direct-ingest
# batch fills on one shared fixture, with the per-stage ms/wf budget,
# plus the fp32/bf16/int8 storage-dtype ladder (measured bytes/wf;
# int8 includes the stage_raw device-dequant lane). Gates: direct >=
# 2x hdf5, int8 bytes <= 0.55x fp32. Committed headline:
# BENCH_loader_r02.json.
bench-loader:
	JAX_PLATFORMS=cpu python -m tools.bench_loader --compare

# Batch re-picking smoke (docs/DATA.md "Batch re-picking"): 2-worker
# CPU map-reduce over a synthetic packed archive — one worker SIGKILL'd
# mid-shard, relaunched at its exact segment offset — asserting the
# merged catalog is BYTE-identical to a serial run and that every
# worker's CompileBudget window recorded ZERO compiles after warm-up.
# One JSON verdict line; non-zero on any violation.
repick-smoke:
	JAX_PLATFORMS=cpu python -m tools.repick_smoke

# int8 end-to-end smoke (docs/DATA.md "Storage dtype"): tiny fp32 +
# int8 packs of the same synthetic source -> direct ingest -> inline
# repick of both -> gates on-disk bytes <= 0.55x fp32, decision parity
# vs the fp32 catalog (pick positions within the repo's 0.1 s residual
# tolerance), host-feed (fill + device_put) speedup >= 1.7x (bytes-
# bound CPU mechanism proof; the end-to-end chip run is flagged
# tpu_run: pending), and zero post-warm-up compiles. One JSON verdict
# line. Committed headline: BENCH_repick_r02.json.
quant-smoke:
	JAX_PLATFORMS=cpu python -m tools.quant_smoke

# Batch-fleet chaos lane (docs/FAULT_TOLERANCE.md "Batch fleet
# faults"): a 3-worker LEASE fleet (tools/supervise_repick.py over
# batch/fleet.py) re-picks a synthetic archive with every batch-plane
# failure class injected at once — worker 0 rides out a lease-store
# partition (commits while locally valid, parks, heals into a counted
# fence-reject), worker 1 is SIGKILL'd at its first lease (expiry ->
# peer reclaim at the next fencing token -> crash-budget relaunch),
# worker 2 is preempted into the exit-75 contract (drain, release,
# rejoin). Gates: fleet finishes unattended, merged catalog sha256 ==
# the serial no-fault run, ZERO double-committed segments, and the
# fence-reject counter accounts the zombie attempt. repick_smoke
# geometry, so the XLA compile cache stays warm across lanes. One JSON
# verdict line.
batch-chaos:
	JAX_PLATFORMS=cpu python -m tools.batch_chaos

# Batch-fleet scaling headline (docs/FAULT_TOLERANCE.md): 3 lease
# workers vs 1 over the same archive via supervise_repick, byte-identity
# HARD-gated; the >= 1.8x wall-clock gate is enforced on >= 3-core
# hosts and recorded as pending on the 1-core CI box (the quant_smoke
# "tpu_run: pending" idiom). Committed headline: BENCH_batch_fleet_r01.json.
bench-batch-fleet:
	JAX_PLATFORMS=cpu python -m tools.bench_batch_fleet

# Batch-vs-serve throughput headline (docs/DATA.md "Batch re-picking"):
# the repick engine and tools/bench_serve on the SAME model/window/host,
# gated at batch >= 5x serve waveforms/sec/chip. Committed headline:
# BENCH_repick_r01.json.
bench-repick:
	JAX_PLATFORMS=cpu python -m tools.bench_repick

# Telemetry-plane smoke (docs/OBSERVABILITY.md): 2-step CPU train run
# with --metrics-port, live Prometheus/JSON/flight scrape, then an
# injected SEIST_FAULT_IO_STALL crash that must exit 75 and leave a
# flight-recorder dump with the final steps' spans. Also runs in the
# chaos lane (tests/test_obs_e2e.py).
obs-smoke:
	JAX_PLATFORMS=cpu python tools/obs_smoke.py

# Checkpoint-free serving smoke: warm-compile (AOT), micro-batch 24
# single-task requests, then a multi-task fan-out pass — 12 requests
# against a shared-trunk seist_s group (dpk+emg+dis on ONE trunk run per
# trace); bench_serve exits non-zero unless EVERY response answered ALL
# requested heads (fanout_complete). Each prints a BENCH-style JSON line.
serve-smoke:
	JAX_PLATFORMS=cpu python tools/bench_serve.py --model-name phasenet \
		--window 256 --requests 24 --concurrency 6 --max-batch 4
	JAX_PLATFORMS=cpu python tools/bench_serve.py --model-name seist_s \
		--tasks dpk,emg,dis --window 256 --requests 12 --concurrency 4 \
		--max-batch 4

# Distributed-tracing smoke (docs/OBSERVABILITY.md "Distributed
# tracing"): 2-replica fleet + router under bench_serve with hedging
# forced on every request; a hedged request's stitched cross-process
# trace (tools/trace_report.py) must total within 10% of the
# client-observed latency, carry queue-wait + device-program spans, and
# GET /fleet/metrics.json must aggregate router + both replicas.
trace-smoke:
	JAX_PLATFORMS=cpu python tools/trace_smoke.py

# Streaming smoke (docs/SERVING.md "Streaming inference"): a real
# phasenet replica driven over HTTP by a 50-station network, 30 s of
# waveform per station through POST /stream — gates zero dropped
# alert-tier windows (no 429/503, no degraded sessions) and
# streaming<->offline pick parity vs POST /annotate on 3 sampled
# stations. One JSON verdict line.
stream-smoke:
	JAX_PLATFORMS=cpu python tools/stream_smoke.py

# Network digital twin (docs/SERVING.md "Streaming inference"): a
# deterministic mainshock + Omori-aftershock scenario over 50 simulated
# stations (noise stations, dropouts, late bursts, duplicate packets)
# driven through the full serve+stream+association plane — gates zero
# missed mainshock alerts, zero alert-tier sheds/dropped windows, and a
# pinned p99 sample->alert latency; writes the BENCH_stream_r01.json
# lane with the per-stage latency breakdown.
twin-smoke:
	JAX_PLATFORMS=cpu python tools/twin.py --smoke \
		--output BENCH_stream_r01.json

# Live-rollout smoke (docs/SERVING.md "Live rollout"): a real 2-replica
# phasenet fleet rolled to a new model version (SIGHUP + --rollout-file)
# under sustained open-loop load — asserts zero failed requests, fleet
# convergence on the target version, and zero stale-version responses
# after convergence (bench_serve --expect-version gate). One JSON
# verdict line; the 3-replica variant is the serve-chaos flywheel test.
rollout-smoke:
	JAX_PLATFORMS=cpu python tools/rollout_smoke.py

# Serving chaos lane (docs/FAULT_TOLERANCE.md "Serving faults"): real
# replica subprocesses under SEIST_FAULT_SERVE_* — SIGKILL-mid-load with
# zero client-visible failures, black-hole circuit open/close, overload
# shedding that protects the alert tier's SLO, the live-rollout
# flywheel (3-replica roll under sustained load: zero failures, zero
# stale versions after convergence), and canary auto-rollback of an
# injected bad candidate. The fleet supervisor + router + rollout units
# (model-free) ride along. Subset of `make chaos`, runnable alone when
# iterating on serve/.
serve-chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/test_serve_chaos.py \
	  tests/test_serve_fleet.py tests/test_router.py -q \
	  -p no:cacheprovider -p no:xdist -p no:randomly

# Streaming chaos lane (docs/FAULT_TOLERANCE.md "Streaming faults"): the
# twin's exported mainshock schedule replayed against a REAL 3-replica
# twin_replica fleet — SIGKILL on the station-heavy replica mid-
# mainshock (journal restore + router re-home, exactly-once alerts at
# the consumer) and a drop/dup/reorder packet-fault run. Each test
# prints a `[stream-chaos] VERDICT {json}` line. Subset of `make chaos`
# (the tests carry the chaos marker), runnable alone when iterating on
# stream/.
stream-chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/test_stream_chaos.py -q \
	  -p no:cacheprovider -p no:xdist -p no:randomly

clean:
	rm -f $(NATIVE_DIR)/libwavekit.so
