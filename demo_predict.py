"""Single-trace inference demo (ref demo_predict.py:26-97).

Load a checkpoint, normalize one 3-channel waveform, run the jitted forward,
and plot the phase-picking figure.

    python demo_predict.py --model-name seist_s_dpk --checkpoint <ckpt> \
        --input trace.npz --output-dir ./demo_out

``--input`` accepts an ``.npz`` with a ``(3, L)`` or ``(L, 3)`` ``data``
array; without it a synthetic event is generated so the demo always runs.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np


def normalize(data: np.ndarray, mode: str = "std") -> np.ndarray:
    """Per-channel demean + scale (ref demo_predict.py:8-23) — delegates to
    the canonical seist_tpu.data.preprocess.normalize. The demo's 'max'
    historically meant abs-max (unlike the training pipeline's signed max),
    preserved via mode 'absmax'."""
    from seist_tpu.data.preprocess import normalize as _norm

    return _norm(data, "absmax" if mode == "max" else "std", axis=-1)


def load_data(path: str, in_samples: int) -> np.ndarray:
    if path:
        npz = np.load(path)
        data = np.asarray(npz["data"], dtype=np.float32)
        if data.shape[0] > data.shape[-1]:  # (L, C) -> (C, L)
            data = data.T
    else:
        from seist_tpu.data.synthetic import Synthetic

        ds = Synthetic(
            seed=0, mode="test", num_events=4, trace_samples=in_samples
        )
        data = ds[0][0]["data"]
    return data[:, :in_samples]


def main() -> None:
    parser = argparse.ArgumentParser(description="seist_tpu demo inference")
    parser.add_argument("--model-name", default="seist_s_dpk", type=str)
    parser.add_argument("--checkpoint", default="", type=str)
    parser.add_argument("--input", default="", type=str, help=".npz with 'data'")
    parser.add_argument("--in-samples", default=8192, type=int)
    parser.add_argument("--sampling-rate", default=50, type=int)
    parser.add_argument("--norm-mode", default="std", type=str)
    parser.add_argument("--output-dir", default="./demo_out", type=str)
    args = parser.parse_args()

    from seist_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()

    import seist_tpu
    from seist_tpu.models import api
    from seist_tpu.train.checkpoint import load_checkpoint
    from seist_tpu.utils.visualization import vis_phase_picking

    seist_tpu.load_all()

    model = api.create_model(
        args.model_name, in_channels=3, in_samples=args.in_samples
    )
    variables = api.init_variables(model, in_samples=args.in_samples, in_channels=3)
    if args.checkpoint:
        restored = load_checkpoint(args.checkpoint)
        variables = {"params": restored["params"]}
        stats = restored.get("batch_stats")
        if stats:  # omit the collection entirely for models without BN
            variables["batch_stats"] = stats

    data = normalize(load_data(args.input, args.in_samples), args.norm_mode)
    x = data.T[None, ...]  # (1, L, C) channels-last

    @jax.jit
    def forward(variables, x):
        return model.apply(variables, x, train=False)

    preds = np.asarray(forward(variables, x))[0]  # (L, 3)
    paths = vis_phase_picking(
        waveforms=data,
        waveforms_labels=["Z", "N", "E"],
        preds=preds.T,
        true_phase_idxs=[],
        true_phase_labels=[],
        pred_phase_labels=["Detection", "P-phase", "S-phase"],
        sampling_rate=args.sampling_rate,
        save_name=f"_{args.model_name}",
        save_dir=args.output_dir,
    )
    print(f"Saved: {paths}")


if __name__ == "__main__":
    main()
