"""Offline dataset -> packed-shard converter (the at-scale ingest format).

Repacks registered datasets into seist_tpu.data.packed's contiguous
binary shards + columnar index, removing the per-sample reader API cost
from the training read path (measured ~30% of per-sample loader cost in
the r3 stage budget; `python -m tools.bench_loader --compare` re-measures
it). Run once per dataset (or dataset mixture); then train with
``--dataset-name packed --data-dir <out>``.

    # single source, 4 pack workers
    python -m tools.pack_dataset --dataset diting_light \
        --data-dir /data/diting --out /data/diting_packed --workers 4

    # DiTing+PNW+SOS joint mixture in ONE directory (per-row source_id
    # provenance; train with --mixture-temperature)
    python -m tools.pack_dataset \
        --mixture diting_light:/data/diting,pnw:/data/pnw,sos:/data/sos \
        --out /data/joint_packed --workers 8

The pack is plan-first (data/packed.py): shard boundaries are a pure
function of the source sizes and the capacity knobs, so an N-worker pack
is bit-identical to a serial one and an interrupted pack resumes at the
last complete shard (re-run the same command; ``--no-resume`` forces a
full rewrite). Sources are constructed with ``data_split=False,
shuffle=False`` so the packed order is the source metadata order; the
packed dataset then applies the standard seeded shuffle/split itself —
same seed => same split as training on the source directly.

Prints ONE JSON verdict line: shards, bytes, samples, wall_s, workers.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List


def _parse_mixture(spec: str) -> List[tuple]:
    """``name:dir[,name:dir...]`` -> [(name, dir), ...]."""
    pairs = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, data_dir = part.partition(":")
        if not sep:
            raise SystemExit(
                f"--mixture entries are name:data_dir, got '{part}'"
            )
        pairs.append((name.strip(), data_dir.strip()))
    if len(pairs) < 2:
        raise SystemExit("--mixture needs at least two name:dir entries")
    return pairs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.pack_dataset", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--dataset", help="registered source dataset")
    src.add_argument(
        "--mixture",
        help="comma-separated name:data_dir pairs packed into ONE "
        "directory with per-row source_id provenance",
    )
    ap.add_argument("--data-dir", default="", help="source dataset dir")
    ap.add_argument("--out", required=True)
    ap.add_argument("--shard-mb", type=float, default=512)
    ap.add_argument(
        "--samples-per-shard", type=int, default=0,
        help="explicit shard capacity (overrides --shard-mb)",
    )
    ap.add_argument(
        "--workers", type=int, default=0,
        help="shard-parallel pack processes (0/1 = serial)",
    )
    ap.add_argument(
        "--no-resume", action="store_true",
        help="rewrite every shard even when complete ones exist",
    )
    ap.add_argument(
        "--dtype", default="float32",
        choices=("float32", "fp32", "bfloat16", "bf16", "int8", "i8"),
        help="on-disk waveform dtype; bf16 halves shard bytes (and read "
        "bandwidth) for INFERENCE-ONLY archives — readers upcast to "
        "float32 on fill; int8 (format v3) quarters them with per-row "
        "per-channel max-abs scales in the index sidecar — readers "
        "dequantize on fill, the repick engine dequantizes ON DEVICE "
        "(docs/DATA.md). int8 and float packs cannot share a directory.",
    )
    ap.add_argument(
        "--dataset-kwargs", default="",
        help="JSON dict forwarded to the dataset constructor(s)",
    )
    args = ap.parse_args(argv)

    import seist_tpu
    from seist_tpu.data.packed import DtypeMixError, PackSource, pack_sources

    seist_tpu.load_all()
    ds_kwargs = json.loads(args.dataset_kwargs) if args.dataset_kwargs else {}
    if args.mixture:
        sources = [
            PackSource(name=name, data_dir=d, dataset_kwargs=ds_kwargs)
            for name, d in _parse_mixture(args.mixture)
        ]
    else:
        sources = [
            PackSource(
                name=args.dataset,
                data_dir=args.data_dir,
                dataset_kwargs=ds_kwargs,
            )
        ]
    try:
        stats = pack_sources(
            sources,
            args.out,
            num_workers=args.workers,
            samples_per_shard=args.samples_per_shard or None,
            shard_mb=args.shard_mb,
            resume=not args.no_resume,
            dtype=args.dtype,
        )
    except DtypeMixError as e:
        # Structured refusal (test-pinned): int8 v3 packs change the
        # index SCHEMA (scale sidecar), so they never share a directory
        # with float packs.
        print(json.dumps({
            "ok": False,
            "error": "dtype_mix",
            "existing_dtype": e.existing,
            "requested_dtype": e.requested,
            "out": e.out_dir,
            "detail": str(e),
        }))
        return 2
    stats["workers"] = args.workers
    print(json.dumps(stats))
    return 0


if __name__ == "__main__":
    sys.exit(main())
