"""Offline HDF5 -> packed-shard converter (SURVEY §7 input mitigation).

Repacks any registered dataset into seist_tpu.data.packed's contiguous
binary shards + columnar index, removing h5py's per-sample API cost from
the training read path (measured ~30% of per-sample loader cost in the
r3 stage budget). Run once per dataset; then train with
``--dataset-name packed --data-dir <out>``.

    python tools/pack_dataset.py --dataset diting_light \
        --data-dir /data/diting --out /data/diting_packed \
        [--shard-mb 512]

The source is constructed with ``data_split=False, shuffle=False`` so
the packed order is the source metadata order; the packed dataset then
applies the standard seeded shuffle/split itself — same seed => same
split as training on the source directly.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", required=True, help="registered source dataset")
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--shard-mb", type=int, default=512)
    args = ap.parse_args()

    import seist_tpu
    from seist_tpu.data.packed import pack_dataset
    from seist_tpu.registry import DATASETS

    seist_tpu.load_all()
    src = DATASETS.create(
        args.dataset,
        seed=0,
        mode="train",
        data_dir=args.data_dir,
        shuffle=False,
        data_split=False,
    )
    t0 = time.perf_counter()
    pack_dataset(src, args.out, shard_mb=args.shard_mb)
    print(
        f"packed {len(src)} events in {time.perf_counter() - t0:.1f}s "
        f"-> {args.out}"
    )


if __name__ == "__main__":
    main()
