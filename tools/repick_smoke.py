"""Repick kill/resume smoke: map-reduce catalog == serial catalog, bytes.

The ``make repick-smoke`` lane (docs/DATA.md "Batch re-picking"):

1. pack a synthetic archive (3 shards, a partial tail);
2. SERIAL reference: one in-process ``tools.repick_archive`` run ->
   ``catalog.jsonl`` bytes;
3. MAP-REDUCE run: two worker SUBPROCESSES over the same archive
   (``SEIST_FAULT_REPICK_SLOW_MS`` slows worker 0 so the kill lands
   mid-shard deterministically); worker 0 is SIGKILL'd after its first
   segment commit, relaunched (resume at the exact segment offset),
   then the reduce merges;
4. assert the merged catalog is BYTE-IDENTICAL to the serial one and
   that every worker's ``CompileBudget`` window after warm-up recorded
   ZERO compiles.

Prints ONE JSON verdict line; exit 0 iff every assertion held.
"""

from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

N_EVENTS = 44
TRACE = 256
SPS = 16  # 3 shards: 16 + 16 + 12 (partial tail unit)
BATCH = 4
BPC = 2  # batches per call -> 8 rows/call
COMMIT = 1  # one call per segment: several segments per unit


def _repick_args(archive: str, out: str):
    return [
        "--archive", archive, "--out", out, "--model", "phasenet",
        "--batch-size", str(BATCH), "--batches-per-call", str(BPC),
        "--commit-every", str(COMMIT),
    ]


def _worker_cmd(archive: str, out: str, index: int):
    return [
        sys.executable, "-m", "tools.repick_archive",
        *_repick_args(archive, out),
        "--worker-index", str(index), "--num-workers", "2",
        "--no-merge", "--compile-gate",
    ]


def main() -> int:
    from seist_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()
    import seist_tpu
    from seist_tpu.data.packed import PackSource, pack_sources

    seist_tpu.load_all()
    t0 = time.monotonic()
    root = tempfile.mkdtemp(prefix="repick_smoke_")
    archive = os.path.join(root, "archive")
    pack_sources(
        [PackSource(
            name="synthetic",
            dataset_kwargs={
                "num_events": N_EVENTS, "trace_samples": TRACE,
                "cache": False,
            },
        )],
        archive,
        samples_per_shard=SPS,
    )

    # --- serial reference ------------------------------------------------
    from tools.repick_archive import main as repick_main

    serial_out = os.path.join(root, "serial")
    rc = repick_main(_repick_args(archive, serial_out))
    assert rc == 0, f"serial repick rc={rc}"
    with open(os.path.join(serial_out, "catalog.jsonl"), "rb") as f:
        ref = f.read()

    # --- 2-worker map with a SIGKILL mid-shard ---------------------------
    mr_out = os.path.join(root, "mapreduce")
    env = dict(os.environ)
    env0 = dict(env, SEIST_FAULT_REPICK_SLOW_MS="300")  # kill lands mid-unit
    w0 = subprocess.Popen(_worker_cmd(archive, mr_out, 0), env=env0,
                          stdout=subprocess.PIPE, text=True)
    w1 = subprocess.Popen(_worker_cmd(archive, mr_out, 1), env=env,
                          stdout=subprocess.PIPE, text=True)

    # SIGKILL worker 0 as soon as its first segment commits (unit 0 has
    # 2 segments at this geometry, so the kill is mid-shard by
    # construction; the slow-call fault keeps it from finishing first).
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if glob.glob(os.path.join(mr_out, "unit_00000.seg_*.jsonl")):
            break
        if w0.poll() is not None:
            raise SystemExit("worker 0 exited before its first commit")
        time.sleep(0.02)
    else:
        raise SystemExit("worker 0 never committed a segment")
    w0.send_signal(signal.SIGKILL)
    w0.wait()
    killed_at = len(glob.glob(os.path.join(mr_out, "unit_00000.seg_*.jsonl")))
    out1, _ = w1.communicate(timeout=600)
    assert w1.returncode == 0, f"worker 1 rc={w1.returncode}"

    # Relaunch worker 0 WITHOUT the slow fault: resumes at its exact
    # segment offset and finishes.
    w0b = subprocess.Popen(_worker_cmd(archive, mr_out, 0), env=env,
                           stdout=subprocess.PIPE, text=True)
    out0, _ = w0b.communicate(timeout=600)
    assert w0b.returncode == 0, f"resumed worker 0 rc={w0b.returncode}"

    # --- reduce + asserts (model-free: geometry/identity from the plan
    # file, so no --model and deliberately NO geometry flags) -------------
    rc = repick_main(
        ["--archive", archive, "--out", mr_out, "--merge-only"]
    )
    assert rc == 0, f"merge rc={rc}"
    with open(os.path.join(mr_out, "catalog.jsonl"), "rb") as f:
        got = f.read()
    identical = got == ref

    def _verdict_line(text: str) -> dict:
        for line in reversed(text.strip().splitlines()):
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if d.get("role") == "worker":
                return d
        raise SystemExit(f"no worker verdict in output: {text[-400:]}")

    v0, v1 = _verdict_line(out0), _verdict_line(out1)
    compiles = v0.get("compiles_after_warmup", -1) + v1.get(
        "compiles_after_warmup", -1
    )
    resumed_skip = v0.get("segments_skipped", 0)
    verdict = {
        "ok": bool(
            identical
            and compiles == 0
            and v0["ok"] and v1["ok"]
        ),
        "byte_identical": identical,
        "rows": len(ref.splitlines()),
        "killed_after_segments": killed_at,
        "resumed_worker_segments": v0.get("segments", 0),
        "compiles_after_warmup": compiles,
        "wall_s": round(time.monotonic() - t0, 1),
        "out": mr_out,
    }
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
