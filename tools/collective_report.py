"""ICI collective payload of the dp=N train step at a given config.

Compiles (does NOT run) the full jitted train step for --model at --batch
over an N-device virtual CPU mesh and prints the per-step collective
payload read off the optimized HLO (seist_tpu.parallel.collectives).
Evidence for the multi-chip scaling argument: the DP payload is
batch-independent (gradient all-reduce = param bytes + BN batch-stats +
loss scalars), so a CPU compile at the reference batch documents exactly
what would ride the ICI links on a real v4-8/v5e-8 slice.

    python tools/collective_report.py [--model seist_l_dpk] [--batch 512]
        [--in-samples 8192] [--devices 8]

Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def attribute_collectives(ops, param_shapes, batch: int, devices: int) -> dict:
    """Bucket per-op collective payloads (VERDICT r3 #6 + advisor r4).

    Gradient reductions are all-reduces of param-shaped tensors inside
    the backward pass (op_name carries XLA's "transpose(jvp(...))"
    marker). Param-shaped all-reduces WITHOUT that marker land in
    ``unattributed`` (XLA's combiner can drop/merge metadata — silently
    misfiling them under bn_stat would claim ~0 gradient traffic);
    ``warn_unattributed`` is True when that bucket is nonzero while zero
    gradient ops were found, i.e. the unattributed bytes ARE the
    gradients. Batch-leading-dim collectives are activation traffic.
    """
    param_shapes = {tuple(s) for s in param_shapes}
    grad_bytes = grad_ops = act_bytes = act_ops = other_bytes = 0
    unattr_bytes = unattr_ops = 0
    per_shard_batch = batch // devices
    for op in ops:
        dims = op["shape_dims"]
        is_param_shaped_ar = op["kind"] == "all-reduce" and any(
            tuple(d) in param_shapes for d in dims
        )
        if is_param_shaped_ar and "transpose(jvp" in op["op_name"]:
            grad_bytes += op["bytes"]
            grad_ops += 1
        elif is_param_shaped_ar:
            # Checked BEFORE the batch-leading-dim heuristic so a param
            # with a batch-sized leading dim can't shadow it.
            unattr_bytes += op["bytes"]
            unattr_ops += 1
            other_bytes += op["bytes"]
        elif any(
            d and d[0] in (batch, per_shard_batch) and len(d) >= 2
            for d in dims
        ):
            act_bytes += op["bytes"]
            act_ops += 1
        else:
            other_bytes += op["bytes"]
    return {
        "grad_bytes": grad_bytes,
        "grad_ops": grad_ops,
        "act_bytes": act_bytes,
        "act_ops": act_ops,
        "other_bytes": other_bytes,
        "unattr_bytes": unattr_bytes,
        "unattr_ops": unattr_ops,
        "warn_unattributed": bool(grad_ops == 0 and unattr_bytes),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="seist_l_dpk")
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--in-samples", type=int, default=8192)
    ap.add_argument("--devices", type=int, default=8)
    args = ap.parse_args()

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.models import api
    from seist_tpu.parallel import (
        collective_stats,
        make_mesh,
    )
    from seist_tpu.train import (
        build_optimizer,
        create_train_state,
        jit_step,
        make_train_step,
    )

    seist_tpu.load_all()
    mesh = make_mesh(data=args.devices)
    model = api.create_model(args.model, in_samples=args.in_samples)
    variables = api.init_variables(
        model, in_samples=args.in_samples, batch_size=2
    )
    state = create_train_state(
        model, variables, build_optimizer("adam", 1e-3)
    )
    n_params = sum(
        x.size for x in jax.tree.leaves(state.params)
    )

    spec = taskspec.get_task_spec(args.model)
    loss_fn = taskspec.make_loss(args.model)
    step = jit_step(make_train_step(spec, loss_fn), mesh=mesh)

    # Abstract lowering: ShapeDtypeStructs — no batch-sized buffers exist.
    x_s = jax.ShapeDtypeStruct(
        (args.batch, args.in_samples, len(spec.inputs[0])
         if isinstance(spec.inputs[0], (list, tuple)) else 3),
        jnp.float32,
    )
    # Label struct mirrors the train batch the worker builds.
    y_shape = jax.eval_shape(
        lambda v, x: model.apply(v, x, train=False), variables,
        jax.ShapeDtypeStruct((args.batch, args.in_samples, 3), jnp.float32),
    )
    y_s = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, jnp.float32), y_shape
    )
    rng_s = jax.ShapeDtypeStruct((2,), jnp.uint32)
    # state is tiny (<1M params) — lower with the concrete pytree; only the
    # batch-sized inputs need to stay abstract.

    t0 = time.time()
    compiled = step.lower(state, x_s, y_s, rng_s).compile()
    from seist_tpu.parallel.collectives import collective_ops

    hlo = compiled.as_text()
    stats = collective_stats(hlo)
    ops = collective_ops(hlo)
    total = sum(s["bytes"] for s in stats.values())
    n = args.devices

    # Attribute the bytes (VERDICT r3 #6: make it self-evident which ops
    # carry the gradient bytes). Gradient reductions are all-reduces of
    # param-shaped tensors INSIDE the backward pass (op_name metadata
    # carries XLA's "transpose(jvp(...))" marker); BN cross-replica
    # batch-stat sums are also (C,)-shaped all-reduces — same shapes as
    # BN scale/bias params — but sit in the forward, so the op_name test
    # keeps them out of the gradient bucket. Collectives with a
    # batch-sized leading dim are activation traffic and scale WITH
    # batch; the rest is BN batch-stats + loss scalars.
    param_shapes = {
        tuple(np.shape(x)) for x in jax.tree.leaves(state.params)
    }
    buckets = attribute_collectives(ops, param_shapes, args.batch, n)
    grad_bytes, grad_ops = buckets["grad_bytes"], buckets["grad_ops"]
    act_bytes, act_ops = buckets["act_bytes"], buckets["act_ops"]
    other_bytes = buckets["other_bytes"]
    unattr_bytes, unattr_ops = buckets["unattr_bytes"], buckets["unattr_ops"]
    if buckets["warn_unattributed"]:
        print(
            "WARNING: no all-reduce carries the transpose(jvp) gradient "
            f"marker, but {unattr_ops} param-shaped all-reduce op(s) "
            f"({unattr_bytes / 1e6:.3f} MB) exist — XLA likely dropped "
            "op_name metadata when combining; treat unattributed_allreduce "
            "as the gradient bucket.",
            file=sys.stderr,
        )
    print(
        json.dumps(
            {
                "metric": "dp_train_step_collective_payload",
                "value": round(total / 1e6, 3),
                "unit": "MB/step payload",
                "model": args.model,
                "batch": args.batch,
                "in_samples": args.in_samples,
                "devices": n,
                "per_kind": stats,
                "param_bytes_mb": round(n_params * 4 / 1e6, 3),
                "gradient_allreduce": {
                    "ops": grad_ops,
                    "mb": round(grad_bytes / 1e6, 3),
                    "note": (
                        "backward-pass (transpose(jvp)) all-reduce ops "
                        "with param-shaped tuple elements == the fp32 "
                        "gradient bytes; batch-independent"
                    ),
                },
                "activation_collectives": {
                    "ops": act_ops,
                    "mb": round(act_bytes / 1e6, 3),
                    "note": (
                        "batch-leading-dim buffers (backward-pass "
                        "activation gathers); scales WITH batch"
                    ),
                },
                "bn_stat_and_scalar_collectives_mb": round(
                    other_bytes / 1e6, 3
                ),
                "unattributed_allreduce": {
                    "ops": unattr_ops,
                    "mb": round(unattr_bytes / 1e6, 3),
                    "note": (
                        "param-shaped all-reduces WITHOUT the "
                        "transpose(jvp) marker (also included in the "
                        "bn_stat bucket); nonzero while gradient ops==0 "
                        "means XLA dropped combiner metadata and these "
                        "ARE the gradient bytes"
                    ),
                },
                "ring_allreduce_link_traffic_mb": round(
                    total * 2 * (n - 1) / n / 1e6, 3
                ),
                "compile_s": round(time.time() - t0, 1),
                "note": (
                    "payload bytes from optimized HLO (static counts; DP "
                    "step has no loop-carried collectives). Link traffic "
                    "per chip for ring all-reduce = 2(N-1)/N x payload."
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
