"""Batch re-picking throughput headline vs the serving path -> BENCH JSON.

The ISSUE-15 acceptance number: waveforms/sec/chip for the straight-line
batch engine (tools/repick_archive.py over a packed archive) must be
>= 5x the serve-path per-chip throughput (tools/bench_serve.py, same
model, same host, same window) — the whole point of a dedicated batch
plane is that an archive re-pick must never ride the request path.

Both measurements run in-process on the same device:

* **batch** — pack a synthetic archive, run the inline map-reduce
  (``tools.repick_archive`` verbatim — the measured path IS the shipped
  tool), read the worker verdict's ``waveforms_per_sec`` + per-stage
  budget (fill / device / decode / write, the ``step_breakdown`` idiom);
* **serve** — ``tools.bench_serve`` closed-loop against the in-process
  service (micro-batcher + AOT programs + per-request decode), read
  ``throughput_rps`` (one waveform per request).

Writes ``BENCH_repick_r01.json``-style output (--out) and prints it.
Exit 0 iff the >= --min-speedup gate holds.
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import sys
import tempfile
import time


def _last_json(text: str, role=None) -> dict:
    for line in reversed(text.strip().splitlines()):
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if role is None or d.get("role") == role:
            return d
    raise SystemExit(f"no JSON verdict found in: {text[-400:]}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.bench_repick")
    ap.add_argument("--model", default="phasenet")
    ap.add_argument("--events", type=int, default=1024)
    ap.add_argument("--trace", type=int, default=256,
                    help="archive window length (= model window)")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--batches-per-call", type=int, default=4)
    ap.add_argument("--serve-requests", type=int, default=64)
    ap.add_argument("--serve-concurrency", type=int, default=8)
    ap.add_argument("--serve-max-batch", type=int, default=8)
    ap.add_argument("--min-speedup", type=float, default=5.0)
    ap.add_argument("--out", default="BENCH_repick_r01.json")
    args = ap.parse_args(argv)

    from seist_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()
    import jax

    import seist_tpu
    from seist_tpu.data.packed import PackSource, pack_sources

    seist_tpu.load_all()
    root = tempfile.mkdtemp(prefix="bench_repick_")
    archive = os.path.join(root, "archive")
    pack_sources(
        [PackSource(
            name="synthetic",
            dataset_kwargs={
                "num_events": args.events, "trace_samples": args.trace,
                "cache": False,
            },
        )],
        archive,
        samples_per_shard=max(args.events // 4, 1),
    )

    # --- batch path (the shipped tool, inline) ---------------------------
    from tools.repick_archive import main as repick_main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = repick_main([
            "--archive", archive, "--out", os.path.join(root, "catalog"),
            "--model", args.model,
            "--batch-size", str(args.batch_size),
            "--batches-per-call", str(args.batches_per_call),
            "--compile-gate",
        ])
    if rc != 0:
        print(buf.getvalue())
        raise SystemExit(f"repick run failed rc={rc}")
    worker = _last_json(buf.getvalue(), role="worker")

    # --- serve path (same model/window/host) -----------------------------
    from tools.bench_serve import main as bench_serve_main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = bench_serve_main([
            "--model-name", args.model, "--window", str(args.trace),
            "--requests", str(args.serve_requests),
            "--concurrency", str(args.serve_concurrency),
            "--max-batch", str(args.serve_max_batch),
        ])
    if rc not in (0, None):
        print(buf.getvalue())
        raise SystemExit(f"bench_serve failed rc={rc}")
    serve = _last_json(buf.getvalue())

    batch_wfs = float(worker["waveforms_per_sec"])
    serve_rps = float(serve.get("throughput_rps", 0.0))
    speedup = batch_wfs / serve_rps if serve_rps else float("inf")
    result = {
        "metric": f"{args.model}_repick_throughput",
        "value": round(batch_wfs, 2),
        "unit": "waveforms/sec/chip",
        "serve_baseline_rps": round(serve_rps, 2),
        "speedup_vs_serve": round(speedup, 2),
        "gate_min_speedup": args.min_speedup,
        "step_breakdown": {
            "stage_seconds": worker["stage_seconds"],
            "stage_ms_per_wf": worker.get("stage_ms_per_wf", {}),
        },
        "compiles_after_warmup": worker.get("compiles_after_warmup"),
        "aot_program": worker.get("warmup_program"),
        "aot_compile_ms": worker.get("warmup_compile_ms"),
        "config": {
            "model": args.model,
            "events": args.events,
            "window": args.trace,
            "batch": args.batch_size,
            "batches_per_call": args.batches_per_call,
            "serve_requests": args.serve_requests,
            "serve_concurrency": args.serve_concurrency,
            "serve_max_batch": args.serve_max_batch,
            "serve_p50_ms": serve.get("p50_ms"),
            "serve_p99_ms": serve.get("p99_ms"),
        },
        "device": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
        "measured_at": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
        "pass": speedup >= args.min_speedup,
    }
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
