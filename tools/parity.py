"""torch -> flax checkpoint converter for golden-parity testing.

One-shot tooling (NOT in the product path, per SURVEY.md §7.9): maps the
reference's shipped ``pretrained/*.pth`` state-dicts (raw tensors, torch
layout) onto this framework's flax variable tree so the same weights can be
forward-compared. The reference's SeisT param naming
(models/seist.py:613-852) and ours were designed to correspond 1:1:

    stem.{i}.*                    -> params/stem{i}/*
    encoder_layers.{i}.0.*        -> params/stage{i}_aggr/*
    encoder_layers.{i}.{j+1}.*    -> params/stage{i}_block{j}/*
    out_head.up_layers.{i}.conv   -> params/out_head/conv{i}   (+ norm{i})
    out_head.out_conv / linear    -> params/out_head/...
    convs.{k}/norms.{k}/projs.{k} -> conv{k}/norm{k}/proj{k}

Per-leaf layout transforms are shape-driven:
    torch Conv1d  (out, in/g, k) -> flax Conv kernel (k, in/g, out)
    torch Linear / 1x1 Conv1d    -> flax Dense kernel (in, out)
    BatchNorm weight/bias        -> params .../scale, .../bias
    BatchNorm running_mean/var   -> batch_stats .../mean, .../var
    num_batches_tracked          -> dropped
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

import numpy as np

_BN_MAP = {
    "weight": ("params", "scale"),
    "bias": ("params", "bias"),
    "running_mean": ("batch_stats", "mean"),
    "running_var": ("batch_stats", "var"),
}
_BN_LEAVES = set(_BN_MAP) | {"num_batches_tracked"}


# torch nn.ModuleList/Sequential list-module name -> flax per-index prefix
# (list.{k}.* -> prefix{k}/*). One table instead of one elif per model family;
# reference anchors: eqtransformer.py:269-614 (res_convs/bilstms/transformers/
# decoders/upsamplings), ditingmotion.py:174-335 (blocks/side/fuse lists),
# distpt_network.py:37-135 (conv_blocks), phasenet.py:152-267 (down/up_convs).
_LIST_MODULES = {
    "blocks": "block",
    "conv_blocks": "block",  # distPT TCN residual blocks live under tcn/
    "clarity_side_layers": "clarity_side",
    "polarity_side_layers": "polarity_side",
    "fuse_clarity": "fuse_clarity",
    "fuse_polarity": "fuse_polarity",
    "res_convs": "resconv",
    "bilstms": "bilstm",
    "transformers": "transformer",
    "decoders": "decoder",
    "upsamplings": "up",
    "down_convs": "down",
    "up_convs": "up",
}


def torch_key_to_flax(key: str) -> Optional[Tuple[str, Tuple[str, ...]]]:
    """Map one torch state-dict key to (collection, flax path) or None to skip."""
    parts = key.split(".")
    leaf = parts[-1]
    parent = parts[-2] if len(parts) > 1 else ""

    # Norm leaves re-route by collection. Norm modules are named "norm",
    # "norm{k}", "out_norm", or live in a "norms.{k}" list.
    collection = "params"
    if parent.isdigit():
        norm_parent = len(parts) > 2 and parts[-3] == "norms"
    else:
        # "norm"/"norm{k}"/"out_norm" (seist) or "bn{k}"/"bn_in" (phasenet).
        norm_parent = (
            parent == "out_norm"
            or bool(re.fullmatch(r"norm\d*", parent))
            or bool(re.fullmatch(r"bn\w*", parent))
            # EQTransformer LayerNorms (ln0/ln1): weight -> scale, and LN has
            # no running stats so only the params-collection entries fire.
            or bool(re.fullmatch(r"ln\d*", parent))
        )
    is_norm_leaf = leaf in _BN_LEAVES and bool(norm_parent)
    if leaf == "num_batches_tracked":
        return None
    if is_norm_leaf:
        collection, leaf = _BN_MAP[leaf]
    elif leaf == "weight":
        leaf = "kernel"

    out: list = []
    i = 0
    while i < len(parts) - 1:
        p = parts[i]
        if p == "stem":
            out.append(f"stem{parts[i + 1]}")
            i += 2
        elif p == "encoder_layers":
            stage, blk = int(parts[i + 1]), int(parts[i + 2])
            out.append(
                f"stage{stage}_aggr" if blk == 0 else f"stage{stage}_block{blk - 1}"
            )
            i += 3
        elif p == "out_head":
            out.append("out_head")
            i += 1
        elif p == "up_layers":
            # up_layers.{k}.conv -> conv{k}; up_layers.{k}.norm -> norm{k}
            k = parts[i + 1]
            nxt = parts[i + 2]
            out.append(f"{nxt}{k}")
            i += 3
        elif (
            p in ("convs", "norms", "projs")
            and i + 1 < len(parts)
            and parts[i + 1].isdigit()
        ):
            out.append(f"{p[:-1]}{parts[i + 1]}")
            i += 2
            # DiTingMotion's CombConvLayer wraps each conv in a Sequential
            # (convs.{a}.0.weight, ref ditingmotion.py:38-80); swallow the
            # position index. Only '.0' (the conv) carries params — any other
            # slot stays unswallowed so it fails loudly as an unmapped key
            # instead of silently overwriting conv{a}.
            if i < len(parts) - 1 and parts[i] == "0":
                i += 1
        elif p == "conv_layers" and i + 1 < len(parts) and parts[i + 1].isdigit():
            # Context-dependent: inside a DiTingMotion block (block{n} just
            # emitted) conv_layers.{j} is the j-th CombConvLayer -> comb{j}
            # (ref ditingmotion.py:83-117); at MagNet's top level it is the
            # j-th ConvBlock -> conv{j} (ref magnet.py:36-61).
            kind = "comb" if (out and out[-1].startswith("block")) else "conv"
            out.append(f"{kind}{parts[i + 1]}")
            i += 2
        elif (
            p in _LIST_MODULES
            and i + 1 < len(parts)
            and parts[i + 1].isdigit()
        ):
            out.append(f"{_LIST_MODULES[p]}{parts[i + 1]}")
            i += 2
        elif (
            p == "layers"
            and i + 2 < len(parts)
            and parts[i + 1].isdigit()
            and parts[i + 2] == "0"
        ):
            # BAZ-Network wave branch: layers.{k} is Sequential(conv, act) —
            # only slot 0 (the conv) has params -> wave_conv{k}
            # (ref baz_network.py:17-121).
            out.append(f"wave_conv{parts[i + 1]}")
            i += 3
        else:
            out.append(p)
            i += 1
    out.append(leaf)
    return collection, tuple(out)


def _fit_leaf(value: np.ndarray, target_shape: Tuple[int, ...], key: str) -> np.ndarray:
    """Layout-transform a torch tensor to the flax leaf shape.

    The transform is decided by the tensors' RANKS, never by a shape match:
    a square Linear (in==out) or a conv with out_channels==kernel_size is
    coincidentally target-shaped untransposed, and an equality early-return
    would silently convert it wrong. Every 2-D/3-D torch weight needs its
    transpose; only 1-D vectors pass through.
    """
    v = np.asarray(value)
    leaf_name = key.split(".")[-1]
    if leaf_name in ("Wx", "Wt", "Wa"):
        # EQTransformer additive-attention weights are raw nn.Parameters
        # used as x @ W on BOTH sides (ref eqtransformer.py:135-198, ours
        # models/eqtransformer.py AttentionLayer) — same orientation, no
        # transpose.
        t = v
    elif v.ndim <= 1:
        t = v
    elif ".convt." in f".{key}." and v.ndim == 3:
        # torch ConvTranspose1d (in,out,k) -> flax ConvTranspose kernel
        # (k,in,out) with the spatial axis FLIPPED (verified empirically:
        # flax's conv_transpose does not flip, torch's semantics do).
        t = v.transpose(2, 0, 1)[::-1]
    elif len(target_shape) == 3 and v.ndim == 3:
        t = v.transpose(2, 1, 0)  # (out,in,k) -> (k,in,out)
    elif len(target_shape) == 2:
        if v.ndim == 3 and v.shape[-1] == 1:
            v = v[:, :, 0]  # 1x1 Conv1d used as a Linear
        t = v.T  # (out,in) -> (in,out)
    else:
        t = v
    if tuple(t.shape) != tuple(target_shape):
        raise ValueError(
            f"Cannot fit '{key}' {np.asarray(value).shape} into flax leaf "
            f"{target_shape}"
        )
    return t


_LSTM_LEAF_RE = re.compile(r"(weight|bias)_(ih|hh)_l0(_reverse)?")


def collect_lstm_leaf(
    path: Tuple[str, ...],
    value: np.ndarray,
    groups: Dict[Tuple[Tuple[str, ...], str], Dict[str, np.ndarray]],
) -> bool:
    """If ``path`` (a mapped flax path) ends in a torch fused-LSTM leaf,
    stash it in ``groups`` keyed by (module prefix, direction) for
    :func:`_convert_lstm_group` and return True; else return False. Shared
    by convert_state_dict and the gradient-parity test so the grouping
    rules live in one place."""
    m = _LSTM_LEAF_RE.fullmatch(path[-1])
    if not m:
        return False
    direction = "bwd" if m.group(3) else "fwd"
    groups.setdefault((path[:-1], direction), {})[
        f"{m.group(1)}_{m.group(2)}"
    ] = np.asarray(value)
    return True


def _convert_lstm_group(
    prefix: Tuple[str, ...],
    direction: str,
    leaves: Dict[str, np.ndarray],
    flat_target: Dict[Tuple[str, Tuple[str, ...]], Tuple[int, ...]],
) -> Dict[Tuple[str, Tuple[str, ...]], np.ndarray]:
    """torch nn.LSTM -> flax OptimizedLSTMCell leaves.

    torch fuses the four gates as (4H, *) rows in order [i, f, g, o]
    and carries TWO bias vectors (bias_ih + bias_hh); flax's
    OptimizedLSTMCell keeps per-gate Dense layers — input kernels
    ``i{g}`` (no bias) and recurrent kernels ``h{g}`` (with bias), so the
    flax bias is the SUM of torch's two (they are always added together in
    the gate preactivation). BiLSTM directions map to the fwd/bwd
    submodules (ours models/common.py::BiLSTM); the `_reverse` suffix is
    torch's backward direction.
    """
    cell = "OptimizedLSTMCell_0"
    cand_a = prefix + (direction, cell)
    cand_b = prefix + (cell,)
    if prefix and prefix[-1] == "lstm" and not any(
        ("params", prefix + tail + ("ii", "kernel")) in flat_target
        for tail in ((direction, cell), (cell,))
    ):
        # MagNet names its torch module `lstm` but it is bidirectional and
        # ours is named `bilstm` (models/magnet.py); retarget the prefix.
        alt = prefix[:-1] + ("bilstm",)
        if ("params", alt + (direction, cell, "ii", "kernel")) in flat_target:
            prefix = alt
            cand_a = prefix + (direction, cell)
            cand_b = prefix + (cell,)
    if ("params", cand_a + ("ii", "kernel")) in flat_target:
        base = cand_a
    elif ("params", cand_b + ("ii", "kernel")) in flat_target:
        if direction == "bwd":
            raise KeyError(
                f"reverse LSTM weights for {'/'.join(prefix)} but the flax "
                "module is unidirectional"
            )
        base = cand_b
    else:
        raise KeyError(f"no flax LSTM cell found under {'/'.join(prefix)}")

    required = {"weight_ih", "weight_hh", "bias_ih", "bias_hh"}
    if set(leaves) != required:
        raise KeyError(
            f"incomplete torch LSTM group {'/'.join(prefix)} ({direction}): "
            f"{sorted(leaves)}"
        )

    out: Dict[Tuple[str, Tuple[str, ...]], np.ndarray] = {}
    gates = "ifgo"
    w_ih = np.split(leaves["weight_ih"], 4, axis=0)
    w_hh = np.split(leaves["weight_hh"], 4, axis=0)
    b = np.split(leaves["bias_ih"] + leaves["bias_hh"], 4, axis=0)
    for k, g in enumerate(gates):
        for path, val in (
            (base + (f"i{g}", "kernel"), w_ih[k].T),
            (base + (f"h{g}", "kernel"), w_hh[k].T),
            (base + (f"h{g}", "bias"), b[k]),
        ):
            tgt = flat_target.get(("params", path))
            if tgt is None:
                raise KeyError(f"unknown flax LSTM leaf {'/'.join(path)}")
            if tuple(val.shape) != tuple(tgt):
                raise ValueError(
                    f"LSTM leaf {'/'.join(path)}: {val.shape} != {tgt}"
                )
            out[("params", path)] = val
    return out


def convert_state_dict(
    state_dict: Dict[str, Any], flax_variables: Dict[str, Any]
) -> Dict[str, Any]:
    """Convert a torch state-dict into {'params', 'batch_stats'} matching
    ``flax_variables``'s tree. Raises on unmapped or missing leaves."""
    import jax

    flat_target = {}
    for coll in ("params", "batch_stats"):
        if coll not in flax_variables:
            continue
        leaves = jax.tree_util.tree_flatten_with_path(flax_variables[coll])[0]
        for path, leaf in leaves:
            key = tuple(str(k.key) for k in path)
            flat_target[(coll, key)] = np.shape(leaf)

    converted: Dict[Tuple[str, Tuple[str, ...]], np.ndarray] = {}
    lstm_groups: Dict[Tuple[Tuple[str, ...], str], Dict[str, np.ndarray]] = {}
    for tkey, tval in state_dict.items():
        mapped = torch_key_to_flax(tkey)
        if mapped is None:
            continue
        coll, path = mapped
        # torch nn.LSTM fused leaves -> collected per (module, direction)
        # and split into flax OptimizedLSTMCell gates below.
        val = tval.detach().cpu().numpy() if hasattr(tval, "detach") else tval
        if collect_lstm_leaf(path, val, lstm_groups):
            continue
        if (coll, path) not in flat_target:
            raise KeyError(
                f"torch key '{tkey}' mapped to unknown flax leaf {coll}/{'/'.join(path)}"
            )
        converted[(coll, path)] = _fit_leaf(
            tval.detach().cpu().numpy() if hasattr(tval, "detach") else tval,
            flat_target[(coll, path)],
            tkey,
        )

    for (prefix, direction), leaves in lstm_groups.items():
        converted.update(
            _convert_lstm_group(prefix, direction, leaves, flat_target)
        )

    missing = set(flat_target) - set(converted)
    if missing:
        raise KeyError(f"flax leaves not covered by checkpoint: {sorted(missing)[:8]}")

    out: Dict[str, Any] = {"params": {}, "batch_stats": {}}
    for (coll, path), val in converted.items():
        node = out[coll]
        for piece in path[:-1]:
            node = node.setdefault(piece, {})
        node[path[-1]] = val
    if not out["batch_stats"]:
        out.pop("batch_stats")
    return out


def load_reference_checkpoint(model_name: str, dataset: str = "diting"):
    """Load a shipped reference checkpoint and convert it for our model."""
    import torch

    from seist_tpu.models import api

    path = f"/root/reference/pretrained/{model_name}_{dataset}.pth"
    sd = torch.load(path, map_location="cpu", weights_only=True)
    model = api.create_model(model_name, in_samples=8192)
    shapes = api.param_shapes(model, in_samples=8192)
    return model, convert_state_dict(sd, shapes)
