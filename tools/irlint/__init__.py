"""irlint — IR-level static analysis of the repo's *lowered* programs.

Third analyzer of the jaxlint/threadlint family (shared engine frontend,
rationale-required suppressions, line-shift-proof baseline, ``make lint``
gate) whose unit of analysis is a lowered XLA program, not a source file:
a program manifest (tools/irlint/manifest.py) enumerates every jit
boundary the repo ships, lowers each from ``eval_shape``-derived avals
(no weights, no device execution) and walks the jaxpr/StableHLO with the
rule catalog in tools/irlint/rules.py. See docs/STATIC_ANALYSIS.md
"IR-level analysis".
"""
