"""The irlint program manifest: every jit boundary the repo ships,
lowered from ``eval_shape``-derived avals.

No checkpoints, no weights, no device execution: model variables come
from ``models/api.param_shapes`` (an ``eval_shape`` of flax init),
optimizer state from an ``eval_shape`` of ``create_train_state``, and
batch/target avals from ONE synthetic-dataset sample lifted to a batch
of ShapeDtypeStructs. Lowering is then pure tracing — the exact programs
XLA would compile, at zero device cost.

Programs enumerated (the serve table mirrors ``ModelPool.warmup``; the
train table the worker's dispatch in ``train/worker.py``):

* ``train/step.py`` — ``jit_step`` / ``jit_multi_step`` /
  ``jit_device_aug_step`` / ``jit_cached_call``, lowered through the
  REAL jit wrappers (donation resolution via ``resolve_donation``
  included, so the donation audit sees what actually ships);
* ``serve/aot.py`` — the AOT executable table: single-task full
  forwards and group trunk + per-task head programs, per warm bucket x
  variant, with variant weight transforms applied at the aval level
  (bf16 leaves / int8+scale packing) so the analyzed program holds the
  same weights-at-rest as the shipped executable;
* ``ops/stream.py`` — the ``annotate`` device chain
  (stitch + pick + detect).

Findings anchor to each program's REGISTRATION SITE (the ``def`` line of
the jit wrapper / warm-up builder that ships it), so suppressions and
baseline keys live in real source files like the sibling analyzers'.
"""

from __future__ import annotations

import inspect
import os
import sys
from dataclasses import dataclass, field
from functools import cached_property
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def ensure_cpu_backend() -> None:
    """Force the CPU backend for analysis runs (lowering needs no
    accelerator, and touching the TPU tunnel from a lint gate can hang
    for minutes). Must run BEFORE the first jax import; a no-op when jax
    is already imported (pytest's conftest owns the config there)."""
    if "jax" in sys.modules:
        return
    # FORCE-assign, don't setdefault: an exported JAX_PLATFORMS=tpu (the
    # usual tunnel setup on this repo) would otherwise route the lint
    # gate into TPU backend init — minutes of hang when the tunnel is
    # down, the exact failure this pin exists to prevent. An explicit
    # SEIST_IRLINT_BACKEND wins for anyone who really wants on-device
    # lowering.
    os.environ["JAX_PLATFORMS"] = os.environ.get(
        "SEIST_IRLINT_BACKEND", "cpu"
    )
    if os.environ["JAX_PLATFORMS"] == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            # A multi-device mesh is what the replication audit audits.
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax

    # The environment may register a TPU backend at interpreter start
    # (sitecustomize); the config update wins over it.
    if os.environ["JAX_PLATFORMS"] == "cpu":
        jax.config.update("jax_platforms", "cpu")


# ------------------------------------------------------------------- sites
@dataclass(frozen=True)
class SiteRef:
    """Where a program is registered in source — the finding anchor."""

    file: str  # posix relpath from repo root
    line: int
    text: str  # stripped def line (the baseline identity)


def site_of(obj: Any) -> SiteRef:
    src = inspect.getsourcefile(obj)
    lines, lineno = inspect.getsourcelines(obj)
    rel = os.path.relpath(os.path.abspath(src), _REPO_ROOT).replace(
        os.sep, "/"
    )
    text = ""
    for ln in lines:
        s = ln.strip()
        if s.startswith(("def ", "class ")):
            text = s
            break
    return SiteRef(file=rel, line=lineno, text=text or lines[0].strip())


# ---------------------------------------------------------------- programs
@dataclass
class ProgramSpec:
    """One manifest entry: a traceable fn + its abstract args + the
    metadata the rule catalog keys on."""

    key: str  # e.g. "serve/seist_s/trunk/b4/bf16"
    kind: str  # "train" | "serve" | "stream"
    site: SiteRef
    fn: Callable  # unjitted body (jaxpr walks)
    args: Tuple[Any, ...]  # ShapeDtypeStruct pytrees, one per positional
    policy: str = "fp32"  # declared compute dtype of the matmul FLOPs
    coverage_min: float = 0.9
    donate_intent: Tuple[int, ...] = ()  # what the repo WANTS donated
    donate: Tuple[int, ...] = ()  # what resolve_donation actually grants
    jitted: Optional[Callable] = None  # shipped jit wrapper (for .lower)
    mesh_size: int = 1
    data_argnums: Tuple[int, ...] = ()  # args expected batch-sharded
    bucket: Optional[int] = None  # serve batch bucket
    ladder: Optional[Tuple[int, ...]] = None  # full bucket ladder
    notes: Dict[str, Any] = field(default_factory=dict)


class ProgramInfo:
    """A ProgramSpec plus lazily-computed IR views. Tracing happens at
    most twice per program (jaxpr walk + stablehlo lowering), and only
    for the views a rule actually requests."""

    def __init__(self, spec: ProgramSpec):
        self.spec = spec
        self.report: Dict[str, Any] = {
            "kind": spec.kind,
            "policy": spec.policy,
            "site": f"{spec.site.file}:{spec.site.line}",
        }

    @cached_property
    def jaxpr(self):
        import jax

        return jax.make_jaxpr(self.spec.fn)(*self.spec.args)

    @cached_property
    def lowered(self):
        import jax

        jitted = self.spec.jitted
        if jitted is not None:
            # train/step.py wrappers hide the jit behind _first_call_span;
            # @wraps exposes it as __wrapped__ — unwrap until something
            # lowerable appears, so the analysis keeps the SHIPPED
            # donate/in_shardings configuration. (A raw jax.jit function
            # also has __wrapped__ — the original python fn — so unwrap
            # only while .lower is missing.)
            while not hasattr(jitted, "lower") and hasattr(
                jitted, "__wrapped__"
            ):
                jitted = jitted.__wrapped__
        else:
            jitted = jax.jit(
                self.spec.fn, donate_argnums=self.spec.donate
            )
        return jitted.lower(*self.spec.args)

    @cached_property
    def stablehlo(self) -> str:
        return self.lowered.as_text()

    @property
    def kept_var_idx(self) -> Optional[List[int]]:
        """Original flat-arg indices the lowering KEPT (jit prunes unused
        args by default, shifting every ``%argN`` after a pruned one) —
        the alignment key for mapping declared argnums onto the lowered
        ``@main`` signature. None = unknown, assume nothing pruned."""
        try:
            kept = self.lowered._lowering.compile_args.get("kept_var_idx")
        except AttributeError:
            return None
        return sorted(kept) if kept is not None else None


# ------------------------------------------------------------ struct utils
def _lift_batch(sample: Any, batch: int):
    """One host sample pytree -> a batch of ShapeDtypeStructs, with the
    x64 host dtypes narrowed exactly like ``jnp.asarray`` under the
    default x64-disabled config."""
    import jax
    import numpy as np

    def lift(x):
        a = np.asarray(x)
        dt = {
            np.dtype(np.float64): np.dtype(np.float32),
            np.dtype(np.int64): np.dtype(np.int32),
        }.get(a.dtype, a.dtype)
        return jax.ShapeDtypeStruct((batch,) + a.shape, dt)

    return jax.tree.map(lift, sample)


def _structs_of(tree: Any):
    import jax

    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree
    )


def variant_structs(var_structs: Any, variant: str):
    """Aval-level mirror of serve/aot.py's weight transforms: the
    analyzed program must hold the same weights-at-rest as the shipped
    executable (bf16 leaves for the bf16 variant; int8 + per-out-channel
    scale packing for int8)."""
    import jax
    import jax.numpy as jnp

    from seist_tpu.serve import aot

    if variant == "fp32":
        return var_structs
    if variant == "bf16":
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, jnp.bfloat16)
            if jnp.issubdtype(s.dtype, jnp.floating)
            else s,
            var_structs,
        )
    if variant == "int8":
        from typing import Mapping

        def pack(tree):
            if isinstance(tree, Mapping):
                return {k: pack(v) for k, v in tree.items()}
            if (
                jnp.issubdtype(tree.dtype, jnp.floating)
                and len(tree.shape) >= 2
            ):
                return {
                    aot._INT8_MARK: jax.ShapeDtypeStruct(
                        tree.shape, jnp.int8
                    ),
                    "scale": jax.ShapeDtypeStruct(
                        tree.shape[-1:], jnp.float32
                    ),
                }
            return tree

        return pack(var_structs)
    raise ValueError(f"unknown variant {variant!r}")


def _rng_struct():
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct((2,), jnp.uint32)


def _scalar(dtype):
    import jax

    return jax.ShapeDtypeStruct((), dtype)


# ------------------------------------------------------------ model pieces
class _ModelCtx:
    """Shared per-model construction: model object, variable avals,
    abstract train state, one synthetic (inputs, targets) sample."""

    def __init__(self, model_name: str, window: int):
        from seist_tpu import taskspec
        from seist_tpu.models import api

        self.name = model_name
        self.window = int(window)
        self.spec = taskspec.get_task_spec(model_name)
        self.loss_fn = taskspec.make_loss(model_name)
        self.in_channels = taskspec.get_num_inchannels(model_name)
        self.model = api.create_model(
            model_name, in_channels=self.in_channels, in_samples=self.window
        )
        self.var_structs = api.param_shapes(
            self.model, in_samples=self.window, in_channels=self.in_channels
        )

    @cached_property
    def state_structs(self):
        import jax

        from seist_tpu.train import build_optimizer
        from seist_tpu.train.state import create_train_state

        tx = build_optimizer("adam", 1e-3)
        return jax.eval_shape(
            lambda v: create_train_state(self.model, v, tx),
            self.var_structs,
        )

    @cached_property
    def _sample(self):
        from seist_tpu.data import pipeline as pl

        sds = pl.from_task_spec(
            self.spec,
            "synthetic",
            "train",
            seed=0,
            in_samples=self.window,
            augmentation=False,
            data_split=False,
            shuffle=False,
            dataset_kwargs={
                "num_events": 2,
                "trace_samples": max(self.window + 64, 256),
            },
        )
        inputs, targets, _, _ = sds[0]
        return inputs, targets

    def batch_structs(self, batch: int):
        inputs, targets = self._sample
        return _lift_batch(inputs, batch), _lift_batch(targets, batch)

    def x_struct(self, batch: int):
        import jax
        import jax.numpy as jnp

        return jax.ShapeDtypeStruct(
            (batch, self.window, self.in_channels), jnp.float32
        )


# -------------------------------------------------------- train programs
def _mesh():
    from seist_tpu.parallel import mesh as mesh_lib

    return mesh_lib.make_mesh()


def train_programs(
    model_name: str = "phasenet",
    *,
    compute_dtype: Optional[str] = None,
    window: int = 512,
    batch: int = 8,  # divisible by the analysis mesh's data axis
    steps_per_call: int = 2,
    include: Sequence[str] = ("step", "multi_step"),
    guard: bool = True,
) -> List[ProgramSpec]:
    """``jit_step`` / ``jit_multi_step`` programs for one model at one
    compute dtype, lowered through the shipped wrappers (mesh shardings
    and donation resolution exactly as ``train/worker.py`` builds them).
    """
    import seist_tpu
    from seist_tpu.train import step as step_mod

    seist_tpu.load_all()
    ctx = _ModelCtx(model_name, window)
    mesh = _mesh()
    xi, yt = ctx.batch_structs(batch)
    policy = "bf16" if compute_dtype == "bf16" else "fp32"
    donate = step_mod.resolve_donation((0,))
    out: List[ProgramSpec] = []
    tag = compute_dtype or "fp32"

    if "step" in include:
        fn = step_mod.make_train_step(
            ctx.spec, ctx.loss_fn, compute_dtype=compute_dtype, guard=guard
        )
        out.append(
            ProgramSpec(
                key=f"train/jit_step/{model_name}/{tag}",
                kind="train",
                site=site_of(step_mod.jit_step),
                fn=fn,
                args=(ctx.state_structs, xi, yt, _rng_struct()),
                policy=policy,
                donate_intent=(0,),
                donate=donate,
                jitted=step_mod.jit_step(fn, mesh),
                mesh_size=int(mesh.devices.size),
                data_argnums=(1, 2),
                notes=_donation_notes(donate),
            )
        )
    if "multi_step" in include and steps_per_call > 1:
        fn = step_mod.make_multi_train_step(
            ctx.spec,
            ctx.loss_fn,
            compute_dtype=compute_dtype,
            steps_per_call=steps_per_call,
            guard=guard,
        )
        import jax

        stack = lambda s: jax.tree.map(  # noqa: E731
            lambda a: type(a)((steps_per_call,) + a.shape, a.dtype), s
        )
        out.append(
            ProgramSpec(
                key=(
                    f"train/jit_multi_step/{model_name}/{tag}"
                    f"/k{steps_per_call}"
                ),
                kind="train",
                site=site_of(step_mod.jit_multi_step),
                fn=fn,
                args=(ctx.state_structs, stack(xi), stack(yt), _rng_struct()),
                policy=policy,
                donate_intent=(0,),
                donate=donate,
                jitted=step_mod.jit_multi_step(fn, mesh),
                mesh_size=int(mesh.devices.size),
                data_argnums=(1, 2),
                notes=_donation_notes(donate),
            )
        )
    return out


def _donation_notes(donate: Tuple[int, ...]) -> Dict[str, Any]:
    if donate:
        return {}
    return {
        "donation_gated": True,
        "reason": (
            "resolve_donation dropped donate_argnums (persistent compile "
            "cache on the CPU backend — the jax-0.4.37 donation-corruption "
            "hazard, ROADMAP); the lowered program ships without aliasing "
            "by design"
        ),
    }


def device_aug_programs(
    model_name: str = "phasenet",
    *,
    compute_dtype: Optional[str] = None,
    window: int = 128,
    batch: int = 8,  # divisible by the analysis mesh's data axis
    steps_per_call: int = 2,
    num_events: int = 8,
    guard: bool = True,
) -> List[ProgramSpec]:
    """``jit_device_aug_step`` + ``jit_cached_call`` programs. A tiny
    synthetic RawStore supplies the row-pytree STRUCTURE (decode of
    ``num_events`` miniature traces — host work, no device compute); the
    actual rows/cache enter the analysis as avals only."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import seist_tpu
    from seist_tpu.data import device_aug as da
    from seist_tpu.data import pipeline as pl
    from seist_tpu.train import step as step_mod

    seist_tpu.load_all()
    ctx = _ModelCtx(model_name, window)
    mesh = _mesh()
    sds = pl.from_task_spec(
        ctx.spec,
        "synthetic",
        "train",
        seed=0,
        in_samples=window,
        augmentation=True,
        data_split=False,
        shuffle=True,
        # Real augmentation rates: a rate-0 config makes the traced
        # program drop the aug flags entirely (python-level gates), which
        # is NOT the program the worker ships.
        shift_event_rate=0.5,
        add_noise_rate=0.5,
        add_gap_rate=0.5,
        drop_channel_rate=0.5,
        scale_amplitude_rate=0.5,
        pre_emphasis_rate=0.5,
        generate_noise_rate=0.1,
        add_event_rate=0.5,
        max_event_num=2,
        dataset_kwargs={
            "num_events": num_events,
            "trace_samples": max(window + 64, 256),
        },
    )
    store = pl.RawStore.build(sds)
    cfg = da.AugConfig.from_preprocessor(
        sds.preprocessor,
        seed=0,
        raw_len=store.raw_len,
        phase_slots=store.phase_slots,
    )
    policy = "bf16" if compute_dtype == "bf16" else "fp32"
    donate = step_mod.resolve_donation((0,))
    tag = compute_dtype or "fp32"
    rows_struct = _structs_of(
        jax.tree.map(np.asarray, store.row_batch(np.arange(batch)))
    )
    out: List[ProgramSpec] = []

    aug_fn = step_mod.make_device_aug_train_step(
        ctx.spec,
        ctx.loss_fn,
        da.make_row_processor(cfg, sds.input_names, sds.label_names),
        compute_dtype=compute_dtype,
        guard=guard,
    )
    out.append(
        ProgramSpec(
            key=f"train/jit_device_aug_step/{model_name}/{tag}",
            kind="train",
            site=site_of(step_mod.jit_device_aug_step),
            fn=aug_fn,
            args=(
                ctx.state_structs,
                rows_struct,
                jax.ShapeDtypeStruct((batch,), jnp.int32),
                jax.ShapeDtypeStruct((batch,), jnp.bool_),
                _scalar(jnp.int32),
                _rng_struct(),
            ),
            policy=policy,
            donate_intent=(0,),
            donate=donate,
            jitted=step_mod.jit_device_aug_step(aug_fn, mesh),
            mesh_size=int(mesh.devices.size),
            data_argnums=(1, 2, 3),
            notes=_donation_notes(donate),
        )
    )

    cache_struct = _structs_of(jax.tree.map(np.asarray, store.arrays))
    call_fn = step_mod.make_cached_train_call(
        ctx.spec,
        ctx.loss_fn,
        da.make_cache_processor(
            cfg,
            sds.input_names,
            sds.label_names,
            n_raw=store.n_raw,
            augmentation=store.augmentation,
        ),
        steps_per_call=steps_per_call,
        compute_dtype=compute_dtype,
        guard=guard,
    )
    out.append(
        ProgramSpec(
            key=(
                f"train/jit_cached_call/{model_name}/{tag}/k{steps_per_call}"
            ),
            kind="train",
            site=site_of(step_mod.jit_cached_call),
            fn=call_fn,
            args=(
                ctx.state_structs,
                cache_struct,
                jax.ShapeDtypeStruct((steps_per_call, batch), jnp.int32),
                _scalar(jnp.int32),
                _rng_struct(),
            ),
            policy=policy,
            donate_intent=(0,),
            donate=donate,
            jitted=step_mod.jit_cached_call(call_fn, mesh, cache_struct),
            mesh_size=int(mesh.devices.size),
            data_argnums=(2,),
            notes=_donation_notes(donate),
        )
    )
    return out


# --------------------------------------------------------- serve programs
# The in-trace variant conventions are NOT re-implemented here: the
# manifest lowers aot.variant_compute / aot.head_variant_compute — the
# exact builders serve/pool.py ships — over aval-level variables
# (variant_structs), so the audited program cannot drift from the
# shipped executable.
def _serve_full_fn(model, variant: str):
    from seist_tpu.serve import aot

    return aot.variant_compute(
        lambda v, x: model.apply(v, x, train=False), variant
    )


def _trunk_fn(model, variant: str):
    from seist_tpu.models.seist import backbone_apply
    from seist_tpu.serve import aot

    # cast_outputs=False: bf16 features flow to bf16 heads.
    return aot.variant_compute(
        lambda v, x: backbone_apply(model, v, x), variant,
        cast_outputs=False,
    )


def _head_fn(model, variant: str):
    from seist_tpu.serve import aot

    return aot.head_variant_compute(model, variant)


def serve_programs(
    model_name: str = "phasenet",
    *,
    buckets: Sequence[int] = (4,),
    ladder: Sequence[int] = (1, 2, 4),
    variants: Sequence[str] = ("fp32", "bf16"),
    window: int = 512,
) -> List[ProgramSpec]:
    """Single-task AOT programs: full forward per bucket x variant,
    anchored at ``ModelEntry.build_programs`` (the shipped warm-up)."""
    import seist_tpu
    from seist_tpu.serve.pool import ModelEntry

    seist_tpu.load_all()
    ctx = _ModelCtx(model_name, window)
    site = site_of(ModelEntry.build_programs)
    out: List[ProgramSpec] = []
    for variant in variants:
        vs = variant_structs(ctx.var_structs, variant)
        fn = _serve_full_fn(ctx.model, variant)
        for b in buckets:
            out.append(
                ProgramSpec(
                    key=f"serve/{model_name}/full/b{b}/{variant}",
                    kind="serve",
                    site=site,
                    fn=fn,
                    args=(vs, ctx.x_struct(b)),
                    policy="bf16" if variant == "bf16" else "fp32",
                    bucket=b,
                    ladder=tuple(ladder),
                    notes={"variant": variant},
                )
            )
    return out


def group_programs(
    group: str = "seist_s",
    tasks: Sequence[str] = ("dpk", "emg", "dis"),
    *,
    buckets: Sequence[int] = (4,),
    ladder: Sequence[int] = (1, 2, 4),
    variants: Sequence[str] = ("fp32", "bf16"),
    window: int = 512,
) -> List[ProgramSpec]:
    """Shared-trunk group AOT programs: trunk per bucket x variant plus
    each task head on the trunk's feature avals — the fan-out table
    ``MultiTaskEntry.build_programs`` compiles at replica load."""
    import jax

    import seist_tpu
    from seist_tpu.serve.pool import MultiTaskEntry

    seist_tpu.load_all()
    ctxs = {t: _ModelCtx(f"{group}_{t}", window) for t in tasks}
    first = ctxs[tasks[0]]
    site = site_of(MultiTaskEntry.build_programs)
    out: List[ProgramSpec] = []
    for variant in variants:
        policy = "bf16" if variant == "bf16" else "fp32"
        trunk_fn = _trunk_fn(first.model, variant)
        trunk_vs = variant_structs(first.var_structs, variant)
        for b in buckets:
            x = first.x_struct(b)
            out.append(
                ProgramSpec(
                    key=f"serve/{group}/trunk/b{b}/{variant}",
                    kind="serve",
                    site=site,
                    fn=trunk_fn,
                    args=(trunk_vs, x),
                    policy=policy,
                    bucket=b,
                    ladder=tuple(ladder),
                    notes={"variant": variant},
                )
            )
            feats = jax.eval_shape(trunk_fn, trunk_vs, x)
            for t in tasks:
                ctx = ctxs[t]
                out.append(
                    ProgramSpec(
                        key=f"serve/{group}/head:{t}/b{b}/{variant}",
                        kind="serve",
                        site=site,
                        fn=_head_fn(ctx.model, variant),
                        args=(
                            variant_structs(ctx.var_structs, variant),
                            feats,
                            x,
                        ),
                        policy=policy,
                        bucket=b,
                        ladder=tuple(ladder),
                        notes={"variant": variant},
                    )
                )
    return out


# --------------------------------------------------------- repick programs
def repick_programs(
    model_name: str = "phasenet",
    *,
    batch: int = 8,
    window: int = 512,
    variants: Sequence[str] = ("int8",),
) -> List[ProgramSpec]:
    """The batch repick engine's int8-shards program (ISSUE 18): int8
    rows + per-row per-channel scales enter the device program AS
    STORED; the dequant (``engine.dequant_rows``) is fused ahead of the
    z-score prep and the variant forward — the exact per-micro-batch
    step body ``RepickEngine._step_fn`` builds (the shipped executable
    ``lax.map``s it over batches_per_call). Lowering it here keeps the
    host-transfer and matmul-coverage audits on the path forever: the
    widening must happen IN-program, never before the device boundary."""
    import jax
    import jax.numpy as jnp

    import seist_tpu
    from seist_tpu.batch import engine as engine_mod
    from seist_tpu.serve import aot

    seist_tpu.load_all()
    ctx = _ModelCtx(model_name, window)
    site = site_of(engine_mod.RepickEngine._step_fn)
    out: List[ProgramSpec] = []
    for variant in variants:
        vs = variant_structs(ctx.var_structs, variant)
        compute = aot.variant_compute(
            lambda v, x: ctx.model.apply(v, x, train=False), variant
        )

        def step(v, q, scale, _compute=compute):
            x = engine_mod.normalize_transpose(
                engine_mod.dequant_rows(q, scale)
            )
            return _compute(v, x)

        out.append(
            ProgramSpec(
                key=f"repick/{model_name}/b{batch}/{variant}+i8shards",
                kind="serve",
                site=site,
                fn=step,
                args=(
                    vs,
                    jax.ShapeDtypeStruct(
                        (batch, ctx.in_channels, window), jnp.int8
                    ),
                    jax.ShapeDtypeStruct(
                        (batch, ctx.in_channels), jnp.float32
                    ),
                ),
                policy="bf16" if variant == "bf16" else "fp32",
                bucket=batch,
                notes={"variant": variant, "shards": "int8"},
            )
        )
    return out


# --------------------------------------------------------- stream program
def stream_program(
    *, window: int = 512, n_windows: int = 15, record_len: int = 4096
) -> ProgramSpec:
    """The ``ops/stream.annotate`` device chain downstream of the model
    forward: stitch overlapping window probabilities, pick phases,
    detect intervals — one program chain, one final host transfer."""
    import jax
    import jax.numpy as jnp

    from seist_tpu.ops import stream
    from seist_tpu.ops.postprocess import detect_events, pick_peaks

    def chain(probs, offsets):
        curve = stream.stitch_probs(probs, offsets, record_len)
        ppk = pick_peaks(curve[None, :, 1], 0.3, 50, 64)
        spk = pick_peaks(curve[None, :, 2], 0.3, 50, 64)
        det = detect_events(1.0 - curve[:, 0][None, :], 0.5, 64)
        return ppk, spk, det

    return ProgramSpec(
        key="stream/annotate/stitch_pick_detect",
        kind="stream",
        site=site_of(stream.annotate),
        fn=chain,
        args=(
            jax.ShapeDtypeStruct((n_windows, window, 3), jnp.float32),
            jax.ShapeDtypeStruct((n_windows,), jnp.int32),
        ),
        notes={"record_len": record_len, "n_windows": n_windows},
    )


# -------------------------------------------------------- default manifest
def default_manifest(
    *,
    window: int = 512,
    batch: int = 8,  # divisible by the analysis mesh's data axis
    buckets: Sequence[int] = (4,),
    ladder: Sequence[int] = (1, 2, 4),
    variants: Sequence[str] = ("fp32", "bf16"),
    serve_group: str = "seist_s",
    group_tasks: Sequence[str] = ("dpk", "emg", "dis"),
    match: Optional[Callable[[str], bool]] = None,
) -> List[ProgramSpec]:
    """The gate manifest: every shipped jit boundary, sized to lower in
    about a minute on the CPU backend. Tests build narrower manifests
    directly from the builders above (and wider ones — all five heads,
    seist_l — where a number must be pinned).

    ``match(key) -> bool`` prunes at the SECTION level before any model
    is even constructed — candidate keys are deterministic strings, so a
    subset run (``python -m tools.irlint 'serve/phasenet/*'``) never pays
    for building the programs it is not going to lint."""
    keep = match or (lambda _k: True)

    def _keys_train(model: str, tag: str, include, k: int) -> List[str]:
        out = []
        if "step" in include:
            out.append(f"train/jit_step/{model}/{tag}")
        if "multi_step" in include:
            out.append(f"train/jit_multi_step/{model}/{tag}/k{k}")
        return out

    programs: List[ProgramSpec] = []
    sections = [
        (
            _keys_train("phasenet", "fp32", ("step",), 2),
            lambda: train_programs(
                "phasenet", compute_dtype=None, window=window, batch=batch,
                include=("step",),
            ),
        ),
        (
            _keys_train(
                "seist_s_dpk", "bf16", ("step", "multi_step"), 2
            ),
            lambda: train_programs(
                "seist_s_dpk", compute_dtype="bf16", window=window,
                batch=batch, include=("step", "multi_step"),
            ),
        ),
        (
            [
                "train/jit_device_aug_step/phasenet/fp32",
                "train/jit_cached_call/phasenet/fp32/k2",
            ],
            lambda: device_aug_programs(
                "phasenet", compute_dtype=None, window=min(window, 128),
                batch=batch,
            ),
        ),
        (
            [
                f"serve/phasenet/full/b{b}/{v}"
                for b in buckets
                for v in variants
            ],
            lambda: serve_programs(
                "phasenet", buckets=buckets, ladder=ladder,
                variants=variants, window=window,
            ),
        ),
        (
            [
                f"serve/{serve_group}/{part}/b{b}/{v}"
                for b in buckets
                for v in variants
                for part in ["trunk"] + [f"head:{t}" for t in group_tasks]
            ],
            lambda: group_programs(
                serve_group, group_tasks, buckets=buckets, ladder=ladder,
                variants=variants, window=window,
            ),
        ),
        (
            [f"repick/phasenet/b{batch}/int8+i8shards"],
            lambda: repick_programs(
                "phasenet", batch=batch, window=window, variants=("int8",)
            ),
        ),
        (
            ["stream/annotate/stitch_pick_detect"],
            lambda: [stream_program(window=window)],
        ),
    ]
    for keys, build in sections:
        if not any(keep(k) for k in keys):
            continue
        programs.extend(p for p in build() if keep(p.key))
    return programs
