"""irlint CLI — the shared analyzer frontend over the program manifest.

    python -m tools.irlint                          # full manifest, gate
    python -m tools.irlint 'serve/*'                # program-key subset
    python -m tools.irlint --report irlint_report.json
    python -m tools.irlint --list-rules
    python -m tools.irlint --list-programs

Exit codes mirror the sibling analyzers: 0 clean (vs baseline), 1 new
findings, 2 usage / program-lowering error. Suppressions are ordinary
``# irlint: disable=<rule> -- rationale`` comments at a program's
REGISTRATION SITE (the ``def`` line findings anchor to); the baseline
(tools/irlint_baseline.json) is empty by construction and
--update-baseline refuses to touch it while it stays that way.
"""

from __future__ import annotations

import fnmatch
import json
import os
import sys
import traceback
from typing import Dict, List, Optional, Sequence, Tuple

from tools.irlint.manifest import ensure_cpu_backend

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_BASELINE = os.path.join(_REPO_ROOT, "tools", "irlint_baseline.json")


def _add_args(ap) -> None:
    ap.add_argument(
        "--report",
        default="",
        help="write the per-program machine-readable report JSON here",
    )
    ap.add_argument(
        "--list-programs",
        action="store_true",
        help="print the manifest's program keys and exit",
    )
    ap.add_argument(
        "--window", type=int, default=512,
        help="analysis window length (trace-time only; default 512)",
    )
    ap.add_argument(
        "--buckets", default="4",
        help="comma-separated serve buckets to lower (default 4)",
    )
    ap.add_argument(
        "--ladder", default="1,2,4",
        help="declared serve bucket ladder for the padding audit",
    )
    ap.add_argument(
        "--variants", default="fp32,bf16",
        help="serve variants to lower (fp32,bf16,int8)",
    )
    ap.add_argument(
        "--group", default="seist_s",
        help="task group for the shared-trunk serve table",
    )
    ap.add_argument(
        "--group-tasks", default="dpk,emg,dis",
        help="tasks of the analyzed group",
    )


def _csv_ints(s: str) -> Tuple[int, ...]:
    return tuple(int(x) for x in s.split(",") if x.strip())


def _csv(s: str) -> Tuple[str, ...]:
    return tuple(x.strip() for x in s.split(",") if x.strip())


def apply_site_suppressions(
    findings: List,
    site_files: Sequence[str],
    *,
    root: str,
    full_catalog: bool,
) -> List:
    """Honor ``# irlint: disable=<rule> -- rationale`` comments at the
    registration sites findings anchor to — the engine's suppression
    grammar and semantics (rationale required, comment-above idiom,
    tag-scoped so a jaxlint/threadlint comment can never silence an
    irlint finding), applied to manifest findings instead of AST ones.
    ``full_catalog`` enables unused-suppression reporting (mirroring the
    engine: a --select subset would make every un-run rule's suppression
    look stale)."""
    from tools.jaxlint.engine import Finding, ModuleInfo, parse_suppressions

    mod_cache: Dict[str, ModuleInfo] = {}
    sups_by_file: Dict[str, Dict] = {}
    problems: List[Finding] = []
    for rel in site_files:
        path = os.path.join(root, rel)
        with open(path, encoding="utf-8") as f:
            mod_cache[rel] = ModuleInfo(rel, f.read())
        sups_by_file[rel], probs = parse_suppressions(
            mod_cache[rel], tag="irlint"
        )
        problems.extend(probs)
    kept: List[Finding] = []
    for f in findings:
        sup = sups_by_file.get(f.file, {}).get(f.line)
        if sup is not None and f.rule != "parse-error" and sup.covers(f.rule):
            sup.used = True
            continue
        kept.append(f)
    out = kept + problems
    if full_catalog:
        seen_ids = set()
        for rel, sups in sups_by_file.items():
            for sup in sups.values():
                if id(sup) in seen_ids or sup.used:
                    continue
                seen_ids.add(id(sup))
                out.append(
                    Finding(
                        file=rel,
                        line=sup.line,
                        col=0,
                        rule="unused-suppression",
                        message=(
                            "suppression matches no finding (rules: "
                            f"{', '.join(sup.rules)}) — the program it "
                            "excused is clean or the rule name is wrong"
                        ),
                        hint="delete the stale `# irlint: disable` comment",
                        text=mod_cache[rel].line_text(sup.line),
                    )
                )
    return out


def collect(args, rules) -> Tuple[List, set]:
    """The manifest collector the shared frontend plugs in where the AST
    analyzers walk files: build + filter the manifest, lower + lint every
    program, apply site-file suppressions, write the report."""
    from tools.irlint import rules as irrules
    from tools.irlint.manifest import default_manifest
    from tools.jaxlint.engine import Finding

    match = None
    if args.paths:
        match = lambda key: any(  # noqa: E731
            fnmatch.fnmatch(key, g) for g in args.paths
        )
    programs = default_manifest(
        window=args.window,
        buckets=_csv_ints(args.buckets),
        ladder=_csv_ints(args.ladder),
        variants=_csv(args.variants),
        serve_group=args.group,
        group_tasks=_csv(args.group_tasks),
        match=match,
    )
    if not programs:
        raise FileNotFoundError(
            f"no manifest program matches {args.paths}"
        )
    if args.list_programs:
        for p in programs:
            print(f"{p.key}  ({p.site.file}:{p.site.line}, {p.policy})")
        raise SystemExit(0)

    findings: List[Finding] = []
    report: Dict[str, Dict] = {}
    linted: set = set()
    for spec in programs:
        linted.add(spec.site.file)
        try:
            info_list = irrules.lint_programs([spec], rules)
        except Exception as e:  # noqa: BLE001 - a program that fails to
            # lower must fail the gate loudly (exit 2 via parse-error),
            # never silently shrink the manifest to green.
            traceback.print_exc(file=sys.stderr)
            findings.append(
                Finding(
                    file=spec.site.file,
                    line=spec.site.line,
                    col=0,
                    rule="parse-error",
                    message=(
                        f"[{spec.key}] program failed to lower/lint: "
                        f"{e!r}"
                    ),
                    text=spec.site.text,
                )
            )
            continue
        for info in info_list:
            findings.extend(info.findings)
            report[spec.key] = info.report

    findings = apply_site_suppressions(
        findings,
        sorted(linted),
        root=args.root,
        full_catalog=rules is None,
    )

    if args.report:
        payload = {
            "schema_version": 1,
            "tool": "irlint",
            "programs": report,
            "summary": _summarize(report),
        }
        with open(args.report, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        print(
            f"irlint: report for {len(report)} program(s) -> {args.report}",
            file=sys.stderr,
        )
    findings.sort(key=lambda f: (f.file, f.line, f.col))
    return findings, linted


def _summarize(report: Dict[str, Dict]) -> Dict:
    """Trendable roll-up: the numbers bench/CI watch across commits."""
    cov = {
        k: r["matmul"]["coverage"]
        for k, r in report.items()
        if r.get("matmul", {}).get("coverage") is not None
    }
    pad = {
        k: r["padding"]["waste_frac_worst"]
        for k, r in report.items()
        if "padding" in r
    }
    transfers = sum(
        t["count"]
        for r in report.values()
        for t in r.get("host_transfers", ())
    )
    donated = sum(
        r.get("donation", {}).get("donated_leaves", 0)
        for r in report.values()
    )
    aliased = sum(
        r.get("donation", {}).get("aliased_leaves", 0)
        for r in report.values()
    )
    deferred = sum(
        r.get("donation", {}).get("deferred_leaves", 0)
        for r in report.values()
    )
    return {
        "programs": len(report),
        "bf16_coverage_min": min(cov.values()) if cov else None,
        "bf16_coverage_by_program": cov,
        "padding_waste_worst": max(pad.values()) if pad else None,
        "host_transfers_total": transfers,
        "donated_leaves": donated,
        "aliased_leaves": aliased,
        "deferred_alias_leaves": deferred,
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    ensure_cpu_backend()
    from tools.irlint.rules import RULES, RULES_BY_NAME
    from tools.jaxlint.__main__ import run

    return run(
        argv,
        tag="irlint",
        catalog=RULES,
        rules_by_name=RULES_BY_NAME,
        default_baseline=_BASELINE,
        docs="docs/STATIC_ANALYSIS.md",
        example_paths="",
        collect=collect,
        add_args=_add_args,
        refuse_empty_baseline_update=True,
    )


if __name__ == "__main__":
    sys.exit(main())
