"""IR analysis primitives: dtype-aware matmul FLOP accounting, host
transfer detection, donation-aliasing and sharding extraction from
lowered StableHLO.

FLOP formulas are obs/attribution.py's exact ``dot_general`` /
``conv_general_dilated`` accounting (imported, not duplicated) — the
same numbers the BENCH ``step_breakdown`` reports, so an irlint coverage
fraction and a bench MFU decomposition agree about what a matmul costs.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from seist_tpu.obs.attribution import (
    conv_flops as _conv_flops,
    dot_flops as _dot_flops,
    inner_jaxpr as _inner,
    sub_jaxprs as _sub_jaxprs,
)

#: Primitives that move data across the device<->host boundary inside a
#: program. Matched by exact name OR by the ``callback`` substring so a
#: jax version rename (pure_callback -> ...) fails loud, not silent.
HOST_TRANSFER_PRIMS = frozenset(
    (
        "pure_callback",
        "io_callback",
        "debug_callback",
        "host_callback_call",
        "outside_call",
        "infeed",
        "outfeed",
    )
)


def _is_host_transfer(prim_name: str) -> bool:
    return prim_name in HOST_TRANSFER_PRIMS or "callback" in prim_name


def _shape_str(v) -> str:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None:
        return "?"
    return f"{dtype}[{','.join(str(d) for d in shape)}]"


# ------------------------------------------------------------- jaxpr walks
def matmul_dtype_table(closed_jaxpr) -> List[Dict[str, Any]]:
    """Per-(primitive, operand-dtypes) matmul FLOP records, scan bodies
    multiplied by trip count, cond branches summed (conservative: a
    branch's f32 matmul counts even if the other branch is hotter).

    Returns records ``{"op", "dtypes": (lhs, rhs), "flops", "count",
    "example"}`` sorted by descending FLOPs.
    """
    acc: Dict[Tuple[str, Tuple[str, str]], Dict[str, Any]] = {}

    def walk(jaxpr, scale: int) -> None:
        for eqn in _inner(jaxpr).eqns:
            subs = _sub_jaxprs(eqn)
            if subs:
                for sub, mult, _excl in subs:
                    walk(sub, scale * mult)
                continue
            name = eqn.primitive.name
            try:
                if name == "dot_general":
                    flops = _dot_flops(eqn)
                elif name == "conv_general_dilated":
                    flops = _conv_flops(eqn)
                else:
                    continue
            except (AttributeError, KeyError, TypeError, IndexError):
                continue  # unmodeled layout: skip rather than die
            dts = tuple(str(v.aval.dtype) for v in eqn.invars[:2])
            rec = acc.setdefault(
                (name, dts),
                {
                    "op": name,
                    "dtypes": dts,
                    "flops": 0,
                    "count": 0,
                    "example": " x ".join(
                        _shape_str(v) for v in eqn.invars[:2]
                    ),
                },
            )
            rec["flops"] += flops * scale
            rec["count"] += scale

    walk(closed_jaxpr, 1)
    return sorted(acc.values(), key=lambda r: -r["flops"])


def matmul_coverage(table: Sequence[Dict[str, Any]], dtype: str) -> Dict[str, Any]:
    """Fraction of matmul FLOPs whose BOTH operands are ``dtype`` —
    the precision campaign's per-program coverage number."""
    total = sum(r["flops"] for r in table)
    covered = sum(
        r["flops"] for r in table if all(d == dtype for d in r["dtypes"])
    )
    return {
        "matmul_flops_total": int(total),
        "matmul_flops_covered": int(covered),
        "coverage": (covered / total) if total else None,
        "by_dtype": [
            {
                "op": r["op"],
                "dtypes": list(r["dtypes"]),
                "flops": int(r["flops"]),
                "count": int(r["count"]),
                "example": r["example"],
            }
            for r in table
        ],
    }


def host_transfers(closed_jaxpr) -> List[Dict[str, Any]]:
    """Host-boundary primitives inside the program (callbacks, infeed,
    outfeed), scan-scaled. The IR-level truth jaxlint's AST host-sync
    pass can only approximate: anything here executes a device->host
    round trip INSIDE the compiled program, per call."""
    acc: Dict[str, Dict[str, Any]] = {}

    def walk(jaxpr, scale: int) -> None:
        for eqn in _inner(jaxpr).eqns:
            subs = _sub_jaxprs(eqn)
            if subs:
                for sub, mult, _excl in subs:
                    walk(sub, scale * mult)
                continue
            name = eqn.primitive.name
            if _is_host_transfer(name):
                rec = acc.setdefault(
                    name,
                    {
                        "prim": name,
                        "count": 0,
                        "example": " ".join(
                            _shape_str(v) for v in eqn.invars[:2]
                        ),
                    },
                )
                rec["count"] += scale

    walk(closed_jaxpr, 1)
    return sorted(acc.values(), key=lambda r: -r["count"])


def total_flops_bytes(closed_jaxpr) -> Tuple[int, int]:
    """(analytic FLOPs, analytic bytes) via obs/attribution's full walk."""
    from seist_tpu.obs.attribution import jaxpr_op_costs

    ops = jaxpr_op_costs(closed_jaxpr)
    return (
        int(sum(r["flops"] for r in ops)),
        int(sum(r["bytes"] for r in ops)),
    )


# -------------------------------------------------------- stablehlo parses
_MAIN_RE = re.compile(
    r"func\.func\s+public\s+@main\((?P<args>.*?)\)\s*->", re.DOTALL
)
_ARG_HEAD_RE = re.compile(r"%arg(?P<idx>\d+):\s*tensor<(?P<ty>[^>]*)>")
_ALIAS_RE = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")
_SHARD_RE = re.compile(r'mhlo\.sharding\s*=\s*"([^"]*)"')


def parse_main_args(stablehlo_text: str) -> List[Dict[str, Any]]:
    """Flat entry-arg records from a lowered module's ``@main`` signature:
    ``{"index", "type", "aliased_output": int|None, "buffer_donor": bool,
    "sharding": str|None}``.

    Donation shows up two ways depending on how the program was lowered:
    a plain jit emits ``tf.aliasing_output = N`` on every donated arg it
    could pair with an output AT LOWERING TIME; a sharded (mesh) lowering
    instead emits ``jax.buffer_donor = true`` and defers the actual
    aliasing decision to XLA's compile. A declared-donated arg carrying
    NEITHER marker was dropped by the lowering (the "Some donated buffers
    were not usable" warning) — the distinction the audit pins.

    Parsing splits on ``%argN:`` boundaries instead of matching the attr
    brace block — attribute values legally contain nested braces
    (``mhlo.sharding = "{replicated}"``), which brace-matching regexes
    silently truncate.
    """
    m = _MAIN_RE.search(stablehlo_text)
    if not m:
        return []
    args: List[Dict[str, Any]] = []
    for part in re.split(r"(?=%arg\d+:)", m.group("args")):
        head = _ARG_HEAD_RE.match(part.strip())
        if not head:
            continue
        alias = _ALIAS_RE.search(part)
        shard = _SHARD_RE.search(part)
        args.append(
            {
                "index": int(head.group("idx")),
                "type": head.group("ty"),
                "aliased_output": int(alias.group(1)) if alias else None,
                "buffer_donor": "jax.buffer_donor" in part,
                "sharding": shard.group(1) if shard else None,
            }
        )
    return args


def flat_arg_ranges(arg_structs: Sequence[Any]) -> List[Tuple[int, int]]:
    """[start, end) flat-leaf index range of each positional argument —
    maps a jit argnum to the contiguous ``%argN`` block it flattens to
    in the lowered module's ``@main`` signature."""
    import jax

    ranges: List[Tuple[int, int]] = []
    off = 0
    for a in arg_structs:
        n = len(jax.tree_util.tree_leaves(a))
        ranges.append((off, off + n))
        off += n
    return ranges


def _lowered_positions(
    flat_indices: Sequence[int], kept: Optional[Sequence[int]]
) -> Dict[int, Optional[int]]:
    """Map original flat-arg indices to their ``%argN`` position in the
    lowered module. ``kept`` is the lowering's kept_var_idx (sorted);
    a pruned index maps to None. ``kept=None`` = identity (nothing
    pruned, or the lowering doesn't report)."""
    if kept is None:
        return {i: i for i in flat_indices}
    pos = {orig: n for n, orig in enumerate(kept)}
    return {i: pos.get(i) for i in flat_indices}


def donation_audit(
    stablehlo_text: str,
    arg_structs: Sequence[Any],
    donate_argnums: Sequence[int],
    kept: Optional[Sequence[int]] = None,
) -> Dict[str, Any]:
    """Declared donation vs what the lowering actually did with it.

    Returns ``{"declared_argnums", "donated_leaves", "aliased_leaves",
    "deferred_leaves", "unaliased": [{"index", "type"}...],
    "stray_aliases": [...]}``:

    * ``aliased_leaves`` — donated buffers paired to an output at
      LOWERING time (``tf.aliasing_output``, plain-jit lowerings);
    * ``deferred_leaves`` — donated buffers marked ``jax.buffer_donor``
      (sharded lowerings): donation accepted, the input->output pairing
      happens inside XLA's compile — the exact stage where the
      jax-0.4.37 deserialized-executable corruption lives (ROADMAP);
    * ``unaliased`` — declared-donated buffers carrying NEITHER marker:
      the lowering dropped them ("Some donated buffers were not
      usable"), so they free HBM only after the program finishes.
    """
    args = parse_main_args(stablehlo_text)
    by_pos = {a["index"]: a for a in args}
    ranges = flat_arg_ranges(arg_structs)
    donated: List[int] = []
    for argnum in donate_argnums:
        if 0 <= argnum < len(ranges):
            start, end = ranges[argnum]
            donated.extend(range(start, end))
    positions = _lowered_positions(donated, kept)
    pruned = [i for i in donated if positions[i] is None]
    recs = [
        by_pos[positions[i]]
        for i in donated
        if positions[i] is not None and positions[i] in by_pos
    ]
    unaliased = [
        {"index": a["index"], "type": a["type"]}
        for a in recs
        if a["aliased_output"] is None and not a["buffer_donor"]
    ]
    aliased = [a for a in recs if a["aliased_output"] is not None]
    deferred = [
        a
        for a in recs
        if a["buffer_donor"] and a["aliased_output"] is None
    ]
    # Aliases the lowering claims outside the declared donation would be
    # a jax-level invariant violation; surface them rather than hide.
    donated_pos = {
        positions[i] for i in donated if positions[i] is not None
    }
    stray = [
        a["index"]
        for a in args
        if (a["aliased_output"] is not None or a["buffer_donor"])
        and a["index"] not in donated_pos
    ]
    return {
        "declared_argnums": list(donate_argnums),
        "donated_leaves": len(donated),
        "aliased_leaves": len(aliased),
        "deferred_leaves": len(deferred),
        "pruned_leaves": len(pruned),
        "unaliased": unaliased,
        "stray_aliases": stray,
    }


def sharding_audit(
    stablehlo_text: str,
    arg_structs: Sequence[Any],
    data_argnums: Sequence[int],
    kept: Optional[Sequence[int]] = None,
) -> Dict[str, Any]:
    """Entry-arg sharding of a mesh-lowered program: for each declared
    DATA argument (expected batch-sharded), report whether the lowered
    module actually annotates it with a device split. ``replicated``
    lists data-arg buffers lowered as ``{replicated}`` (or with no
    sharding at all) — each one is a full copy of the global batch on
    every device. Args the lowering pruned (unused) are skipped."""
    args = parse_main_args(stablehlo_text)
    by_pos = {a["index"]: a for a in args}
    ranges = flat_arg_ranges(arg_structs)
    flat: List[int] = []
    for argnum in data_argnums:
        if 0 <= argnum < len(ranges):
            start, end = ranges[argnum]
            flat.extend(range(start, end))
    positions = _lowered_positions(flat, kept)
    replicated: List[Dict[str, Any]] = []
    sharded = 0
    total = 0
    pruned = 0
    for i in flat:
        pos = positions[i]
        if pos is None:
            pruned += 1
            continue
        a = by_pos.get(pos)
        if a is None:
            continue
        total += 1
        s = a["sharding"]
        if s is not None and "devices=" in s:
            sharded += 1
        else:
            replicated.append(
                {"index": pos, "type": a["type"], "sharding": s}
            )
    return {
        "data_argnums": list(data_argnums),
        "data_leaves": total,
        "sharded_leaves": sharded,
        "pruned_leaves": pruned,
        "replicated": replicated,
    }
