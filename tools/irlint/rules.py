"""irlint rule catalog — each rule audits one ProgramInfo and returns
engine Findings anchored at the program's registration site, while
filling the program's machine-readable report entry (irlint_report.json)
as a side effect. Rules must stay device-free: everything here reads
jaxprs and lowered StableHLO text, never runs a program.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List

from tools.irlint import ir
from tools.irlint.manifest import ProgramInfo
from tools.jaxlint.engine import Finding


@dataclass(frozen=True)
class IrRule:
    name: str
    summary: str
    hint: str
    check: Callable[[ProgramInfo], List[Finding]]

    # The shared --list-rules printer reads .name/.summary/.hint like the
    # AST analyzers' Rule objects.


def _finding(prog: ProgramInfo, rule: str, message: str, hint: str) -> Finding:
    site = prog.spec.site
    return Finding(
        file=site.file,
        line=site.line,
        col=0,
        rule=rule,
        message=f"[{prog.spec.key}] {message}",
        hint=hint,
        text=site.text,
    )


# ------------------------------------------------- f32 matmuls under bf16
_COVERAGE_HINT = (
    "trace the offending module under the bf16 policy "
    "(train/precision.py) — a single fp32 operand (an fp32 carry, a "
    "policy-blind module dtype) promotes the matmul and everything "
    "downstream; deliberately-fp32 math needs an `# irlint: disable` "
    "with a rationale at the program's registration site"
)


def check_precision(prog: ProgramInfo) -> List[Finding]:
    table = ir.matmul_dtype_table(prog.jaxpr)
    cov = ir.matmul_coverage(table, "bfloat16")
    if prog.spec.policy == "bf16":
        prog.report["matmul"] = cov
    else:
        # fp32/int8 programs: record totals, no coverage judgment.
        prog.report["matmul"] = {
            "matmul_flops_total": cov["matmul_flops_total"],
            "coverage": None,
        }
        return []
    frac = cov["coverage"]
    if frac is None or frac >= prog.spec.coverage_min:
        return []
    offenders = [
        f"{r['op']}{list(r['dtypes'])} {r['flops']:.3g} flops ({r['example']})"
        for r in cov["by_dtype"]
        if not all(d == "bfloat16" for d in r["dtypes"])
    ][:3]
    return [
        _finding(
            prog,
            "f32-matmul-under-bf16-policy",
            (
                f"bf16 matmul-FLOPs coverage {frac:.3f} < "
                f"{prog.spec.coverage_min:.2f} under the declared bf16 "
                f"policy; non-bf16: {'; '.join(offenders)}"
            ),
            _COVERAGE_HINT,
        )
    ]


# ------------------------------------------------------- donation aliasing
_DONATE_HINT = (
    "a donated buffer the lowering could not alias frees HBM only after "
    "the program finishes — match the donated leaf's (shape, dtype) to an "
    "output or drop it from donate_argnums; the runtime use-after-reuse "
    "hazard itself is gated by train/step.py:resolve_donation"
)


def check_donation(prog: ProgramInfo) -> List[Finding]:
    spec = prog.spec
    if not spec.donate_intent:
        return []
    if not spec.donate:
        # resolve_donation gated donation out (hazard config): the lowered
        # program legitimately carries no aliasing. Record, don't flag.
        prog.report["donation"] = dict(
            spec.notes, declared_argnums=list(spec.donate_intent),
            aliased_leaves=0, donated_leaves=0,
        )
        return []
    audit = ir.donation_audit(
        prog.stablehlo, spec.args, spec.donate, kept=prog.kept_var_idx
    )
    prog.report["donation"] = audit
    out: List[Finding] = []
    if audit["unaliased"]:
        ex = ", ".join(u["type"] for u in audit["unaliased"][:3])
        out.append(
            _finding(
                prog,
                "donation-alias-audit",
                (
                    f"{len(audit['unaliased'])} of "
                    f"{audit['donated_leaves']} donated buffer(s) were NOT "
                    f"aliased to an output by the lowering (e.g. {ex})"
                ),
                _DONATE_HINT,
            )
        )
    if audit["stray_aliases"]:
        out.append(
            _finding(
                prog,
                "donation-alias-audit",
                (
                    f"lowering aliased {len(audit['stray_aliases'])} "
                    "buffer(s) OUTSIDE the declared donate_argnums "
                    f"(entry indices {audit['stray_aliases'][:5]})"
                ),
                "an alias jax did not get from donate_argnums means the "
                "declared donation table and the lowered program disagree "
                "— audit the jit wrapper",
            )
        )
    return out


# ------------------------------------------------------------ host transfer
_HOST_HINT = (
    "a callback/infeed/outfeed inside a compiled program is a synchronous "
    "device<->host round trip PER CALL — hoist it out of the program, or "
    "suppress with a rationale if the transfer is the program's purpose"
)


def check_host_transfer(prog: ProgramInfo) -> List[Finding]:
    transfers = ir.host_transfers(prog.jaxpr)
    prog.report["host_transfers"] = transfers
    if not transfers:
        return []
    desc = ", ".join(f"{t['prim']} x{t['count']}" for t in transfers)
    return [
        _finding(
            prog,
            "host-transfer-in-program",
            f"host-boundary primitive(s) inside the lowered program: {desc}",
            _HOST_HINT,
        )
    ]


# ------------------------------------------------------------ padding waste
_PAD_HINT = (
    "a request landing just above a bucket boundary pays the whole gap as "
    "padded FLOPs — tighten the bucket ladder (serve --buckets) so no gap "
    "exceeds 2x, or accept the waste with a rationale'd suppression"
)


def check_padding(prog: ProgramInfo) -> List[Finding]:
    spec = prog.spec
    if spec.kind != "serve" or not spec.bucket or not spec.ladder:
        return []
    flops, _ = ir.total_flops_bytes(prog.jaxpr)
    below = [b for b in spec.ladder if b < spec.bucket]
    worst_occupancy = (max(below) if below else 0) + 1
    waste_worst = 1.0 - worst_occupancy / spec.bucket
    prog.report["padding"] = {
        "bucket": spec.bucket,
        "ladder": list(spec.ladder),
        "flops_total": flops,
        "flops_per_row": flops // max(spec.bucket, 1),
        "worst_occupancy": worst_occupancy,
        "waste_frac_worst": round(waste_worst, 4),
    }
    if waste_worst <= 0.5:
        return []
    return [
        _finding(
            prog,
            "padding-waste",
            (
                f"bucket {spec.bucket} with ladder {list(spec.ladder)}: a "
                f"{worst_occupancy}-row flush pads {waste_worst:.0%} of "
                f"{flops:.3g} FLOPs"
            ),
            _PAD_HINT,
        )
    ]


# ------------------------------------------------------- replication audit
_REPL_HINT = (
    "declare the batch axis in in_shardings (jit_step/jit_multi_step/"
    "jit_cached_call do this; a bare jax.jit under a mesh does not) — a "
    "replicated data arg uploads the full global batch to EVERY device"
)


def check_replication(prog: ProgramInfo) -> List[Finding]:
    spec = prog.spec
    if spec.mesh_size <= 1 or not spec.data_argnums:
        return []
    audit = ir.sharding_audit(
        prog.stablehlo, spec.args, spec.data_argnums,
        kept=prog.kept_var_idx,
    )
    prog.report["sharding"] = audit
    if not audit["replicated"]:
        return []
    ex = ", ".join(r["type"] for r in audit["replicated"][:3])
    return [
        _finding(
            prog,
            "replication-audit",
            (
                f"{len(audit['replicated'])} of {audit['data_leaves']} "
                f"data-arg buffer(s) lowered REPLICATED on a "
                f"{spec.mesh_size}-device mesh (e.g. {ex})"
            ),
            _REPL_HINT,
        )
    ]


RULES = (
    IrRule(
        name="f32-matmul-under-bf16-policy",
        summary=(
            "matmul FLOPs still running in fp32 in a program whose "
            "declared compute policy is bf16 (per-program coverage "
            "fraction below the manifest's threshold)"
        ),
        hint=_COVERAGE_HINT,
        check=check_precision,
    ),
    IrRule(
        name="donation-alias-audit",
        summary=(
            "declared donate_argnums vs the input_output aliases the "
            "lowering actually established: donated-but-unaliased and "
            "stray-aliased buffers"
        ),
        hint=_DONATE_HINT,
        check=check_donation,
    ),
    IrRule(
        name="host-transfer-in-program",
        summary=(
            "callback/infeed/outfeed primitives inside a compiled "
            "program — synchronous host round trips per call"
        ),
        hint=_HOST_HINT,
        check=check_host_transfer,
    ),
    IrRule(
        name="padding-waste",
        summary=(
            "worst-case FLOPs fraction burned padding a partial flush up "
            "to its serve bucket, per bucket ladder"
        ),
        hint=_PAD_HINT,
        check=check_padding,
    ),
    IrRule(
        name="replication-audit",
        summary=(
            "data arguments of mesh-lowered programs that the lowering "
            "left replicated (full global batch on every device)"
        ),
        hint=_REPL_HINT,
        check=check_replication,
    ),
)

RULES_BY_NAME: Dict[str, IrRule] = {r.name: r for r in RULES}


def lint_programs(
    programs,
    rules=None,
) -> List[ProgramInfo]:
    """Run the catalog over ProgramSpecs; returns the ProgramInfos with
    ``.findings`` attached (suppression/baseline handling is the
    frontend's job, like the AST analyzers)."""
    infos: List[ProgramInfo] = []
    for spec in programs:
        info = ProgramInfo(spec)
        findings: List[Finding] = []
        for rule in rules if rules is not None else RULES:
            findings.extend(rule.check(info))
        info.findings = findings
        infos.append(info)
    return infos
