"""Continuous-record phase picking CLI (capability the reference lacks —
its demo scores exactly one 8192-sample window, demo_predict.py:59-97).

    python tools/predict.py --model-name seist_s_dpk \
        --checkpoint ./imported/seist_s_dpk \
        --input record.npz --output picks.csv \
        [--window 8192] [--stride 4096] [--batch-size 32]

``--input``: .npz with a ``data`` array of shape (L, C) or (C, L), any
length >= window. Output CSV: one row per pick/detection with absolute
sample index and time (s at --sampling-rate).
"""

from __future__ import annotations

import argparse
import os
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))


def main() -> None:
    ap = argparse.ArgumentParser(description="continuous-record picking")
    ap.add_argument("--model-name", default="seist_s_dpk")
    ap.add_argument("--checkpoint", required=True)
    ap.add_argument("--input", required=True, help=".npz with 'data'")
    ap.add_argument("--output", default="picks.csv")
    ap.add_argument("--window", type=int, default=8192)
    ap.add_argument("--stride", type=int, default=0, help="0 = window//2")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--sampling-rate", type=int, default=50)
    ap.add_argument("--ppk-threshold", type=float, default=0.3)
    ap.add_argument("--spk-threshold", type=float, default=0.3)
    ap.add_argument("--det-threshold", type=float, default=0.5)
    ap.add_argument("--min-peak-dist", type=float, default=1.0)
    ap.add_argument("--combine", default="max", choices=["mean", "max"],
                    help="overlap stitching: max (robust picks, default) "
                    "or mean (smoother curves)")
    ap.add_argument("--max-events", type=int, default=0,
                    help="cap on picks over the whole record; 0 = scale "
                    "with record length (4 per window span)")
    args = ap.parse_args()

    from seist_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()

    import numpy as np
    import pandas as pd

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.ops.stream import annotate
    from seist_tpu.serve.pool import load_model_entry

    seist_tpu.load_all()

    # Fail fast on model family before touching the input file.
    spec = taskspec.get_task_spec(args.model_name)
    first_group = spec.labels[0]
    if not (
        isinstance(first_group, (tuple, list))
        and tuple(first_group)[0] in ("non", "det")
        and len(first_group) == 3
    ):
        raise SystemExit(
            f"{args.model_name} is not a dpk-family model "
            f"(labels {spec.labels}); continuous picking needs "
            f"(non|det, ppk, spk) outputs"
        )
    channel0 = first_group[0]

    npz = np.load(args.input)
    record = np.asarray(npz["data"], np.float32)
    if record.ndim != 2:
        raise ValueError(f"'data' must be 2-D, got {record.shape}")
    if record.shape[0] < record.shape[1]:  # (C, L) -> (L, C)
        record = record.T

    # Checkpoint loading/warm-up logic lives in the serve model pool —
    # offline CLI and online service share exactly one loader.
    entry = load_model_entry(
        args.model_name, args.checkpoint, window=args.window
    )

    picks = annotate(
        entry.forward,
        record,
        jitted=True,  # entry.forward is already jax.jit'd by the pool
        window=args.window,
        stride=args.stride or None,
        batch_size=args.batch_size,
        sampling_rate=args.sampling_rate,
        ppk_threshold=args.ppk_threshold,
        spk_threshold=args.spk_threshold,
        det_threshold=args.det_threshold,
        min_peak_dist=args.min_peak_dist,
        combine=args.combine,
        max_events=args.max_events or None,
        channel0=channel0,
    )

    fs = float(args.sampling_rate)
    rows = []
    for idx in picks["ppk"]:
        rows.append({"kind": "P", "sample": int(idx), "time_s": idx / fs})
    for idx in picks["spk"]:
        rows.append({"kind": "S", "sample": int(idx), "time_s": idx / fs})
    for on, off in picks["det"]:
        rows.append({
            "kind": "detection", "sample": int(on), "time_s": on / fs,
            "end_sample": int(off), "end_time_s": off / fs,
        })
    pd.DataFrame(rows).to_csv(args.output, index=False)
    print(
        f"{len(picks['ppk'])} P, {len(picks['spk'])} S, "
        f"{len(picks['det'])} detections -> {args.output}"
    )


if __name__ == "__main__":
    main()
