"""Capture a jax.profiler trace of the training step on the live TPU.

Usage (writes a TensorBoard-loadable trace directory):

    python tools/profile_step.py --model-name seist_l_dpk --batch 256 \
        --steps 10 --out /tmp/seist_trace

Then inspect with TensorBoard's profile plugin, or grep the
``*.trace.json.gz`` event names for the top self-time ops. Complements
bench.py (which reports wall-clock wf/s + MFU but not per-op breakdown).

Env: same knobs as bench.py (BENCH_DTYPE etc. are read from flags here).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> None:
    p = argparse.ArgumentParser(description="TPU train-step profiler")
    p.add_argument("--model-name", default="seist_l_dpk")
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--in-samples", type=int, default=8192)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--dtype", default="fp32", choices=["fp32", "bf16"])
    p.add_argument("--out", default="/tmp/seist_trace")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.models import api
    from seist_tpu.train import (
        build_cyclic_schedule,
        build_optimizer,
        create_train_state,
        make_train_step,
    )

    seist_tpu.load_all()
    print(f"backend: {jax.default_backend()}, devices: {jax.devices()}")

    model = api.create_model(args.model_name, in_samples=args.in_samples)
    variables = api.init_variables(
        model, in_samples=args.in_samples, batch_size=args.batch
    )
    state = create_train_state(
        model,
        variables,
        build_optimizer(
            "adam", build_cyclic_schedule(8e-5, 1e-3, total_steps=10_000)
        ),
    )

    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((args.batch, args.in_samples, 3)), jnp.float32
    )
    y = np.zeros((args.batch, args.in_samples, 3), np.float32)
    y[:, args.in_samples // 4, 1] = 1.0
    y[:, args.in_samples // 2, 2] = 1.0
    y[..., 0] = 1.0 - y[..., 1] - y[..., 2]
    y = jnp.asarray(y)

    spec = taskspec.get_task_spec(args.model_name)
    loss_fn = taskspec.make_loss(args.model_name)
    step_fn = make_train_step(spec, loss_fn, compute_dtype=args.dtype)
    key = jax.random.PRNGKey(0)

    t0 = time.time()
    step = jax.jit(step_fn).lower(state, x, y, key).compile()
    print(f"compiled in {time.time() - t0:.1f}s")
    for _ in range(3):
        state, loss, _ = step(state, x, y, key)
    jax.block_until_ready(state.params)

    with jax.profiler.trace(args.out):
        for _ in range(args.steps):
            state, loss, _ = step(state, x, y, key)
        jax.block_until_ready(state.params)
    print(f"trace written to {args.out}")


if __name__ == "__main__":
    main()
