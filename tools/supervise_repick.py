"""Batch-fleet supervisor: N lease-based repick workers, relaunched
through preemption and crashes, then a fence-audited merge.

The fleet counterpart of tools/supervise_fleet.py (serving) for the
batch plane (docs/FAULT_TOLERANCE.md "Batch fleet faults"): spawn N
``tools/repick_archive --fleet`` workers over one shared lease
directory and keep the fleet converging without human intervention —

* **exit 75** (the PR 2 preemption contract: SIGTERM -> drain the
  current segment -> release the lease -> exit) schedules a RELAUNCH
  after ``--rejoin-delay-s``, without consuming the crash budget; while
  the worker is away its released/expired leases are reclaimed by
  peers, and on rejoin it steals whatever work is still open;
* **any other nonzero exit** (SIGKILL, OOM, a real bug) consumes one
  unit of that worker's ``--retries`` crash budget and relaunches
  immediately; a worker that exhausts its budget is ABANDONED — the
  fleet still finishes, because its leases expire and peers reclaim
  them (the supervisor only fails when EVERY worker is gone);
* after the last worker joins, the reduce runs with the lease store's
  done-fence ledger so the merge audits every segment's fence sidecar
  (a zombie-written segment refuses the merge — ``batch/catalog.py``).

Per-worker fault injection for the chaos lane: ``--fault-env
i:KEY=VALUE`` (repeatable) scopes SEIST_FAULT_BATCH_* knobs to worker
``i`` only; every worker additionally gets ``SEIST_BATCH_WORKER=<i>``
and its own stamp file, so kill/preempt faults fire once across that
worker's relaunches. Worker stdout goes to per-incarnation log files
under ``<out>/logs/`` and the final verdict aggregates every
incarnation's lease counters (acquire/renew/reclaim/fence-reject/
double-commit) — the numbers ``make batch-chaos`` gates on.

    python -m tools.supervise_repick --archive A --out O \
        --model phasenet --workers 3 --lease-dir O/leases

Prints ONE JSON verdict line (role "supervisor").
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from tools.repick_archive import _archive_index, _units_from_cols

PREEMPT_EXIT_CODE = 75  # train.checkpoint contract (import-free: no jax here)

#: lease counter keys aggregated across every worker incarnation
_LEASE_KEYS = (
    "acquires", "reclaims", "renews", "releases", "expires",
    "fence_rejects", "double_commits", "store_errors", "parks",
)


def get_args(argv=None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(
        prog="python -m tools.supervise_repick", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--archive", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--model", default="")
    ap.add_argument("--model-group", default="")
    ap.add_argument("--tasks", default="")
    ap.add_argument("--variant", default="fp32",
                    choices=("fp32", "bf16", "int8"))
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--batches-per-call", type=int, default=4)
    ap.add_argument("--commit-every", type=int, default=4)
    ap.add_argument("--prefetch", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workers", type=int, default=3,
                    help="fleet size (worker indices 0..N-1)")
    ap.add_argument("--lease-dir", required=True,
                    help="shared lease-store directory (created if absent)")
    ap.add_argument("--retries", type=int, default=2,
                    help="crash-relaunch budget per worker (exit-75 "
                    "preempt relaunches never consume it)")
    ap.add_argument("--rejoin-delay-s", type=float, default=0.5,
                    help="delay before relaunching a preempted (exit-75) "
                    "worker — the window in which peers reclaim its units")
    ap.add_argument("--fault-env", action="append", default=[],
                    metavar="I:KEY=VALUE",
                    help="inject KEY=VALUE into worker I's environment "
                    "only (repeatable; scopes SEIST_FAULT_BATCH_* knobs "
                    "per worker for the chaos lane)")
    ap.add_argument("--compile-gate", action="store_true")
    ap.add_argument("--no-merge", action="store_true")
    ap.add_argument("--timeout-s", type=float, default=900.0,
                    help="overall fleet deadline (a wedged fleet must "
                    "fail loudly, not hang CI)")
    args = ap.parse_args(argv)
    if bool(args.model) == bool(args.model_group):
        ap.error("exactly one of --model / --model-group is required")
    return args


def _parse_fault_env(specs: List[str], n_workers: int) -> Dict[int, Dict[str, str]]:
    out: Dict[int, Dict[str, str]] = {i: {} for i in range(n_workers)}
    for spec in specs:
        head, sep, val = spec.partition("=")
        idx_s, sep2, key = head.partition(":")
        if not sep or not sep2 or not key:
            raise SystemExit(f"bad --fault-env '{spec}' (want I:KEY=VALUE)")
        idx = int(idx_s)
        if idx not in out:
            raise SystemExit(
                f"--fault-env '{spec}': worker {idx} out of range "
                f"(fleet has {n_workers})"
            )
        out[idx][key] = val
    return out


def _worker_cmd(args, i: int) -> List[str]:
    cmd = [
        sys.executable, "-m", "tools.repick_archive",
        "--archive", args.archive, "--out", args.out,
        "--variant", args.variant,
        "--batch-size", str(args.batch_size),
        "--batches-per-call", str(args.batches_per_call),
        "--commit-every", str(args.commit_every),
        "--prefetch", str(args.prefetch),
        "--seed", str(args.seed),
        "--fleet", "--lease-dir", args.lease_dir,
        "--lease-store", "dir",
        "--worker-index", str(i),
        "--worker-id", f"w{i}",
        "--no-merge",
    ]
    if args.model:
        cmd += ["--model", args.model]
    if args.model_group:
        cmd += ["--model-group", args.model_group]
    if args.tasks:
        cmd += ["--tasks", args.tasks]
    if args.compile_gate:
        cmd += ["--compile-gate"]
    return cmd


class _Worker:
    """One worker slot: its process, crash budget, incarnation logs,
    and (for exit-75) its scheduled rejoin time."""

    def __init__(self, index: int, budget: int, fault_env: Dict[str, str],
                 log_dir: str, stamp_dir: str):
        self.index = index
        self.budget = budget
        self.fault_env = fault_env
        self.log_dir = log_dir
        self.stamp = os.path.join(stamp_dir, f"w{index}.stamp")
        self.incarnation = 0
        self.proc: Optional[subprocess.Popen] = None
        self.log_f = None
        self.logs: List[str] = []
        self.rejoin_at: Optional[float] = None  # monotonic
        self.done = False
        self.failed = False
        self.relaunches = 0
        self.preempts = 0
        self.crashes = 0

    def launch(self, args) -> None:
        self.incarnation += 1
        if self.incarnation > 1:
            self.relaunches += 1
        path = os.path.join(
            self.log_dir, f"w{self.index}.{self.incarnation:02d}.log"
        )
        self.logs.append(path)
        env = dict(os.environ)
        env["SEIST_BATCH_WORKER"] = str(self.index)
        if self.fault_env:
            env["SEIST_FAULT_STAMP"] = self.stamp
            env.update(self.fault_env)
        self.log_f = open(path, "w")
        self.proc = subprocess.Popen(
            _worker_cmd(args, self.index),
            stdout=self.log_f, stderr=subprocess.STDOUT, env=env,
        )
        self.rejoin_at = None

    def close_log(self) -> None:
        if self.log_f is not None:
            self.log_f.close()
            self.log_f = None


def _drain_verdicts(w: _Worker) -> List[dict]:
    """Every fleet-worker verdict line this slot's incarnations printed
    (a SIGKILL'd incarnation prints none — that's expected)."""
    out = []
    for path in w.logs:
        try:
            with open(path) as f:
                for line in f:
                    try:
                        d = json.loads(line)
                    except ValueError:
                        continue
                    if d.get("role") == "fleet-worker":
                        out.append(d)
        except FileNotFoundError:
            pass
    return out


def main(argv=None) -> int:
    args = get_args(argv)
    t0 = time.monotonic()
    os.makedirs(args.out, exist_ok=True)
    os.makedirs(args.lease_dir, exist_ok=True)
    log_dir = os.path.join(args.out, "logs")
    os.makedirs(log_dir, exist_ok=True)
    fault_env = _parse_fault_env(args.fault_env, args.workers)

    workers = [
        _Worker(i, args.retries, fault_env[i], log_dir, log_dir)
        for i in range(args.workers)
    ]
    for w in workers:
        w.launch(args)

    deadline = t0 + args.timeout_s
    while True:
        live = [w for w in workers if w.proc is not None]
        waiting = [w for w in workers if w.rejoin_at is not None]
        if not live and not waiting:
            break
        if time.monotonic() > deadline:
            for w in live:
                w.proc.kill()
                w.close_log()
            print(json.dumps({
                "ok": False, "role": "supervisor",
                "error": f"fleet deadline {args.timeout_s}s exceeded",
            }))
            return 1
        now = time.monotonic()
        for w in list(waiting):
            if now >= w.rejoin_at:
                w.launch(args)
        for w in list(live):
            rc = w.proc.poll()
            if rc is None:
                continue
            w.proc = None
            w.close_log()
            if rc == 0:
                w.done = True
            elif rc == PREEMPT_EXIT_CODE:
                w.preempts += 1
                w.rejoin_at = time.monotonic() + args.rejoin_delay_s
            elif w.budget > 0:
                w.budget -= 1
                w.crashes += 1
                w.launch(args)
            else:
                w.crashes += 1
                w.failed = True
        time.sleep(0.1)

    finished = [w for w in workers if w.done]
    if not finished:
        print(json.dumps({
            "ok": False, "role": "supervisor",
            "error": "every worker exhausted its relaunch budget",
            "crashes": sum(w.crashes for w in workers),
        }))
        return 1

    lease = {k: 0 for k in _LEASE_KEYS}
    verdicts = 0
    for w in workers:
        for v in _drain_verdicts(w):
            verdicts += 1
            for k in _LEASE_KEYS:
                lease[k] += int(v.get("lease", {}).get(k, 0))

    verdict: Dict[str, Any] = {
        "ok": True,
        "role": "supervisor",
        "workers": args.workers,
        "finished": len(finished),
        "abandoned": [w.index for w in workers if w.failed],
        "relaunches": sum(w.relaunches for w in workers),
        "preempts": sum(w.preempts for w in workers),
        "crashes": sum(w.crashes for w in workers),
        "worker_verdicts": verdicts,
        "lease": lease,
    }
    if not args.no_merge:
        from tools.repick_archive import _merge

        meta, cols = _archive_index(args.archive)
        units = _units_from_cols(cols)
        merged = _merge(args, meta, units, print_verdict=False)
        verdict["rows"] = merged["rows"]
        verdict["units"] = merged["units"]
        verdict["fence_audit"] = merged.get("fence_audit")
    verdict["wall_s"] = round(time.monotonic() - t0, 2)
    print(json.dumps(verdict), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
