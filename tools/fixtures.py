"""DiTing-light-format fixture dataset writer (shared by parity_eval).

Writes a tiny on-disk dataset in the exact format the reference's
``DiTing_light`` reader consumes (ref datasets/diting.py:217-311: single
numeric CSV ``DiTing330km_light.csv`` + per-part HDF5 with ``earthquake/<key>``
datasets of shape (L, 3), keys zero-padded by the reader, diting.py:136-137)
— so BOTH the torch reference and this framework can be evaluated on
byte-identical data.

Traces are generated at exactly ``in_samples`` length: the reference's
``_cut_window`` is a no-op when input length == window size (ref
preprocess.py:207-219 — neither the crop nor the pad branch runs), which
removes the only RNG-dependent step from the eval input path and makes the
two frameworks' model inputs bit-comparable.

Waveforms are noise + damped P/S wavelets (same recipe as
seist_tpu/data/synthetic.py, independent of any reference code).
"""

from __future__ import annotations

import os
import sys

import h5py
import numpy as np
import pandas as pd

_SNR_COLS = [
    f"{c}_{ph}_{kind}_snr"
    for c in "ZNE"
    for ph in "PS"
    for kind in ("amplitude", "power")
]


def write_diting_light_fixture(
    root: str,
    *,
    n_events: int = 240,
    trace_samples: int = 8192,
    fs: int = 50,
    seed: int = 1234,
    n_parts: int = 2,
) -> str:
    """Write the fixture dataset under ``root``; returns ``root``."""
    # Lazy: pulls the shared wavelet recipe from the framework without
    # making this numpy/h5py/pandas-only writer depend on jax at import.
    import sys

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from seist_tpu.data.synthetic import make_wavelet as _wavelet

    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(seed)
    rows = []
    waves = {p: {} for p in range(n_parts)}
    for i in range(n_events):
        part = i % n_parts
        # Short key on purpose: exercises the reader's zero-padding
        # (ref diting.py:136-137).
        key = f"{100 + i}.{part}"
        ppk = int(rng.integers(trace_samples // 8, trace_samples // 2))
        spk = int(ppk + rng.integers(trace_samples // 20, trace_samples // 4))
        data = rng.normal(0, 1.0, size=(trace_samples, 3)).astype(np.float32)
        amp = float(rng.uniform(5.0, 20.0))
        wl = min(trace_samples - spk, trace_samples // 4)
        for c in range(3):
            data[ppk : ppk + wl, c] += amp * _wavelet(
                rng, wl, float(rng.uniform(4, 8)), fs
            )
            data[spk : spk + wl, c] += 1.6 * amp * _wavelet(
                rng, wl, float(rng.uniform(1.5, 4)), fs
            )
        padded = key.split(".")
        padded = padded[0].rjust(6, "0") + "." + padded[1].ljust(4, "0")
        waves[part][padded] = data
        row = {
            "key": key,
            "part": part,
            "ev_id": 1000 + i,
            "mag_type": "ml",
            "evmag": float(np.clip(rng.normal(3.5, 1.0), 0, 8)),
            "st_mag": float(np.clip(rng.normal(3.5, 1.0), 0, 8)),
            "p_pick": ppk,
            "p_clarity": "i" if i % 2 else "e",
            "p_motion": "u" if i % 3 else "d",
            "s_pick": spk,
            "net": "XX",
            "sta_id": i,
            "dis": float(rng.uniform(0, 330)),
            "baz": float(rng.uniform(0, 360)),
            "P_residual": 0.1,
            "S_residual": 0.2,
        }
        for col in _SNR_COLS:
            row[col] = 20.0
        rows.append(row)
    pd.DataFrame(rows).to_csv(os.path.join(root, "DiTing330km_light.csv"))
    for part in range(n_parts):
        with h5py.File(
            os.path.join(root, f"DiTing330km_part_{part}.hdf5"), "w"
        ) as f:
            for key, data in waves[part].items():
                f.create_dataset("earthquake/" + key, data=data)
    return root


def ensure_loader_fixture(n_events: int, in_samples: int) -> str:
    """Idempotent DiTing-light fixture under logs/, shared by the loader
    tools (bench_loader / loader_stage_budget / gil_probe) so they all
    measure the same data. The ``.complete`` sentinel is written only
    after the full fixture lands — the CSV is the FIRST artifact the
    writer produces, so its existence alone would turn an interrupted
    write into a permanently broken cache."""
    import time

    root = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        os.pardir,
        "logs",
        f"loader_fixture_{n_events}x{in_samples}",
    )
    marker = os.path.join(root, ".complete")
    if not os.path.exists(marker):
        t0 = time.perf_counter()
        write_diting_light_fixture(
            root, n_events=n_events, trace_samples=in_samples
        )
        with open(marker, "w") as f:
            f.write("ok\n")
        print(
            f"fixture written in {time.perf_counter() - t0:.1f}s: {root}",
            file=sys.stderr,
        )
    return root


def ensure_packed_fixture(
    n_events: int, in_samples: int, dtype: str = "float32"
) -> str:
    """The packed-shard conversion of :func:`ensure_loader_fixture`'s
    DiTing-light fixture (marker-cached): builds the HDF5 fixture, then
    repacks it with seist_tpu.data.packed.pack_dataset. Returns the
    packed data_dir — train on it with dataset ``packed``. Non-float32
    dtypes land in sibling ``packed_<dtype>`` directories (int8 packs
    change the sidecar schema and may never share a directory with the
    float fixture — the bench_loader dtype ladder packs all three)."""
    import sys
    import time

    src_dir = ensure_loader_fixture(n_events, in_samples)
    suffix = "" if dtype == "float32" else f"_{dtype}"
    out = os.path.join(src_dir, "packed" + suffix)
    marker = os.path.join(out, ".complete")
    if not os.path.exists(marker):
        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        import seist_tpu
        from seist_tpu.data.packed import pack_dataset
        from seist_tpu.registry import DATASETS

        seist_tpu.load_all()
        src = DATASETS.create(
            "diting_light",
            seed=0,
            mode="train",
            data_dir=src_dir,
            shuffle=False,
            data_split=False,
        )
        t0 = time.perf_counter()
        pack_dataset(src, out, dtype=dtype)
        with open(marker, "w") as f:
            f.write("ok\n")
        print(
            f"packed fixture written in {time.perf_counter() - t0:.1f}s: {out}",
            file=sys.stderr,
        )
    return out
