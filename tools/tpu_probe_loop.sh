#!/bin/bash
# Round-3: block until the TPU tunnel answers, then exit 0.
# Driven interactively by the session (no fire-and-forget work here).
probe() {
  timeout 70 python -c "
import jax, jax.numpy as jnp
r = jax.jit(lambda a, b: a @ b)(jnp.ones((128,128)), jnp.ones((128,128)))
r.block_until_ready(); print('UP')" 2>/dev/null | grep -q UP
}
n=0
until probe; do
  n=$((n+1))
  echo "probe $n down $(date -u +%H:%M:%SZ)"
  sleep 180
done
echo "TUNNEL UP $(date -u +%H:%M:%SZ)"
