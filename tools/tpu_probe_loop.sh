#!/bin/bash
# Round-3: block until the TPU tunnel answers, then exit 0.
# Driven interactively by the session (no fire-and-forget work here).
# Lines carry FULL ISO dates: bench.py's fail-fast path only trusts a
# 'down' line whose own timestamp is fresh (HH:MM:SS alone would match
# the same wall-clock window on any later day).
probe() {
  timeout 70 python -c "
import jax, jax.numpy as jnp
r = jax.jit(lambda a, b: a @ b)(jnp.ones((128,128)), jnp.ones((128,128)))
r.block_until_ready(); print('UP')" 2>/dev/null | grep -q UP
}
n=0
until probe; do
  n=$((n+1))
  echo "probe $n down $(date -u +%FT%TZ)"
  sleep 180
done
echo "TUNNEL UP $(date -u +%FT%TZ)"
