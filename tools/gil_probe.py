"""Measure the GIL-held fraction of the input pipeline.

This sandbox host has ONE core, so loader thread scaling cannot be shown
by wall clock here. What CAN be measured — and is what actually bounds
thread scaling on a real multi-core TPU-VM host — is how much of the
loader's wall time holds the GIL: a probe thread runs a pure-Python
counter loop (always needs the GIL) while the main thread drives the
real-format loader. The probe's achieved rate, relative to its idle-host
baseline, is the fraction of time the GIL was available:

    gil_available = probe_rate_during_load / probe_rate_idle
    gil_held      = 1 - gil_available
    max useful loader threads ~= 1 / gil_held      (Amdahl on the GIL)

h5py reads and numpy array math release the GIL; the Python glue between
them does not. Prints one JSON line.

    python tools/gil_probe.py [n_batches] [batch]
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


class _Counter(threading.Thread):
    """Tight pure-Python loop; its rate tracks GIL availability."""

    def __init__(self):
        super().__init__(daemon=True)
        self.count = 0
        self.stop = False

    def run(self):
        c = 0
        while not self.stop:
            c += 1
            if not c % 1024:
                self.count = c
        self.count = c


def _probe(seconds: float, work=None) -> float:
    t = _Counter()
    t.start()
    t0 = time.perf_counter()
    if work is None:
        time.sleep(seconds)
    else:
        work()
    dt = time.perf_counter() - t0
    t.stop = True
    t.join(timeout=5)
    return t.count / dt


def main() -> None:
    n_batches = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 250
    in_samples = int(os.environ.get("BENCH_SAMPLES", 8192))

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.data import pipeline
    from tools.fixtures import ensure_loader_fixture, ensure_packed_fixture

    seist_tpu.load_all()
    # BENCH_DATASET: diting_light (default) or packed — the packed-shard
    # repack of the same fixture (GIL profile of the memmap read path).
    dataset_name = os.environ.get("BENCH_DATASET", "diting_light")
    if dataset_name == "packed":
        data_dir = ensure_packed_fixture(1000, in_samples)
    elif dataset_name == "diting_light":
        data_dir = ensure_loader_fixture(1000, in_samples)
    else:
        raise SystemExit(f"unknown BENCH_DATASET {dataset_name!r}")

    spec = taskspec.get_task_spec("seist_l_dpk")
    ds = pipeline.from_task_spec(
        spec,
        dataset_name,
        "train",
        seed=0,
        in_samples=in_samples,
        augmentation=True,
        data_dir=data_dir,
    )
    # Inline fetch (num_workers=1, main thread blocked on the pool) would
    # hide GIL handoffs in pool machinery; drive __getitem__ directly.
    for i in range(10):
        ds[i]  # warm

    done = [0]

    def work():
        k = done[0]
        for _ in range(n_batches):
            for _ in range(batch):
                ds[k % len(ds)]
                k += 1
        done[0] = k

    # Calibration control: a deliberately GIL-BOUND workload of similar
    # wall time. Raw rates on a 1-core VM confound CPU contention with GIL
    # contention; the control pins the "fully GIL-held" end of the scale.
    wall = [1.0]

    def gil_bound():
        t_end = time.perf_counter() + wall[0]
        x = 0
        while time.perf_counter() < t_end:
            for _ in range(10000):
                x += 1

    # Interleave idle/loaded/control rounds and take medians: the VM's
    # effective CPU speed drifts minute to minute (observed 1.6x between
    # adjacent runs), so the three phases must sample the same periods.
    idle_rates, loaded_rates, control_rates = [], [], []
    t_work = 0.0
    for _ in range(3):
        idle_rates.append(_probe(1.5))
        t0 = time.perf_counter()
        loaded_rates.append(_probe(0.0, work=work))
        wall[0] = time.perf_counter() - t0
        t_work += wall[0]
        control_rates.append(_probe(0.0, work=gil_bound))

    med = lambda xs: sorted(xs)[len(xs) // 2]
    idle_rate, loaded_rate, control_rate = (
        med(idle_rates),
        med(loaded_rates),
        med(control_rates),
    )
    dt = t_work

    # Linear calibration: probe rate idle_rate => GIL held 0; control_rate
    # => GIL held ~1 (the control holds it except at switch intervals).
    span = max(idle_rate - control_rate, 1.0)
    held = min(1.0, max(0.0, (idle_rate - loaded_rate) / span))
    print(
        json.dumps(
            {
                "metric": "loader_gil_held_fraction",
                "value": round(held, 3),
                "unit": "fraction (calibrated)",
                "dataset": dataset_name,
                "probe_idle_rate": round(idle_rate),
                "probe_loaded_rate": round(loaded_rate),
                "probe_gil_bound_control_rate": round(control_rate),
                "loader_wfs_during_probe": round(done[0] / dt, 1),
                "max_useful_threads": round(1.0 / max(held, 1e-3), 1),
                "note": (
                    "probe thread competes with the loader for the GIL on 1 "
                    "core; rate is calibrated between an idle host (held=0) "
                    "and a pure-Python GIL-bound control (held~1). h5py/"
                    "numpy/native stages release the GIL"
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
