"""Distributed-tracing smoke (`make trace-smoke`): a real 2-replica
fleet + router under bench_serve, then prove the trace plane end to end:

1. every replica runs with an injected 50 ms forward delay and the
   router hedges at 15 ms, so every request's trace is *hedged* (two
   racing attempts) — the hardest shape to account for;
2. bench_serve records every request's trace id (``--trace-log``);
3. a hedged trace is stitched across router + both replicas
   (tools/trace_report.py) and its span-tree total must land within
   10% of the latency the CLIENT measured for that same request — the
   acceptance bar that the decomposition actually adds up;
4. the stitched tree must contain the queue-wait and device-forward
   spans (with the AOT program key) from the serving replica;
5. the supervisor's ``GET /fleet/metrics.json`` must aggregate router +
   both replicas (the fleet pane rides the same scrape machinery).

Prints one JSON verdict line; exit 0 = pass, 1 = fail.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

_TOOLS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(_TOOLS)
sys.path.insert(0, REPO)
sys.path.insert(0, _TOOLS)

WINDOW = 256
HEDGE_MS = 15.0
SLOW_MS = 50  # injected per-forward delay: every request out-waits the hedge
TOLERANCE = 0.10
WARM_TIMEOUT_S = 300.0


def _log(msg: str) -> None:
    print(f"[trace-smoke] {msg}", file=sys.stderr, flush=True)


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _drain(pipe, buf):
    # A dead drain thread would let the 64 KB pipe buffer wedge the whole
    # fleet on its next write (threadlint thread-target-raises).
    try:
        for line in pipe:
            buf.append(line)
    except Exception as e:  # noqa: BLE001 — log-and-die is the contract
        _log(f"pipe drain died: {e!r}")


def _get_json(url: str, path: str):
    from seist_tpu.serve.router import _http_request

    status, _, body = _http_request(url, "GET", path, timeout_s=10.0)
    return status, json.loads(body.decode())


def main() -> int:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["SEIST_FAULT_SERVE_SLOW_MS"] = str(SLOW_MS)
    proc = subprocess.Popen(
        [
            sys.executable, os.path.join(_TOOLS, "supervise_fleet.py"),
            "--replicas", "2",
            "--base-port", str(_free_port()),
            "--router-port", "0",
            "--probe-interval-s", "0.3",
            "--hedge-ms", str(HEDGE_MS),
            "--request-timeout-s", "30",
            "--fleet-scrape-interval-s", "1.0",
            "--drain-timeout-s", "20",
            "--",
            sys.executable, os.path.join(REPO, "main.py"), "serve",
            "--model", "phasenet=",
            "--window", str(WINDOW),
            "--max-batch", "4",
            "--max-delay-ms", "5",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True,
    )
    err_buf: list = []
    threading.Thread(target=_drain, args=(proc.stderr, err_buf),
                     daemon=True).start()
    router_url = None
    for _ in range(50):
        line = proc.stdout.readline()
        if not line:
            break
        m = re.search(r"ROUTER=(http://[\d.]+:\d+)", line)
        if m:
            router_url = m.group(1)
            break
    if router_url is None:
        proc.kill()
        _log("FAIL: no ROUTER line from supervise_fleet\n"
             + "".join(err_buf[-50:]))
        return 1
    threading.Thread(target=_drain, args=(proc.stdout, []),
                     daemon=True).start()
    _log(f"router at {router_url}")

    verdict = {"ok": False}
    try:
        # ---- wait for both replicas probed-ready (first run compiles)
        deadline = time.monotonic() + WARM_TIMEOUT_S
        while time.monotonic() < deadline:
            try:
                _, payload = _get_json(router_url, "/router/replicas")
                states = [r["probe_state"]
                          for r in payload.get("replicas", [])]
                if states.count("ok") >= 2:
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.25)
        else:
            raise AssertionError("fleet never reached 2 ready replicas")
        replica_urls = [r["url"] for r in payload["replicas"]]
        _log(f"replicas ready: {replica_urls}")

        # ---- drive load, recording every request's trace id
        import tempfile

        import bench_serve

        with tempfile.TemporaryDirectory() as tmp:
            out = os.path.join(tmp, "bench.json")
            tlog = os.path.join(tmp, "traces.jsonl")
            rc = bench_serve.main([
                "--url", router_url,
                "--model-name", "phasenet",
                "--window", str(WINDOW),
                "--requests", "24",
                "--concurrency", "4",
                "--timeout-ms", "60000",
                "--output", out,
                "--trace-log", tlog,
            ])
            with open(out) as f:
                bench = json.load(f)
            client_lat = {}
            with open(tlog) as f:
                for line in f:
                    rec = json.loads(line)
                    client_lat[rec["trace_id"]] = rec
        assert rc == 0 and bench["errors"] == 0, (
            f"bench failed rc={rc}: {bench}"
        )
        assert bench["trace_exemplars"]["slowest"], "no exemplars recorded"

        # ---- find a hedged trace the client also measured
        _, idx = _get_json(router_url, "/traces")
        hedged = [
            t for t in idx["traces"]
            if "hedged" in t["flags"] and t["trace_id"] in client_lat
            and client_lat[t["trace_id"]]["status"] == 200
        ]
        assert hedged, (
            f"no hedged traces on the router "
            f"(hedge_ms={HEDGE_MS}, slow_ms={SLOW_MS}): {idx['traces'][:5]}"
        )
        # The slowest hedged request: relative overheads are smallest.
        pick = max(
            hedged, key=lambda t: client_lat[t["trace_id"]]["latency_ms"]
        )
        trace_id = pick["trace_id"]
        client_ms = client_lat[trace_id]["latency_ms"]

        # ---- stitch across the fleet and check the acceptance bar
        import trace_report

        st = trace_report.stitch_from_endpoints(
            trace_id, [router_url] + replica_urls
        )
        print(st.format(), file=sys.stderr, flush=True)
        assert st.spans, "stitched trace is empty"
        assert len(st.processes()) >= 2, (
            f"trace did not cross processes: {st.processes()}"
        )
        assert st.find("queue_wait"), "no queue_wait span in the tree"
        forwards = st.find("forward")
        assert forwards, "no device-forward span in the tree"
        assert any(
            (s.get("annotations") or {}).get("program")
            for s in forwards
        ), f"forward span lacks the program key: {forwards}"
        assert "hedged" in st.flags, st.flags
        total = st.total_ms
        rel = abs(total - client_ms) / client_ms
        assert rel <= TOLERANCE, (
            f"span tree total {total:.1f} ms vs client {client_ms:.1f} ms "
            f"({rel:.1%} > {TOLERANCE:.0%})"
        )

        # ---- the fleet pane aggregates router + both replicas
        _, fleet = _get_json(router_url, "/fleet/metrics.json")
        assert fleet["up"] >= 3, fleet["sources"]
        agg = fleet["aggregate"]
        assert any(
            k.startswith("serve_batcher_submitted")
            for k in agg["collectors"]
        ), sorted(agg["collectors"])[:10]

        verdict = {
            "ok": True,
            "trace_id": trace_id,
            "client_ms": client_ms,
            "span_tree_total_ms": round(total, 3),
            "rel_err": round(rel, 4),
            "processes": st.processes(),
            "flags": st.flags,
            "fleet_sources_up": fleet["up"],
        }
        return 0
    except AssertionError as e:
        verdict = {"ok": False, "error": str(e)}
        _log(f"FAIL: {e}")
        return 1
    finally:
        print(json.dumps(verdict), flush=True)
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()


if __name__ == "__main__":
    sys.exit(main())
