"""int8 end-to-end smoke: pack -> direct ingest -> repick -> parity gate.

The ``make quant-smoke`` lane (docs/DATA.md "Storage dtype"): proves the
whole ISSUE 18 quantization ladder on one tiny synthetic event set, in
one process, in seconds:

1. pack the SAME synthetic source twice — fp32 (format v2) and int8
   (format v3, per-row scale sidecar) — and gate the measured on-disk
   bytes at <= 0.55x fp32;
2. re-pick both archives inline (``tools.repick_archive``): fp32
   weights on fp32 shards vs the int8 weight variant on int8 shards
   through the stage_raw device-dequant path, both under the
   CompileBudget gate (zero post-warm-up compiles);
3. gate DECISION parity: the fraction of catalog rows whose pick
   decisions match the fp32 reference at the repo's pick-residual
   convention (positions within ``--time-threshold`` 0.1 s, same pick
   counts — seist_tpu/cli.py eval uses the same tolerance). The smoke
   decodes at threshold 0.4: a FRESH-INIT phasenet emits near-uniform
   softmax (~0.33/class), so the serving default 0.3 sits inside the
   init noise band where every pick is a coin flip — 0.4 gates real
   peaks, which a trained checkpoint produces regardless;
4. mechanism proof for the >=1.7x throughput acceptance on the CPU
   backend: the repick host feed is bytes-bound, so the gate measures
   the engine's per-call host path — PackedRawStore fill + device_put
   — fp32 vs int8 stage_raw at the engine's b64x2 rows-per-call on the
   shared bench_loader fixture (512 events x 8192 samples), min-of-5
   trials against scheduler noise. The end-to-end TPU run stays
   flagged ``tpu_run: pending`` until a chip runs it.

Prints ONE JSON verdict line; exit 0 iff every gate held. With
``--out FILE`` also writes the BENCH-style headline
(``BENCH_repick_r02.json`` is the committed artifact).
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# Tiny repick geometry (mirrors tools/repick_smoke.py).
N_EVENTS = 48
TRACE = 256
SPS = 16
BATCH = 4
BPC = 2
COMMIT = 2

# Decision-parity convention (docstring point 3): decode at 0.4 (above
# the fresh-init softmax noise band), match picks at the repo's 0.1 s
# residual tolerance (cli.py --time-threshold) at the packs' 50 Hz.
PICK_THR = 0.4
PICK_TOL = int(0.1 * 50)

# Mechanism feed bench (docstring point 4): the bench_loader fixture
# (512 x 8192, marker-cached under logs/), fill + device_put at the
# engine's b64x2 = 128 rows per call, min-of-5 trials.
MECH_EVENTS = 512
MECH_TRACE = 8192
MECH_BATCH = 128
MECH_PASSES = 2
MECH_TRIALS = 5

PARITY_MIN = 0.95
SPEEDUP_MIN = 1.7
BYTES_MAX = 0.55


def _pack(root: str, name: str, dtype: str, n_events: int, trace: int,
          sps: int):
    from seist_tpu.data.packed import PackSource, pack_sources

    return pack_sources(
        [PackSource(
            name="synthetic",
            dataset_kwargs={
                "num_events": n_events, "trace_samples": trace,
                "cache": False,
            },
        )],
        os.path.join(root, name),
        samples_per_shard=sps,
        dtype=dtype,
    )


def _repick(archive: str, out: str, variant: str) -> dict:
    """Inline single-process repick; returns the worker verdict."""
    from tools.repick_archive import main as repick_main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = repick_main([
            "--archive", archive, "--out", out, "--model", "phasenet",
            "--batch-size", str(BATCH), "--batches-per-call", str(BPC),
            "--commit-every", str(COMMIT), "--variant", variant,
            "--compile-gate",
            "--ppk-threshold", str(PICK_THR),
            "--spk-threshold", str(PICK_THR),
        ])
    if rc != 0:
        raise SystemExit(
            f"repick({variant}) rc={rc}: {buf.getvalue()[-400:]}"
        )
    for line in reversed(buf.getvalue().strip().splitlines()):
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if d.get("role") == "worker":
            return d
    raise SystemExit(f"no worker verdict: {buf.getvalue()[-400:]}")


def _decisions(out_dir: str) -> list:
    rows = []
    with open(os.path.join(out_dir, "catalog.jsonl")) as f:
        for line in f:
            r = json.loads(line)
            rows.append({
                k: v for k, v in r.items() if k not in ("key", "row")
            })
    return rows


def _rows_match(a: dict, b: dict) -> bool:
    """Decision-level row equality: same heads, same pick/detection
    counts, positions within PICK_TOL samples (0.1 s), scalar heads
    within 5% relative."""
    if set(a) != set(b):
        return False
    for key, va in a.items():
        vb = b[key]
        if isinstance(va, list):
            if len(va) != len(vb):
                return False
            for x, y in zip(va, vb):
                if isinstance(x, list):  # det [start, end] windows
                    if len(x) != len(y) or any(
                        abs(p - q) > PICK_TOL for p, q in zip(x, y)
                    ):
                        return False
                elif abs(x - y) > PICK_TOL:
                    return False
        elif isinstance(va, (int, float)):
            if abs(va - vb) > max(1e-6, 0.05 * abs(va)):
                return False
        elif va != vb:
            return False
    return True


def _feed_ms_per_wf(archive: str, stage_raw: bool) -> float:
    """The engine's per-call host feed — PackedRawStore fill +
    device_put of what was staged — at MECH_BATCH rows per call.
    Min-of-MECH_TRIALS full passes (least-noise estimate of the true
    per-wf cost on a shared-CPU box)."""
    import jax
    import numpy as np

    from seist_tpu.data import pipeline
    from seist_tpu.data.ingest import PackedRawStore

    sds = pipeline.SeismicDataset(
        "packed", "train", seed=0, data_dir=archive,
        input_names=[], label_names=[], task_names=[],
        in_samples=MECH_TRACE, augmentation=False, shuffle=False,
        data_split=False,
    )
    store = PackedRawStore.build(
        sds, batch_size=MECH_BATCH, stage_raw=stage_raw
    )
    chunks = [
        np.arange(b * MECH_BATCH, (b + 1) * MECH_BATCH)
        for b in range(store.n_raw // MECH_BATCH)
    ]
    store.row_batch(chunks[0])  # warm memmaps / page cache
    best = float("inf")
    for _ in range(MECH_TRIALS):
        t0 = time.perf_counter()
        n = 0
        for _ in range(MECH_PASSES):
            for c in chunks:
                rows = store.row_batch(c)
                dev = jax.device_put(
                    (rows["data"], rows["data_scale"])
                    if stage_raw else rows["data"]
                )
                jax.block_until_ready(dev)
                n += len(c)
        best = min(best, (time.perf_counter() - t0) * 1e3 / n)
    return best


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.quant_smoke")
    ap.add_argument("--out", default="", help="also write the BENCH-style "
                    "headline JSON here (BENCH_repick_r02.json)")
    args = ap.parse_args(argv)

    from seist_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()
    import jax

    import seist_tpu
    from seist_tpu.utils.misc import enable_compile_cache

    seist_tpu.load_all()
    enable_compile_cache()
    t0 = time.monotonic()
    root = tempfile.mkdtemp(prefix="quant_smoke_")

    # 1. pack fp32 + int8 of the same source; bytes gate.
    s_f32 = _pack(root, "f32", "float32", N_EVENTS, TRACE, SPS)
    s_i8 = _pack(root, "i8", "int8", N_EVENTS, TRACE, SPS)
    bytes_ratio = s_i8["on_disk_bytes"] / max(s_f32["on_disk_bytes"], 1)

    # 2. repick both (inline, compile-gated).
    v_f32 = _repick(os.path.join(root, "f32"),
                    os.path.join(root, "cat_f32"), "fp32")
    v_i8 = _repick(os.path.join(root, "i8"),
                   os.path.join(root, "cat_i8"), "int8")
    compiles = (
        v_f32.get("compiles_after_warmup", -1)
        + v_i8.get("compiles_after_warmup", -1)
    )

    # 3. decision parity at the pick-residual tolerance.
    ref = _decisions(os.path.join(root, "cat_f32"))
    got = _decisions(os.path.join(root, "cat_i8"))
    same = sum(1 for a, b in zip(ref, got) if _rows_match(a, b))
    parity = same / max(len(ref), 1)

    # 4. host-feed mechanism bench (bytes-bound CPU proof) on the
    # shared bench_loader fixture — same data BENCH_loader_r02 measures.
    from tools.fixtures import ensure_packed_fixture

    mech_f32 = ensure_packed_fixture(MECH_EVENTS, MECH_TRACE)
    mech_i8 = ensure_packed_fixture(MECH_EVENTS, MECH_TRACE, dtype="int8")
    f32_ms = _feed_ms_per_wf(mech_f32, False)
    i8_ms = _feed_ms_per_wf(mech_i8, True)
    feed_speedup = f32_ms / i8_ms

    verdict = {
        "ok": bool(
            len(ref) == len(got) == N_EVENTS
            and bytes_ratio <= BYTES_MAX
            and parity >= PARITY_MIN
            and feed_speedup >= SPEEDUP_MIN
            and compiles == 0
            and v_f32["ok"] and v_i8["ok"]
        ),
        "bytes_vs_fp32": round(bytes_ratio, 4),
        "gate_max_bytes": BYTES_MAX,
        "decision_parity": round(parity, 4),
        "decision_rows": f"{same}/{len(ref)}",
        "pick_tol_samples": PICK_TOL,
        "gate_min_parity": PARITY_MIN,
        "feed_speedup_int8_vs_fp32": round(feed_speedup, 2),
        "feed_ms_per_wf": {
            "fp32": round(f32_ms, 4), "int8_raw": round(i8_ms, 4),
        },
        "gate_min_speedup": SPEEDUP_MIN,
        "compiles_after_warmup": compiles,
        "int8_program": v_i8.get("warmup_program", ""),
        "tpu_run": "pending",
        "backend": jax.default_backend(),
        "wall_s": round(time.monotonic() - t0, 1),
    }
    print(json.dumps(verdict))
    if args.out:
        headline = {
            "metric": "phasenet_repick_int8_ladder",
            "value": verdict["feed_speedup_int8_vs_fp32"],
            "unit": "host-feed (fill+device_put) speedup int8 shards vs "
                    "fp32 (bytes-bound mechanism; end-to-end chip run "
                    "pending)",
            "gate_min_speedup": SPEEDUP_MIN,
            "bytes_vs_fp32": verdict["bytes_vs_fp32"],
            "gate_max_bytes": BYTES_MAX,
            "decision_parity": verdict["decision_parity"],
            "pick_tol_samples": PICK_TOL,
            "gate_min_parity": PARITY_MIN,
            "feed_ms_per_wf": verdict["feed_ms_per_wf"],
            "stage_ms_per_wf_int8": v_i8.get("stage_ms_per_wf", {}),
            "stage_ms_per_wf_fp32": v_f32.get("stage_ms_per_wf", {}),
            "compiles_after_warmup": compiles,
            "aot_program": verdict["int8_program"],
            "config": {
                "model": "phasenet", "events": N_EVENTS, "window": TRACE,
                "batch": BATCH, "batches_per_call": BPC,
                "pick_threshold": PICK_THR,
                "mech_events": MECH_EVENTS, "mech_window": MECH_TRACE,
                "mech_rows_per_call": MECH_BATCH,
            },
            "device": jax.devices()[0].platform,
            "backend": jax.default_backend(),
            "tpu_run": "pending",
            "measured_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "pass": verdict["ok"],
        }
        with open(args.out, "w") as f:
            f.write(json.dumps(headline) + "\n")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
