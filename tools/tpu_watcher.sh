#!/bin/bash
# Wait for the TPU tunnel, then run the conv-lowering A/B + missing matrix
# configs. Results -> /root/repo/tools/ab_results.log (JSON lines).
cd /root/repo
probe() {
  timeout 70 python -c "
import jax, jax.numpy as jnp
r = jax.jit(lambda a, b: a @ b)(jnp.ones((128,128)), jnp.ones((128,128)))
r.block_until_ready(); print('UP')" 2>/dev/null | grep -q UP
}
echo "watcher start $(date)" >> /root/repo/tools/ab_results.log
until probe; do sleep 300; done
echo "tunnel UP $(date)" >> /root/repo/tools/ab_results.log

run() {  # run <label> <env...>
  label="$1"; shift
  echo "=== $label $(date)" >> /root/repo/tools/ab_results.log
  env "$@" BENCH_STEPS=10 BENCH_PROBE_ATTEMPTS=1 BENCH_PROBE_TIMEOUT=120 \
    python bench.py 2>/dev/null >> /root/repo/tools/ab_results.log
}

run "seist_s NEW (shift+dense)" BENCH_MODEL=seist_s_dpk BENCH_BATCH=256
run "seist_s OLD (grouped)" BENCH_MODEL=seist_s_dpk BENCH_BATCH=256 \
  SEIST_DWCONV_IMPL=grouped SEIST_GCONV_IMPL=grouped
run "seist_l NEW (shift+dense)" BENCH_MODEL=seist_l_dpk BENCH_BATCH=256
run "seist_l OLD (grouped)" BENCH_MODEL=seist_l_dpk BENCH_BATCH=256 \
  SEIST_DWCONV_IMPL=grouped SEIST_GCONV_IMPL=grouped
run "seist_s einsum-gconv" BENCH_MODEL=seist_s_dpk BENCH_BATCH=256 \
  SEIST_GCONV_IMPL=einsum
echo "AB DONE $(date)" >> /root/repo/tools/ab_results.log

python tools/bench_matrix.py --steps 15 \
  --only seist_l_emg,seist_l_baz,seist_l_dis >> /root/repo/tools/ab_results.log 2>&1
echo "ALL DONE $(date)" >> /root/repo/tools/ab_results.log
