#!/bin/bash
# Wait for the TPU tunnel, then capture whatever measurements are pending.
# Round-2 (2026-07-30) pending list: the seist_l_dis bf16 matrix row
# (tunnel wedged mid-sweep) and a fresh default-config bench.py line.
# Results -> /root/repo/tools/ab_results.log (JSON lines) and the matrix
# JSON files. Edit the "pending work" block as needs change; the probe /
# wait loop is the reusable part.
cd /root/repo
probe() {
  timeout 70 python -c "
import jax, jax.numpy as jnp
r = jax.jit(lambda a, b: a @ b)(jnp.ones((128,128)), jnp.ones((128,128)))
r.block_until_ready(); print('UP')" 2>/dev/null | grep -q UP
}
echo "watcher start $(date)" >> /root/repo/tools/ab_results.log
until probe; do sleep 300; done
echo "tunnel UP $(date)" >> /root/repo/tools/ab_results.log

# ---- pending work ----
BENCH_DTYPE=bf16 python tools/bench_matrix.py --steps 15 \
  --only seist_l_dis --out tools/bench_matrix_bf16.json \
  >> /root/repo/tools/ab_results.log 2>&1
echo "=== default bench $(date)" >> /root/repo/tools/ab_results.log
BENCH_STEPS=15 BENCH_PROBE_ATTEMPTS=1 BENCH_PROBE_TIMEOUT=120 \
  python bench.py 2>/dev/null >> /root/repo/tools/ab_results.log
echo "ALL DONE $(date)" >> /root/repo/tools/ab_results.log
