"""Replay-divergence smoke: the whole det-critical pipeline, twice,
under perturbation — every digest pinned byte-identical.

The ``make replay-smoke`` lane (docs/STATIC_ANALYSIS.md "Determinism
analysis"): detlint's static rules hunt the PATTERNS that break
byte-identity on a different machine; this lane proves the CONTRACTS
hold under the perturbations those patterns are sensitive to. Each
child subprocess runs the full pipeline under one perturbation tuple:

* ``PYTHONHASHSEED`` — set/dict hash order (the axis
  `set-or-dict-order-dependence` guards);
* pack/repick worker count — reduction pairing + shard scheduling (the
  `float-reduction-order` axis, and PR 14/15's N-worker contracts);
* shuffled directory inode order via the ``relink_tree`` shim — readdir
  order (the `unsorted-dir-enumeration` axis), exercised on BOTH the
  pack-resume sidecar scan and the journal-restore directory scan (the
  reversed-listdir regression).

Per child: pack a synthetic archive -> delete the last sidecar +
meta.json and RESUME (digests must not move) -> repick the archive to a
catalog -> write per-station journals in hash-order (deliberately) and
restore them from a reversed-relink copy -> append + replay an alert
WAL. The parent cross-compares every digest across children and prints
ONE JSON verdict line; exit 0 iff all byte-identical.

    python -m tools.replay_smoke                # the make lane (2 children)
    python -m tools.replay_smoke --full         # full 2x2 matrix
    python -m tools.replay_smoke --skip-repick  # no model work (fast loop)
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

from tools.detlint.runtime import combine, digest_file, digest_tree, relink_tree

# Same geometry as tools/repick_smoke.py ON PURPOSE: the repick phase
# lowers the same programs, so the persistent XLA compile cache is warm
# for every child after the first.
N_EVENTS = 44
TRACE = 256
SPS = 16
BATCH = 4
BPC = 2
COMMIT = 1

#: (PYTHONHASHSEED, workers, reversed-relink) per child. The default
#: diagonal covers both hash seeds, both worker counts, and the
#: reversed-listdir regression; --full runs the whole matrix.
VARIANTS = ((0, 1, False), (1, 2, True))
VARIANTS_FULL = ((0, 1, False), (0, 2, True), (1, 1, True), (1, 2, False))


# --------------------------------------------------------------- child phases
def _pack(archive: str, workers: int):
    from seist_tpu.data.packed import PackSource, pack_sources

    pack_sources(
        [PackSource(
            name="synthetic",
            dataset_kwargs={
                "num_events": N_EVENTS, "trace_samples": TRACE,
                "cache": False,
            },
        )],
        archive,
        num_workers=workers,
        samples_per_shard=SPS,
    )


def _resume_exercise(archive: str, workers: int, relink: bool) -> bool:
    """Delete the pack commit point (meta.json) plus the LAST shard's
    sidecar, then resume — optionally inside a reversed-relink copy of
    the archive, so the resume scan walks a different readdir order.
    Returns whether the resumed tree digests identical to the original."""
    before = digest_tree(archive)
    target = archive
    if relink:
        target = archive + "_rev"
        relink_tree(archive, target)
    os.remove(os.path.join(target, "meta.json"))
    last_sidecar = sorted(
        f for f in os.listdir(target) if f.endswith(".idx.npz")
    )[-1]
    os.remove(os.path.join(target, last_sidecar))
    _pack(target, workers)
    return digest_tree(target) == before


def _repick(archive: str, out: str, workers: int) -> str:
    from tools.repick_archive import main as repick_main

    base = [
        "--archive", archive, "--out", out, "--model", "phasenet",
        "--batch-size", str(BATCH), "--batches-per-call", str(BPC),
        "--commit-every", str(COMMIT),
    ]
    if workers <= 1:
        rc = repick_main(base)
        assert rc == 0, f"serial repick rc={rc}"
    else:
        # Multi-worker children ride the FLEET path (lease + fencing
        # token, batch/fleet.py) so the divergence grid also proves the
        # lease plane costs zero bytes: worker 0 work-steals every unit,
        # worker 1 joins late and finds only done markers — the merge
        # audits each segment's fence sidecar against the done ledger.
        lease_dir = os.path.join(out, "leases")
        for i in range(workers):
            rc = repick_main(base + [
                "--fleet", "--lease-dir", lease_dir, "--lease-store", "dir",
                "--worker-index", str(i), "--worker-id", f"w{i}",
                "--no-merge",
            ])
            assert rc == 0, f"fleet repick worker {i} rc={rc}"
        rc = repick_main([
            "--archive", archive, "--out", out, "--merge-only",
            "--lease-dir", lease_dir,
        ])
        assert rc == 0, f"repick merge rc={rc}"
    return digest_file(os.path.join(out, "catalog.jsonl"))


def _journal_digest(root: str) -> str:
    """Digest of the RESTORED pick-stream state: station enumeration
    order + every deserialized snapshot, not the npz container bytes
    (compression is an implementation detail; the restored state is the
    contract)."""
    from seist_tpu.stream.journal import StationJournal

    j = StationJournal(root, model="replay")
    h = hashlib.sha256()
    for sid in j.station_ids():
        state = j.load(sid)
        h.update(sid.encode())
        h.update(json.dumps(state["meta"], sort_keys=True).encode())
        for k in sorted(state["arrays"]):
            a = state["arrays"][k]
            h.update(f"{k}:{a.dtype}:{a.shape}".encode())
            h.update(a.tobytes())
    return h.hexdigest()


def _journal_exercise(out: str) -> Dict[str, object]:
    import numpy as np

    from seist_tpu.stream.journal import AlertWAL, StationJournal

    jroot = os.path.join(out, "journal")
    j = StationJournal(jroot, model="replay")
    # Deliberate perturbation: write order is SET-ITERATION order, i.e.
    # it varies with this child's PYTHONHASHSEED — the journal contract
    # must erase write order entirely.
    # detlint: disable=set-or-dict-order-dependence -- the hash-order
    # write sequence IS the perturbation under test; per-station content
    # below is a pure function of the station id.
    for sid in {f"ST{i:02d}" for i in range(8)}:
        idx = int(sid[2:])
        j.write(sid, {
            "meta": {"station": sid, "seq": idx * 7, "sps": 100},
            "arrays": {
                "ring": (np.linspace(0.0, 1.0, 64) + idx).astype(np.float32),
                "watermark": np.array([idx * 100], np.int64),
            },
        })
    restored = _journal_digest(jroot)
    # Reversed-listdir regression for the journal dir scan.
    jrev = jroot + "_rev"
    relink_tree(jroot, jrev)
    rev_identical = _journal_digest(jrev) == restored

    wal = AlertWAL(os.path.join(out, "alerts.jsonl"))
    for i in range(6):
        wal.append({"event_id": f"evt_{i:03d}", "t0": i * 1.5, "n_sta": i + 3})
    replayed = wal.replay()
    wal_digest = hashlib.sha256(
        json.dumps(replayed, sort_keys=True).encode()
    ).hexdigest()
    return {
        "journal": restored,
        "journal_rev_identical": rev_identical,
        "wal": wal_digest,
    }


def _child(args) -> int:
    from seist_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()
    import seist_tpu

    seist_tpu.load_all()
    t0 = time.monotonic()
    out = args.out
    archive = os.path.join(out, "archive")
    _pack(archive, args.workers)
    pack_digests = digest_tree(archive)
    resume_identical = _resume_exercise(archive, args.workers, args.relink)

    catalog: Optional[str] = None
    if not args.skip_repick:
        catalog = _repick(archive, os.path.join(out, "repick"), args.workers)

    result = {
        "role": "child",
        "hashseed": os.environ.get("PYTHONHASHSEED", ""),
        "workers": args.workers,
        "relink": bool(args.relink),
        "pack": combine(pack_digests),
        "pack_files": len(pack_digests),
        "resume_identical": bool(resume_identical),
        "catalog": catalog,
        "wall_s": None,  # filled below so the key order stays stable
    }
    result.update(_journal_exercise(out))
    result["wall_s"] = round(time.monotonic() - t0, 1)
    print(json.dumps(result))
    ok = resume_identical and result["journal_rev_identical"]
    return 0 if ok else 1


# -------------------------------------------------------------------- parent
def _last_json_line(text: str) -> dict:
    for line in reversed(text.strip().splitlines()):
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if d.get("role") == "child":
            return d
    raise SystemExit(f"no child verdict in output: {text[-400:]}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.replay_smoke",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--full", action="store_true",
                    help="run the full 2x2 perturbation matrix")
    ap.add_argument("--skip-repick", action="store_true",
                    help="pack/journal phases only (no model, fast loop)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch directory for inspection")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--workers", type=int, default=1, help=argparse.SUPPRESS)
    ap.add_argument("--out", default="", help=argparse.SUPPRESS)
    ap.add_argument("--relink", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.child:
        return _child(args)

    t0 = time.monotonic()
    root = tempfile.mkdtemp(prefix="replay_smoke_")
    variants = VARIANTS_FULL if args.full else VARIANTS
    children: List[dict] = []
    try:
        # Sequential on purpose: the repick phase is compile-heavy and
        # the host budget is one core (ROADMAP gotchas).
        for hashseed, workers, relink in variants:
            out = os.path.join(root, f"h{hashseed}_w{workers}")
            os.makedirs(out, exist_ok=True)
            cmd = [
                sys.executable, "-m", "tools.replay_smoke", "--child",
                "--workers", str(workers), "--out", out,
            ]
            if relink:
                cmd.append("--relink")
            if args.skip_repick:
                cmd.append("--skip-repick")
            env = dict(os.environ, PYTHONHASHSEED=str(hashseed))
            proc = subprocess.run(
                cmd, env=env, stdout=subprocess.PIPE, text=True,
                timeout=1800,
            )
            if proc.returncode != 0:
                print(proc.stdout[-2000:], file=sys.stderr)
                raise SystemExit(
                    f"child h{hashseed}/w{workers} rc={proc.returncode}"
                )
            children.append(_last_json_line(proc.stdout))

        ref = children[0]
        axes = ("pack", "catalog", "journal", "wal")
        identical = {
            axis: all(c[axis] == ref[axis] for c in children)
            for axis in axes
        }
        resumes = all(c["resume_identical"] for c in children)
        rev = all(c["journal_rev_identical"] for c in children)
        verdict = {
            "ok": bool(all(identical.values()) and resumes and rev),
            "perturbations": [
                {"hashseed": h, "workers": w, "relink": r}
                for h, w, r in variants
            ],
            "identical": identical,
            "resume_identical": resumes,
            "reversed_listdir_identical": rev,
            "digests": {axis: ref[axis] for axis in axes},
            "pack_files": ref["pack_files"],
            "wall_s": round(time.monotonic() - t0, 1),
        }
        print(json.dumps(verdict))
        return 0 if verdict["ok"] else 1
    finally:
        if not args.keep:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
