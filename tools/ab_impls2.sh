#!/bin/bash
# Watcher 4: after tools/ab_impls.sh (IMPL AB DONE marker), collect the
# seist_s_dpk b256 rows ab_impls.sh's header promised but never ran
# (review finding), same session: default lowering + fused stem.
LOG=/root/repo/tools/ab_phase_split.log
until grep -q "IMPL AB DONE" "$LOG" 2>/dev/null; do sleep 120; done

run() {  # $1 = tag, rest = env overrides
  tag=$1; shift
  echo "=== impl A/B: $tag $(date)" >> "$LOG"
  (cd /root/repo && env "$@" BENCH_STEPS=15 BENCH_PROBE_ATTEMPTS=1 \
     BENCH_PROBE_TIMEOUT=120 timeout 900 python bench.py 2>/dev/null) >> "$LOG"
}
run "seist_s default b256"    BENCH_MODEL=seist_s_dpk BENCH_BATCH=256
run "seist_s fused b256"      BENCH_MODEL=seist_s_dpk BENCH_BATCH=256 SEIST_STEM_IMPL=fused
run "eqt b256 unroll8"        BENCH_MODEL=eqtransformer BENCH_BATCH=256
run "eqt b256 unroll1"        BENCH_MODEL=eqtransformer BENCH_BATCH=256 SEIST_LSTM_UNROLL=1
echo "IMPL AB2 DONE $(date)" >> "$LOG"
