#!/bin/bash
# Watcher v2: wait for the TPU tunnel, then
#   1. Mosaic-compile + numerics check of the head-folded attention kernel
#      (tools/check_attn_tpu.py)
#   2. A/B bench: working tree (phase-split stride lowering + head-folded
#      attention) vs pre-change HEAD 74aad2c (worktree /tmp/repo_head),
#      bracketed NEW -> OLD -> NEW to expose chip drift; plus one NEW run
#      at batch 256 for comparability with the bf16 matrix rows.
# JSON lines land in tools/ab_phase_split.log.
LOG=/root/repo/tools/ab_phase_split.log
probe() {
  timeout 70 python -c "
import jax, jax.numpy as jnp
r = jax.jit(lambda a, b: a @ b)(jnp.ones((128,128)), jnp.ones((128,128)))
r.block_until_ready(); print('UP')" 2>/dev/null | grep -q UP
}
echo "watcher2 start $(date)" >> "$LOG"
until probe; do sleep 240; done
echo "tunnel UP $(date)" >> "$LOG"

echo "=== attn kernel check $(date)" >> "$LOG"
(cd /root/repo && timeout 900 python tools/check_attn_tpu.py 2>/dev/null) >> "$LOG"
echo "attn check rc=$?" >> "$LOG"

run() {  # $1 = dir, $2 = tag, $3 = extra env (optional BENCH_BATCH)
  echo "=== $2 $(date)" >> "$LOG"
  (cd "$1" && env $3 BENCH_STEPS=15 BENCH_PROBE_ATTEMPTS=1 BENCH_PROBE_TIMEOUT=120 \
     timeout 900 python bench.py 2>/dev/null) >> "$LOG"
}
run /root/repo      "NEW (1st) b512"
run /tmp/repo_head  "OLD head b512"
run /root/repo      "NEW (2nd) b512"
run /root/repo      "NEW b256" "BENCH_BATCH=256"
echo "ALL DONE $(date)" >> "$LOG"
