"""Batch-fleet chaos lane: SIGKILL + exit-75 preempt + lease-store
partition, all injected mid-archive — merged catalog byte-identical.

The ``make batch-chaos`` headline (docs/FAULT_TOLERANCE.md "Batch fleet
faults"): a 3-worker lease fleet (tools/supervise_repick.py) re-picks a
synthetic packed archive while every failure class the lease plane
exists for fires at once —

* **worker 0** loses the lease store entirely (an injected partition
  window opening shortly after its first lease op): it commits its
  in-flight segments while the lease is still locally valid, PARKS on
  the done-marker write, and heals into the discovery that a peer
  reclaimed + completed its unit — the zombie completion is refused by
  the fence ladder (fence_rejects >= 1, the counter this lane proves is
  live);
* **worker 1** is SIGKILL'd at its first lease acquisition (hard crash,
  no handlers): its lease expires, a peer reclaims at the next fence,
  and the supervisor's crash budget relaunches the worker;
* **worker 2** is SIGTERM'd at its first acquisition (the exit-75
  preemption contract): it drains, releases its lease, exits 75, and
  rejoins after a delay to steal whatever is still open.

Gates: the fleet finishes without human intervention (supervisor rc 0);
the merged catalog's sha256 EQUALS the serial no-fault run's (the
paper-scale invariant: chaos may cost time, never bytes); ZERO
double-committed segments; fence_rejects >= 1 (under chaos the counter
must account the zombie attempt — in a clean run it must be zero, which
``tests/test_batch_fleet.py`` pins).

Geometry is tools/repick_smoke.py's ON PURPOSE: the same programs
lower, so the persistent XLA compile cache is warm for every worker
incarnation. One JSON verdict line; exit 0 iff every gate holds.

    python -m tools.batch_chaos            # the make lane
    python -m tools.batch_chaos --runs 3   # the acceptance loop
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List

# repick_smoke geometry (warm XLA cache across lanes): 44 events over
# 16-sample shards -> 3 shards == 3 work units, one per worker.
N_EVENTS = 44
TRACE = 256
SPS = 16
BATCH = 4
BPC = 2  # rows_per_call = 8 -> 2 calls/unit
COMMIT = 1  # -> 2 segments/unit: a partition can land BETWEEN commits

#: lease clocks for the scenario (seconds). TTL/heartbeat are shrunk so
#: expiry-reclaim happens in seconds; the partition window is sized so
#: worker 0 commits inside it but its TTL lapses before it heals.
LEASE_ENV = {
    "SEIST_LEASE_TTL_S": "2.5",
    "SEIST_LEASE_HEARTBEAT_S": "0.5",
    "SEIST_LEASE_GRACE_S": "0.5",
    "SEIST_LEASE_OP_TIMEOUT_S": "1.0",
    "SEIST_LEASE_RETRIES": "3",
    "SEIST_LEASE_BACKOFF_MS": "30",
    "SEIST_LEASE_BACKOFF_CAP_MS": "200",
    "SEIST_LEASE_PARK_S": "0.3",
}

#: per-device-call sleep making unit runtime fault-window-sized (sleep,
#: not compute: the host budget is one core)
SLOW_MS = "400"

#: worker 0's partition: opens 0.6s after its first lease op (mid-unit,
#: after seg 0's fence check, before seg 1's). The window must dominate
#: the PEERS' schedule, not just TTL+grace: the fence reject fires only
#: if a peer reclaims w0's expired unit (and writes its done marker —
#: cheap, the committed segments resume-scan as already present) BEFORE
#: w0 heals and retries its own parked done-marker write. Both peers
#: pay a full process relaunch (kill + preempt) of ~15-25s on a loaded
#: 1-core host, so a short window lets w0 win its own race back and the
#: zombie never forms; 60s covers the slowest observed relaunch cycle
#: (~50s) with margin.
PARTITION_AFTER_S = "0.6"
PARTITION_FOR_S = "60"


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _pack(archive: str) -> None:
    from seist_tpu.data.packed import PackSource, pack_sources

    pack_sources(
        [PackSource(
            name="synthetic",
            dataset_kwargs={
                "num_events": N_EVENTS, "trace_samples": TRACE,
                "cache": False,
            },
        )],
        archive,
        num_workers=1,
        samples_per_shard=SPS,
    )


def _last_json(text: str, role: str) -> Dict[str, Any]:
    for line in reversed(text.strip().splitlines()):
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if d.get("role") == role:
            return d
    raise SystemExit(f"no '{role}' verdict in output: {text[-400:]}")


def _repick_args(archive: str, out: str) -> List[str]:
    return [
        "--archive", archive, "--out", out, "--model", "phasenet",
        "--batch-size", str(BATCH), "--batches-per-call", str(BPC),
        "--commit-every", str(COMMIT),
    ]


def _serial(archive: str, out: str) -> str:
    """Clean single-process reference run -> catalog sha256."""
    env = dict(os.environ)
    env.pop("SEIST_FAULT_REPICK_SLOW_MS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "tools.repick_archive",
         *_repick_args(archive, out)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=600,
    )
    if proc.returncode != 0:
        print(proc.stdout[-2000:], file=sys.stderr)
        raise SystemExit(f"serial reference run rc={proc.returncode}")
    return _sha256(os.path.join(out, "catalog.jsonl"))


def _fleet(archive: str, out: str) -> Dict[str, Any]:
    """The 3-worker chaos fleet -> supervisor verdict."""
    lease_dir = os.path.join(out, "leases")
    env = dict(os.environ)
    env.update(LEASE_ENV)
    env["SEIST_FAULT_REPICK_SLOW_MS"] = SLOW_MS
    cmd = [
        sys.executable, "-m", "tools.supervise_repick",
        *_repick_args(archive, out),
        "--workers", "3", "--lease-dir", lease_dir,
        "--retries", "2", "--rejoin-delay-s", "1.0",
        "--timeout-s", "300",
        # worker 0: lease-store partition mid-unit
        "--fault-env", f"0:SEIST_FAULT_BATCH_PARTITION_AFTER_S={PARTITION_AFTER_S}",
        "--fault-env", f"0:SEIST_FAULT_BATCH_PARTITION_FOR_S={PARTITION_FOR_S}",
        # worker 1: SIGKILL at its first lease acquisition
        "--fault-env", "1:SEIST_FAULT_BATCH_KILL_UNIT=1",
        # worker 2: exit-75 preempt at its first lease acquisition
        "--fault-env", "2:SEIST_FAULT_BATCH_PREEMPT_UNIT=1",
    ]
    proc = subprocess.run(
        cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=600,
    )
    if proc.returncode != 0:
        print(proc.stdout[-4000:], file=sys.stderr)
        raise SystemExit(f"chaos fleet rc={proc.returncode}")
    return _last_json(proc.stdout, "supervisor")


def _one_run(root: str, run: int) -> Dict[str, Any]:
    archive = os.path.join(root, "archive")
    if not os.path.isdir(archive):
        _pack(archive)
    serial_out = os.path.join(root, f"serial_{run}")
    fleet_out = os.path.join(root, f"fleet_{run}")
    serial_sha = _serial(archive, serial_out)
    sup = _fleet(archive, fleet_out)
    fleet_sha = _sha256(os.path.join(fleet_out, "catalog.jsonl"))
    lease = sup.get("lease", {})
    gates = {
        "fleet_finished": bool(sup.get("ok")),
        "byte_identical": fleet_sha == serial_sha,
        "zero_double_commits": int(lease.get("double_commits", -1)) == 0,
        "fence_reject_counted": int(lease.get("fence_rejects", 0)) >= 1,
        "kill_fired": int(sup.get("crashes", 0)) >= 1,
        "preempt_fired": int(sup.get("preempts", 0)) >= 1,
    }
    return {
        "run": run,
        "ok": all(gates.values()),
        "gates": gates,
        "sha256": fleet_sha,
        "serial_sha256": serial_sha,
        "supervisor": {
            k: sup.get(k)
            for k in ("relaunches", "preempts", "crashes", "abandoned",
                      "rows", "units", "wall_s")
        },
        "lease": lease,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.batch_chaos",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--runs", type=int, default=1,
                    help="repeat the scenario N times (acceptance: 3)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch directory for inspection")
    args = ap.parse_args(argv)

    from seist_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()

    t0 = time.monotonic()
    root = tempfile.mkdtemp(prefix="batch_chaos_")
    try:
        runs = [_one_run(root, i) for i in range(args.runs)]
        verdict = {
            "ok": all(r["ok"] for r in runs),
            "role": "batch-chaos",
            "runs": len(runs),
            "gates": {
                k: all(r["gates"][k] for r in runs)
                for k in runs[0]["gates"]
            },
            "sha256": runs[0]["sha256"],
            "supervisor": [r["supervisor"] for r in runs],
            "lease": [r["lease"] for r in runs],
            "wall_s": round(time.monotonic() - t0, 1),
        }
        print(json.dumps(verdict))
        return 0 if verdict["ok"] else 1
    finally:
        if not args.keep:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
