"""Summarize a silicon-runner log (tools/ab_r4.log) into one table.

The runner (tools/r4_silicon.sh / r3_silicon.sh) appends per-step
sections delimited by ``=== <tag> <iso-time>`` and terminated by
``STATUS ok|fail|skip <tag>``; bench steps print their one-line JSONs
into the same log (matrix steps print SEVERAL — the row notes the count
and shows the last). This tool recovers, per step: status, wall
seconds (bounded by the next section OR a run boundary line, so an
append-mode log with multiple runs never bleeds durations across runs),
and the bench metric/value/kernel-status — the promote-or-revert view
of the A/B evidence without scrolling a multi-MB log.

    python tools/ab_summary.py [tools/ab_r4.log]
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

_ISO = r"\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}Z"
_SECTION = re.compile(rf"^=== (\S+) ({_ISO})$")
_STATUS = re.compile(r"^STATUS (ok|fail|skip) (\S+)(?: rc=(\d+))?$")
# Run boundaries the runners write outside any section: "r4_silicon
# start <ts>", "ALL DONE <ts>", "R4 ALL DONE <ts>", "REFRESH DONE <ts>".
_BOUNDARY = re.compile(rf"^.*(?:\bstart\b|\bDONE\b).* ({_ISO})$")


def _parse_ts(stamp: str) -> float:
    import calendar

    return calendar.timegm(time.strptime(stamp, "%Y-%m-%dT%H:%M:%SZ"))


def summarize(path: str):
    """Returns [{tag, status, seconds, value, unit, cached, degraded,
    kernel, device, json_count}] in log order. Skipped steps (R3_SKIP)
    appear as rows with status 'skip' so 'deliberately skipped' is
    distinguishable from 'never reached before the tunnel died'."""
    steps = []
    current = None
    for raw in open(path, errors="replace"):
        line = raw.rstrip("\n")
        m = _SECTION.match(line)
        if m:
            current = {
                "tag": m.group(1),
                "start": _parse_ts(m.group(2)),
                "end": None,
                "status": "running",
                "jsons": [],
            }
            steps.append(current)
            continue
        m = _STATUS.match(line)
        if m:
            if m.group(1) == "skip":
                # Written WITHOUT a section header; standalone row.
                steps.append(
                    {
                        "tag": m.group(2),
                        "start": None,
                        "end": None,
                        "status": "skip",
                        "jsons": [],
                    }
                )
            elif current is not None and m.group(2) == current["tag"]:
                current["status"] = m.group(1)
            continue
        m = _BOUNDARY.match(line)
        if m and not line.startswith("{"):
            # Run boundary: terminates the open section's duration so a
            # later append-mode run cannot bleed into it.
            if current is not None and current["end"] is None:
                current["end"] = _parse_ts(m.group(1))
            current = None
            continue
        if (
            current is not None
            and line.startswith("{")
            and '"metric"' in line
        ):
            try:
                current["jsons"].append(json.loads(line))
            except ValueError:
                pass
    # Close each section at the next section's start when no boundary did.
    timed = [s for s in steps if s["start"] is not None]
    for i, s in enumerate(timed):
        if s["end"] is None and i + 1 < len(timed):
            s["end"] = timed[i + 1]["start"]
    out = []
    for s in steps:
        j = s["jsons"][-1] if s["jsons"] else {}
        ks = j.get("kernel_status")
        out.append(
            {
                "tag": s["tag"],
                "status": s["status"],
                "seconds": (
                    round(s["end"] - s["start"])
                    if s["start"] is not None and s["end"] is not None
                    else None
                ),
                "metric": j.get("metric"),
                "value": j.get("value"),
                "unit": j.get("unit"),
                "cached": j.get("cached", False),
                "degraded": j.get("degraded", False),
                "kernel": ks.get("overall") if isinstance(ks, dict) else ks,
                "device": j.get("device"),
                "json_count": len(s["jsons"]),
                "config": {
                    k: j[k]
                    for k in ("batch", "dtype", "steps_per_call")
                    if k in j
                },
            }
        )
    return out


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "ab_r4.log"
    )
    if not os.path.exists(path):
        raise SystemExit(f"no log at {path}")
    rows = summarize(path)
    if not rows:
        print("no runner sections found")
        return
    w = max(len(r["tag"]) for r in rows) + 1
    for r in rows:
        val = (
            f"{r['value']:>10.1f} {r['unit'] or '':<18}"
            if r["value"] is not None
            else " " * 29
        )
        flags = "".join(
            [
                "C" if r["cached"] else "-",
                "D" if r["degraded"] else "-",
            ]
        )
        more = (
            f" (last of {r['json_count']} JSONs)"
            if r["json_count"] > 1
            else ""
        )
        kern = r["kernel"] or ""
        secs = f"{r['seconds']}s" if r["seconds"] is not None else ""
        print(
            f"{r['tag']:<{w}} {r['status']:<8} {secs:>7} {val} "
            f"{flags} {kern} {r['config'] or ''}{more}"
        )
    print("\nflags: C=cached replay (NOT a fresh measurement), D=degraded"
          " (einsum fallback on TPU)")


if __name__ == "__main__":
    main()
