"""Supervised training: relaunch on failure, resuming from the newest
checkpoint.

The reference has no failure-recovery mechanism at all — a crashed run is
relaunched by hand with `--checkpoint` (SURVEY.md §5; ref train.py:255-264
is the resume path, nothing invokes it automatically). This wrapper closes
that gap for long unattended runs:

    python tools/supervise.py --retries 3 --backoff 30 -- \
        python main.py --mode train --model-name seist_l_dpk \
        --dataset-name diting --data /path --log-base logs/run1

On a nonzero exit it scans the run's `--log-base` tree for the newest
`checkpoints/model-*` directory (orbax layout, train/checkpoint.py) and
relaunches the SAME command with `--checkpoint <newest>` (replacing any
prior value), up to `--retries` times with `--backoff` seconds between
attempts. A run that produced no checkpoint yet is relaunched from
scratch. Exit code is the final attempt's.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from typing import List, Optional


def find_newest_checkpoint(log_base: str) -> Optional[str]:
    """Newest `*/checkpoints/model-*` dir under ``log_base`` by mtime."""
    newest, newest_t = None, -1.0
    for dirpath, dirnames, _ in os.walk(log_base):
        if os.path.basename(dirpath) != "checkpoints":
            continue
        for d in dirnames:
            # Skip orbax in-progress dirs (e.g. model-7.orbax-checkpoint-
            # tmp-<ts>): a crash mid-save leaves one with the newest mtime,
            # and resuming from it would fail on every retry.
            if not d.startswith("model-") or "tmp" in d:
                continue
            p = os.path.join(dirpath, d)
            t = os.path.getmtime(p)
            if t > newest_t:
                newest, newest_t = p, t
    return newest


def _arg_value(cmd: List[str], flag: str) -> Optional[str]:
    """Value of ``flag`` in ``cmd`` — both ``--flag v`` and ``--flag=v``."""
    for i, tok in enumerate(cmd):
        if tok == flag:
            return cmd[i + 1] if i + 1 < len(cmd) else None
        if tok.startswith(flag + "="):
            return tok[len(flag) + 1:]
    return None


def with_checkpoint(cmd: List[str], ckpt: str) -> List[str]:
    """Return ``cmd`` with ``--checkpoint ckpt`` set (replacing any prior,
    in either ``--checkpoint v`` or ``--checkpoint=v`` form)."""
    cmd = list(cmd)
    for i, tok in enumerate(cmd):
        if tok == "--checkpoint":
            if i + 1 < len(cmd):
                cmd[i + 1] = ckpt
                return cmd
            return cmd[:i] + ["--checkpoint", ckpt]
        if tok.startswith("--checkpoint="):
            cmd[i] = f"--checkpoint={ckpt}"
            return cmd
    return cmd + ["--checkpoint", ckpt]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="relaunch-on-failure wrapper with checkpoint resume",
        usage="supervise.py [--retries N] [--backoff S] -- <command...>",
    )
    ap.add_argument("--retries", type=int, default=3,
                    help="max relaunches after the first attempt (default 3)")
    ap.add_argument("--backoff", type=float, default=30.0,
                    help="seconds to wait before each relaunch (default 30)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="the training command, after `--`")
    args = ap.parse_args(argv)

    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given (use: supervise.py [opts] -- python main.py ...)")

    log_base = _arg_value(cmd, "--log-base") or "./logs"

    rc = 0
    for attempt in range(args.retries + 1):
        if attempt:
            ckpt = find_newest_checkpoint(log_base)
            if ckpt:
                cmd = with_checkpoint(cmd, ckpt)
                print(f"[supervise] resuming from {ckpt}", file=sys.stderr)
            else:
                print("[supervise] no checkpoint yet; restarting fresh",
                      file=sys.stderr)
            time.sleep(args.backoff)
        print(f"[supervise] attempt {attempt + 1}/{args.retries + 1}: "
              f"{' '.join(cmd)}", file=sys.stderr, flush=True)
        rc = subprocess.call(cmd)
        if rc == 0:
            return 0
        print(f"[supervise] exited rc={rc}", file=sys.stderr, flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
