"""Supervised training: relaunch on failure, resuming from the newest
checkpoint; preemption-aware.

The reference has no failure-recovery mechanism at all — a crashed run is
relaunched by hand with `--checkpoint` (SURVEY.md §5; ref train.py:255-264
is the resume path, nothing invokes it automatically). This wrapper closes
that gap for long unattended runs:

    python tools/supervise.py --retries 3 --backoff 30 -- \
        python main.py --mode train --model-name seist_l_dpk \
        --dataset-name diting --data /path --log-base logs/run1

On a nonzero exit it scans the run's `--log-base` tree for the newest
committed checkpoint dir (legacy `model-<epoch>` or step-granular
`model_<step>`, the orbax layouts of train/checkpoint.py) and relaunches
the SAME command with `--checkpoint <newest>` (replacing any prior value).

Exit-code contract (docs/FAULT_TOLERANCE.md):

* ``PREEMPT_EXIT_CODE`` (75, sysexits EX_TEMPFAIL) — the worker caught
  SIGTERM, checkpointed, and exited cleanly. Relaunched IMMEDIATELY (no
  backoff) and the retry budget is untouched — but only when the
  checkpoint actually advanced since the last launch; a trainer stuck in
  an exit-75 loop without making progress consumes the budget like any
  crash (otherwise a broken job would relaunch forever).
* any other nonzero — a crash. Relaunch after ``--backoff`` seconds, up
  to ``--retries`` times. The budget RESETS whenever a relaunch dies with
  a newer checkpoint than the previous attempt had: forward progress
  means the job is healthy and the environment is flaky, so a long run
  is not killed by N spread-out outages (tools/tpu_outage_r4.log ate 4
  in one night).

A run that produced no checkpoint yet is relaunched from scratch. Exit
code is the final attempt's. This file is stdlib-only (it must not drag
jax into the supervisor process); PREEMPT_EXIT_CODE is therefore
duplicated from seist_tpu/train/checkpoint.py — a unit test pins the two
constants together.
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys
import time
from typing import List, Optional, Tuple

# Keep in sync with seist_tpu.train.checkpoint.PREEMPT_EXIT_CODE
# (tests/test_supervise.py::test_preempt_code_matches_trainer).
PREEMPT_EXIT_CODE = 75

# Committed checkpoint dirs: legacy epoch naming `model-<epoch>` or the
# step-granular manager naming `model_<step>`.
_CKPT_RE = re.compile(r"^model[-_](\d+)$")
# Orbax in-progress dirs (e.g. `model_7.orbax-checkpoint-tmp-123`): a
# crash mid-save leaves one with the newest mtime, and resuming from it
# would fail on every retry. Match the exact orbax marker, NOT a bare
# "tmp" substring — that rejected legitimate names containing those
# three letters anywhere.
_ORBAX_TMP_MARKER = ".orbax-checkpoint-tmp-"


def checkpoint_step(path_or_name: str) -> Optional[int]:
    """Step/epoch number parsed from a checkpoint dir name, else None."""
    m = _CKPT_RE.match(os.path.basename(str(path_or_name)))
    return int(m.group(1)) if m else None


def find_newest_checkpoint(log_base: str) -> Optional[str]:
    """Newest committed `*/checkpoints/model{-,_}<n>` dir under
    ``log_base`` by mtime (step number breaks same-second ties)."""
    newest: Optional[str] = None
    newest_key: Tuple[float, int] = (-1.0, -1)
    for dirpath, dirnames, _ in os.walk(log_base):
        if os.path.basename(dirpath) != "checkpoints":
            continue
        for d in dirnames:
            if _ORBAX_TMP_MARKER in d:
                continue  # interrupted save: never resume from it
            step = checkpoint_step(d)
            if step is None:
                continue
            p = os.path.join(dirpath, d)
            key = (os.path.getmtime(p), step)
            if key > newest_key:
                newest, newest_key = p, key
    return newest


def _arg_value(cmd: List[str], flag: str) -> Optional[str]:
    """Value of ``flag`` in ``cmd`` — both ``--flag v`` and ``--flag=v``."""
    for i, tok in enumerate(cmd):
        if tok == flag:
            return cmd[i + 1] if i + 1 < len(cmd) else None
        if tok.startswith(flag + "="):
            return tok[len(flag) + 1:]
    return None


def with_checkpoint(cmd: List[str], ckpt: str) -> List[str]:
    """Return ``cmd`` with ``--checkpoint ckpt`` set (replacing any prior,
    in either ``--checkpoint v`` or ``--checkpoint=v`` form)."""
    cmd = list(cmd)
    for i, tok in enumerate(cmd):
        if tok == "--checkpoint":
            if i + 1 < len(cmd):
                cmd[i + 1] = ckpt
                return cmd
            return cmd[:i] + ["--checkpoint", ckpt]
        if tok.startswith("--checkpoint="):
            cmd[i] = f"--checkpoint={ckpt}"
            return cmd
    return cmd + ["--checkpoint", ckpt]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="relaunch-on-failure wrapper with checkpoint resume",
        usage="supervise.py [--retries N] [--backoff S] -- <command...>",
    )
    ap.add_argument("--retries", type=int, default=3,
                    help="max relaunches after a crash WITHOUT checkpoint "
                    "progress (default 3); progress resets the budget")
    ap.add_argument("--backoff", type=float, default=30.0,
                    help="seconds to wait before a crash relaunch "
                    "(default 30); clean preempts relaunch immediately")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="the training command, after `--`")
    args = ap.parse_args(argv)

    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given (use: supervise.py [opts] -- python main.py ...)")

    log_base = _arg_value(cmd, "--log-base") or "./logs"

    def _log(msg: str) -> None:
        print(f"[supervise] {msg}", file=sys.stderr, flush=True)

    failures = 0  # crash relaunches since the last checkpoint progress
    attempt = 0
    prev_ckpt = find_newest_checkpoint(log_base)
    while True:
        attempt += 1
        _log(f"attempt {attempt} (budget {failures}/{args.retries} used): "
             f"{' '.join(cmd)}")
        rc = subprocess.call(cmd)
        if rc == 0:
            return 0
        ckpt = find_newest_checkpoint(log_base)
        # Progress = the newest checkpoint CHANGED (a new step in the
        # same run, or a fresh run's first save). Comparing raw step
        # numbers across the whole log_base would let a stale higher-step
        # checkpoint from an old run sharing the tree mask every new
        # run's progress and burn the budget on clean preempts.
        progressed = ckpt is not None and ckpt != prev_ckpt
        if progressed:
            # Forward progress: the job is healthy, the environment flaky.
            failures = 0
        if rc == PREEMPT_EXIT_CODE and progressed:
            _log(f"clean preempt (rc={rc}), checkpoint advanced to "
                 f"{ckpt}: immediate relaunch, retry budget untouched")
        else:
            failures += 1
            _log(f"exited rc={rc} "
                 f"({'no checkpoint progress' if not progressed else 'crash'}); "
                 f"budget {failures}/{args.retries} used")
            if failures > args.retries:
                return rc
            time.sleep(args.backoff)
        if ckpt:
            cmd = with_checkpoint(cmd, ckpt)
            _log(f"resuming from {ckpt}")
        else:
            _log("no checkpoint yet; restarting fresh")
        prev_ckpt = ckpt


if __name__ == "__main__":
    sys.exit(main())
