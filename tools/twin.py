"""Network digital twin: the streaming subsystem's acceptance harness.

Synthesizes a deterministic earthquake scenario — a mainshock followed by
an Omori-law aftershock sequence over a simulated station network (noise
stations, dropouts, late-burst deliveries, duplicated packets) — and
drives the REAL serve plane end-to-end: every packet goes through
``ServeService.stream`` (admission -> shed ladder -> StationMux ->
MicroBatcher -> StreamSession -> Associator), exactly the path a live
``POST /stream`` request takes, minus the socket.

The model is a deterministic batch-invariant outlier picker. Windows
reach the model z-normalized (the session mirrors annotate's per-window
``normalize(chunk, 'std')``), so amplitude thresholds are useless —
instead P probability = ``clip(|z| - 4.5, 0, 1)``: a 256-sample Gaussian
noise window tops out near 3.5 sigma (probability 0), while a triangular
pulse peak z-scores to ~5.5 sigma *whatever its raw amplitude* (the
pulse inflates the window's own std, so peak-z saturates). Synthetic
pulse => pick, noise floor => silence, and ground truth is *computable*
— the twin knows which stations were handed a pulse with intact timing,
so it can gate on network-level behavior rather than eyeball it:

* **zero missed mainshock alerts** — at least one alert back-projects to
  the mainshock origin time, and the union of mainshock-alert picks
  covers every expected detector (minus the < ``min_stations`` leftover
  the associator cannot form a final alert from);
* **zero alert-tier sheds / dropped windows / degraded sessions** — the
  scenario's offered load must ride inside the alert tier's guarantees;
* **pinned p99 sample->alert latency** with the per-stage breakdown
  (arrival -> due -> queue -> device -> pick -> association) stamped into
  the ``BENCH_stream_r01.json`` lane;
* the chaos actually fired: duplicate packets were deduplicated and
  sequence gaps counted (a twin whose faults never trigger gates nothing).

    python tools/twin.py --smoke --output BENCH_stream_r01.json

Exit 0 when every gate holds, 3 (the bench SLO convention) otherwise.
`make twin-smoke` runs the pinned 50-station smoke configuration.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time
from datetime import datetime, timezone
from types import SimpleNamespace
from typing import Any, Dict, List, Optional

import numpy as np

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))

GATE_EXIT_CODE = 3

#: footprint of the simulated network (~90 km across: regional array)
LAT0, LAT1 = 34.6, 35.4
LON0, LON1 = -117.9, -117.1
NOISE_STD = 0.05  # background channel noise (P prob ~= 0.05 << 0.5)
PULSE_HALF = 10  # triangular pulse half-width, samples
DROPOUT_SPAN_S = (0.5, 0.7)  # dropout window, fraction of duration


def get_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(description="network digital twin")
    ap.add_argument("--stations", type=int, default=200)
    ap.add_argument("--duration-s", type=float, default=240.0)
    ap.add_argument("--window", type=int, default=256)
    ap.add_argument("--fs", type=int, default=50)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mainshock-frac", type=float, default=0.25,
                    help="mainshock origin time as a fraction of duration")
    ap.add_argument("--noise-frac", type=float, default=0.16,
                    help="fraction of stations that never see an event")
    ap.add_argument("--min-stations", type=int, default=4,
                    help="associator co-detection quorum")
    ap.add_argument("--p99-budget-ms", type=float, default=2500.0,
                    help="sample->alert p99 gate")
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--output", default="BENCH_stream_r01.json")
    ap.add_argument("--smoke", action="store_true",
                    help="the pinned make twin-smoke configuration: "
                         "50 stations, 60 s scenario")
    ap.add_argument("--export-schedule", default=None, metavar="PATH",
                    help="also write the deterministic arrival schedule "
                    "(stations + per-round packet plan) as JSON, so the "
                    "real-fleet chaos lane replays the exact same "
                    "mainshock delivery this twin run drove")
    args = ap.parse_args(argv)
    if args.smoke:
        args.stations = 50
        args.duration_s = 60.0
    return args


# ----------------------------------------------------------- scenario
def make_stations(args, rng) -> List[Dict[str, Any]]:
    """Grid geometry + deterministic fault-role assignment. Roles are
    disjoint so each fault's effect is attributable."""
    n = args.stations
    side = max(1, int(math.ceil(math.sqrt(n))))
    stations = []
    for i in range(n):
        stations.append({
            "id": f"TW{i:04d}",
            "network": "TW",
            "lat": round(LAT0 + (LAT1 - LAT0) * (i // side) / max(1, side - 1), 4),
            "lon": round(LON0 + (LON1 - LON0) * (i % side) / max(1, side - 1), 4),
            "noise": False, "late": False, "dup": False, "dropout": False,
        })
    order = rng.permutation(n)
    k_noise = int(round(args.noise_frac * n))
    roles = (["noise"] * k_noise + ["late"] * 3 + ["dup"] * 4
             + ["dropout"] * 5)
    for idx, role in zip(order, roles):
        stations[int(idx)][role] = True
    return stations


def _dist_km(lat1, lon1, lat2, lon2) -> float:
    la1, la2 = math.radians(lat1), math.radians(lat2)
    dlon = math.radians(lon2 - lon1) * math.cos(0.5 * (la1 + la2))
    return 6371.0 * math.hypot(la2 - la1, dlon)


def make_events(args, rng) -> List[Dict[str, Any]]:
    """Mainshock + Omori-law aftershocks (rate K/(t+c)^p after the
    mainshock), sampled by deterministic integral thinning with a 3 s
    refractory so consecutive events stay separable by the associator's
    origin-time tolerance. The first aftershock waits 6 s: two pulses
    inside one analysis window inflate its std enough to push peak-z
    under the picker threshold, and the mainshock gate must not depend
    on that (aftershock-pair shadowing is allowed, and reported)."""
    t_main = args.mainshock_frac * args.duration_s
    clat, clon = 0.5 * (LAT0 + LAT1), 0.5 * (LON0 + LON1)
    events = [{
        "name": "mainshock", "t": t_main, "lat": clat, "lon": clon,
        "amp": 1.5, "radius_km": 1e9,
    }]
    K, c, p = 2.5, 1.0, 1.1
    acc, last_t = 0.0, -10.0
    dt = 0.1
    horizon = args.duration_s - t_main - 8.0  # leave room for moveout
    t = 0.0
    i = 0
    while t < horizon:
        acc += K / (t + c) ** p * dt
        if acc >= 1.0:
            acc -= 1.0
            if t - last_t >= 3.0 and t >= 6.0:
                last_t = t
                i += 1
                events.append({
                    "name": f"aftershock{i}",
                    "t": t_main + t,
                    "lat": clat + float(rng.uniform(-0.12, 0.12)),
                    "lon": clon + float(rng.uniform(-0.12, 0.12)),
                    "amp": 1.2,
                    "radius_km": float(rng.uniform(30.0, 60.0)),
                })
        t += dt
    return events


def synth_network(args, stations, events, rng, velocity_kms=6.0):
    """Per-station waveforms (noise + triangular P pulses at the
    physical moveout arrival) and the ground-truth detector sets.

    A station is an *expected detector* of an event when it was handed a
    pulse AND its sample clock is intact at the arrival — dropout
    stations lose whole packets, which shifts every later sample
    earlier, so their post-dropout picks carry wrong times by design and
    are excluded from expectations (the realistic failure, accounted)."""
    fs = args.fs
    L = int(args.duration_s * fs)
    drop_lo = DROPOUT_SPAN_S[0] * args.duration_s
    # expected[event][station_id] = arrival time (s): the truth table —
    # evaluation matches observed picks against it by (station, time).
    waves, expected = {}, {ev["name"]: {} for ev in events}
    for st in stations:
        w = rng.standard_normal((L, 3)).astype(np.float32) * NOISE_STD
        if not st["noise"]:
            for ev in events:
                d = _dist_km(ev["lat"], ev["lon"], st["lat"], st["lon"])
                if d > ev["radius_km"]:
                    continue
                arr_s = ev["t"] + d / velocity_kms
                s0 = int(round(arr_s * fs))
                if s0 - PULSE_HALF < 0 or s0 + PULSE_HALF >= L:
                    continue
                for k in range(-PULSE_HALF, PULSE_HALF + 1):
                    w[s0 + k, 0] += ev["amp"] * (1.0 - abs(k) / (PULSE_HALF + 1))
                if not (st["dropout"] and arr_s >= drop_lo):
                    expected[ev["name"]][st["id"]] = s0 / fs
        waves[st["id"]] = w
    return waves, expected


def build_scenario(args):
    """The full deterministic scenario from one seed: geometry, events,
    waveforms, truth table. One rng threads through all three stages, so
    any consumer (the in-process twin, the chaos lane's HTTP driver)
    regenerates bit-identical waveforms from the same args."""
    rng = np.random.default_rng(args.seed)
    stations = make_stations(args, rng)
    events = make_events(args, rng)
    waves, expected = synth_network(args, stations, events, rng)
    return stations, events, waves, expected


def make_schedule(args, stations) -> List[List[Dict[str, Any]]]:
    """Deterministic arrival schedule: a list of ROUNDS, each round the
    packets delivered in that scenario step, in station order. All fault
    roles are resolved here — dup stations' replayed packets appear
    twice (same seq), late stations' bursts land in the round that
    flushes them, dropout packets are simply absent (seq still advances,
    so the receiver sees the gap). The final round carries one
    ``end=true`` close per station. ``drive`` and the real-fleet chaos
    lane (tests/test_stream_chaos.py) both consume this plan, so the
    twin's gates and the chaos run argue about the SAME replay."""
    fs = args.fs
    packet = args.window // 2
    L = int(args.duration_s * fs)
    n_rounds = (L + packet - 1) // packet
    drop_lo = int(DROPOUT_SPAN_S[0] * L)
    drop_hi = int(DROPOUT_SPAN_S[1] * L)
    rounds: List[List[Dict[str, Any]]] = [[] for _ in range(n_rounds + 1)]
    for st in stations:
        sid = st["id"]
        seq = 0
        held: List[Dict[str, Any]] = []
        for r in range(n_rounds):
            lo, hi = r * packet, min((r + 1) * packet, L)
            seq += 1
            if st["dropout"] and lo < drop_hi and hi > drop_lo:
                continue  # packet lost; seq advances -> gap
            pkt = {"station": sid, "seq": seq, "lo": lo, "hi": hi}
            if st["late"]:
                held.append(pkt)
                if r % 4 == 3 or r == n_rounds - 1:
                    rounds[r].extend(held)
                    held = []
                continue
            rounds[r].append(pkt)
            if st["dup"] and seq % 5 == 0:
                rounds[r].append(dict(pkt))  # replayed packet, same seq
        rounds[n_rounds].extend(held)  # stragglers (never for r%4 math)
        seq += 1
        rounds[n_rounds].append(
            {"station": sid, "seq": seq, "end": True}
        )
    return rounds


def export_schedule(path, args, stations, events, rounds) -> None:
    """One self-describing JSON artifact: enough to regenerate the
    waveforms (scenario args incl. seed) plus the resolved delivery
    plan. Written atomically (dotfile + replace, the flight.py idiom) so
    a concurrently-starting chaos driver never reads a torn file."""
    doc = {
        "scenario": {
            "stations": args.stations,
            "duration_s": args.duration_s,
            "window": args.window,
            "fs": args.fs,
            "seed": args.seed,
            "mainshock_frac": args.mainshock_frac,
            "noise_frac": args.noise_frac,
            "min_stations": args.min_stations,
        },
        "stations": stations,
        "events": events,
        "n_rounds": len(rounds),
        "rounds": rounds,
    }
    tmp = os.path.join(
        os.path.dirname(os.path.abspath(path)) or ".",
        "." + os.path.basename(path) + ".tmp",
    )
    with open(tmp, "w") as f:
        json.dump(doc, f, separators=(",", ":"))
        f.write("\n")
    os.replace(tmp, path)


# -------------------------------------------------------------- drive
def _make_service(args):
    """ServeService over the deterministic z-outlier picker (module
    docstring): per-sample thresholds only, so batch shape cannot flip a
    crossing, and a pure-noise window yields NO picks."""
    from seist_tpu.serve import BatcherConfig, ServeService

    def run(x, variant="fp32"):
        import jax.numpy as jnp

        x = jnp.asarray(x)  # already z-scored per window by the session
        p = jnp.clip(jnp.abs(x[..., 0]) - 4.5, 0.0, 1.0)
        s = jnp.clip(jnp.abs(x[..., 1]) - 4.5, 0.0, 1.0)
        return jnp.stack([1.0 - p, p, s], axis=-1)

    entry = SimpleNamespace(
        name="twinpick", window=args.window, in_channels=3, channel0="non",
        is_picker=True, is_group=False, version=1, variants=("fp32",),
        run=run,
    )

    class Pool:
        warmup_report: List[Any] = []

        def names(self):
            return ["twinpick"]

        def get(self, name=None):
            return entry

        def warmup(self, buckets):
            pass

    return ServeService(
        Pool(),
        BatcherConfig(max_batch=16, max_delay_ms=2.0, max_queue=1024),
        stream_config={
            "max_stations": max(64, 2 * args.stations),
            "assoc_min_stations": args.min_stations,
            "assoc_window_s": 30.0,
            "assoc_tolerance_s": 2.0,
            # Durability plane — unset for the in-process twin, set by
            # tools/twin_replica.py when the chaos fleet needs journaled
            # failover over the same deterministic model.
            "journal_dir": getattr(args, "journal_dir", None),
            "journal_every_s": getattr(args, "journal_every_s", 5.0),
            "assoc_dedup_window_s": getattr(
                args, "assoc_dedup_window_s", 2.0
            ),
        },
    )


def drive(args, service, stations, waves, rounds):
    """Feed the whole network through POST /stream semantics, replaying
    the arrival schedule ``make_schedule`` resolved (dup/late/dropout
    fates and all). ``--workers`` threads each OWN stations ``w::W``
    (per-station packet order is a protocol invariant); within a worker,
    rounds advance in schedule order, so picks reach the associator in
    roughly scenario-time order."""
    from seist_tpu.serve.protocol import Overloaded, QueueFull, ServeError

    options = {"ppk_threshold": 0.5, "spk_threshold": 0.95,
               "det_threshold": 0.95, "sampling_rate": args.fs}
    by_id = {st["id"]: st for st in stations}

    lock = threading.Lock()
    out = {"alerts": [], "sheds": 0, "errors": 0, "packets": 0,
           "windows": 0}

    def send(st, body_data, seq, end=False):
        body = {
            "model": "twinpick",
            "station": {k: st[k] for k in ("id", "network", "lat", "lon")},
            "seq": seq,
            "options": options,
        }
        if body_data is not None:
            body["data"] = body_data
        if end:
            body["end"] = True
        try:
            r = service.stream(body)
        except (Overloaded, QueueFull):
            with lock:
                out["sheds"] += 1
            return
        except ServeError:
            with lock:
                out["errors"] += 1
            return
        with lock:
            out["packets"] += 1
            out["windows"] += r["windows"]
            out["alerts"].extend(r["alerts"])

    def worker(w):
        # Whole body under try: (threadlint thread-target-raises).
        try:
            mine = {st["id"] for st in stations[w :: max(1, args.workers)]}
            for rnd in rounds:
                for pkt in rnd:
                    sid = pkt["station"]
                    if sid not in mine:
                        continue
                    st = by_id[sid]
                    if pkt.get("end"):
                        send(st, None, pkt["seq"], end=True)
                    else:
                        data = waves[sid][pkt["lo"]:pkt["hi"]].tolist()
                        send(st, data, pkt["seq"])
        except BaseException as e:  # noqa: BLE001
            with lock:
                out["errors"] += 1
            sys.stderr.write(f"[twin] worker {w} died: {e!r}\n")

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(max(1, args.workers))]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    out["wall_s"] = time.monotonic() - t0
    return out


# -------------------------------------------------------------- gates
def _pct(vals, q):
    return round(float(np.percentile(np.asarray(vals), q)), 3) if vals else -1.0


def evaluate(args, events, expected, run, stream_stats):
    """Ground truth vs observed alerts -> the gate ledger."""
    t_main = events[0]["t"]
    main_alerts = [a for a in run["alerts"]
                   if abs(a["origin"]["t_s"] - t_main) <= 3.0]
    # Coverage credit is TRUTH-based: a station counts as covered when
    # its known mainshock arrival appears as a pick in ANY alert —
    # including an outlier alert whose origin landed on a remote grid
    # node (its picks are still real mainshock detections that reached
    # the alert plane; only the location was degraded).
    exp_main = expected["mainshock"]
    union = set()
    for a in run["alerts"]:
        for p in a["picks"]:
            t_true = exp_main.get(p["station"])
            if t_true is not None and abs(p["t_s"] - t_true) <= 0.5:
                union.add(p["station"])
    # The associator can never alert on the last < min_stations pending
    # picks — the reachable coverage bound.
    need = len(exp_main) - (args.min_stations - 1)
    # Location gate on the MEDIAN over mainshock alerts: a quorum-sized
    # leftover pick set can cohere at a remote grid node (the known
    # moveout-compression degeneracy) — one such outlier alert must not
    # decide the gate either way.
    errs = sorted(
        max(abs(a["origin"]["lat"] - events[0]["lat"]),
            abs(a["origin"]["lon"] - events[0]["lon"]))
        for a in main_alerts
    )
    origin_err_deg = round(errs[len(errs) // 2], 4) if errs else -1.0

    aft = [ev for ev in events[1:]
           if len(expected[ev["name"]]) >= args.min_stations]
    aft_detected = sum(
        1 for ev in aft
        if any(abs(a["origin"]["t_s"] - ev["t"]) <= 3.0
               for a in run["alerts"])
    )

    s2a = [a["latency_ms"]["sample_to_alert"] for a in run["alerts"]
           if "sample_to_alert" in a["latency_ms"]]
    stages = {}
    for key in ("arrival_to_due", "due_to_queue", "queue_device",
                "pick", "association", "sample_to_alert"):
        vals = [a["latency_ms"][key] for a in run["alerts"]
                if key in a["latency_ms"]]
        stages[key] = {"p50": _pct(vals, 50), "p99": _pct(vals, 99)}

    gates = {
        "mainshock_alert_emitted": len(main_alerts) >= 1,
        "mainshock_all_picks_covered": len(union) >= need,
        "mainshock_origin_within_half_deg":
            0.0 <= origin_err_deg <= 0.5,
        "zero_alert_tier_sheds": run["sheds"] == 0 and run["errors"] == 0,
        "zero_dropped_windows":
            stream_stats.get("windows_dropped", -1.0) == 0.0,
        "zero_degraded_sessions":
            stream_stats.get("degraded_sessions", -1.0) == 0.0,
        "p99_sample_to_alert_within_budget":
            bool(s2a) and _pct(s2a, 99) <= args.p99_budget_ms,
        "duplicates_exercised": stream_stats.get("duplicates", 0.0) > 0.0,
        "gaps_exercised": stream_stats.get("gaps", 0.0) > 0.0,
    }
    detail = {
        "mainshock_alerts": len(main_alerts),
        "mainshock_expected_stations": len(exp_main),
        "mainshock_stations_covered": len(union),
        "mainshock_coverage_floor": need,
        "mainshock_origin_err_deg_median": origin_err_deg,
        "aftershocks_alertable": len(aft),
        "aftershocks_detected": aft_detected,
        "alerts_total": len(run["alerts"]),
        "p99_sample_to_alert_ms": _pct(s2a, 99),
        "latency_stages_ms": stages,
    }
    return gates, detail


def main(argv: Optional[List[str]] = None) -> int:
    args = get_args(argv)
    stations, events, waves, expected = build_scenario(args)
    rounds = make_schedule(args, stations)
    print(f"[twin] scenario: {len(stations)} stations "
          f"({sum(s['noise'] for s in stations)} noise, 5 dropout, "
          f"3 late, 4 dup), mainshock @ {events[0]['t']:.1f}s, "
          f"{len(events) - 1} aftershocks, {args.duration_s:.0f}s @ "
          f"{args.fs} Hz", flush=True)
    if args.export_schedule:
        export_schedule(args.export_schedule, args, stations, events,
                        rounds)
        print(f"[twin] arrival schedule -> {args.export_schedule}",
              flush=True)

    service = _make_service(args)
    try:
        run = drive(args, service, stations, waves, rounds)
        stream_stats = service.metrics()["stream"].get("twinpick", {})
    finally:
        service.shutdown()

    gates, detail = evaluate(args, events, expected, run, stream_stats)
    ok = all(gates.values())
    result = {
        "metric": "stream_twin_p99_sample_to_alert_ms",
        "value": detail["p99_sample_to_alert_ms"],
        "unit": "ms",
        "budget_ms": args.p99_budget_ms,
        "gates": gates,
        "detail": detail,
        "scenario": {
            "stations": args.stations,
            "duration_s": args.duration_s,
            "window": args.window,
            "fs": args.fs,
            "seed": args.seed,
            "events": len(events),
            "min_stations": args.min_stations,
        },
        "run": {
            "packets": run["packets"],
            "windows": run["windows"],
            "sheds": run["sheds"],
            "errors": run["errors"],
            "wall_s": round(run["wall_s"], 3),
        },
        "stream_stats": stream_stats,
        "measured_at": datetime.now(timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"),
        "pass": ok,
    }
    if args.output:
        with open(args.output, "w") as f:
            json.dump(result, f, indent=1, sort_keys=False)
            f.write("\n")
    for name, good in gates.items():
        print(f"[twin] {'PASS' if good else 'FAIL'}  {name}", flush=True)
    print(f"[twin] {'PASS' if ok else 'FAIL'}: "
          f"{detail['alerts_total']} alerts, mainshock covered "
          f"{detail['mainshock_stations_covered']}/"
          f"{detail['mainshock_expected_stations']} stations, "
          f"p99 sample->alert {detail['p99_sample_to_alert_ms']:.1f} ms "
          f"(budget {args.p99_budget_ms:.0f} ms) -> {args.output}",
          flush=True)
    return 0 if ok else GATE_EXIT_CODE


if __name__ == "__main__":
    sys.exit(main())
