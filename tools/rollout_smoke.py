"""Rollout smoke (`make rollout-smoke`): a real 2-replica phasenet fleet
is rolled to a new model version while a sustained open-loop bench runs
against the router — the zero-downtime acceptance in one command
(docs/SERVING.md "Live rollout").

Asserts, from the bench's own JSON:

* ``error_rate == 0.0`` — not one request failed across the roll;
* ``converged_at_s > 0`` — the fleet reached the target version while
  the load was still running;
* ``stale_after_convergence == 0`` — after convergence, no response
  carried the old version;
* both versions appear in ``by_version`` (the run really spanned the
  roll);

and, from the supervisor log, that each replica was drained, relaunched
and probed ready one at a time. Prints one JSON verdict line; exit 0/1.
"""

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
sys.path.insert(0, HERE)

WINDOW = 256
TARGET_VERSION = 2


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _drain(pipe, buf):
    # The whole body under try: a reader surprise must not silently stop
    # draining the fleet's pipe — a full kernel buffer would wedge every
    # fleet process on its next write (threadlint thread-target-raises).
    try:
        for line in pipe:
            buf.append(line)
    except Exception as e:  # noqa: BLE001
        buf.append(f"[rollout_smoke] pipe drain died: {e!r}\n")


def main() -> int:
    import tempfile

    import bench_serve

    tmp = tempfile.mkdtemp(prefix="rollout_smoke_")
    spec_path = os.path.join(tmp, "rollout.json")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [
            sys.executable, os.path.join(HERE, "supervise_fleet.py"),
            "--replicas", "2",
            "--base-port", str(_free_port()),
            "--router-port", "0",
            "--probe-interval-s", "0.3",
            "--router-retries", "3",
            "--request-timeout-s", "30",
            "--rollout-file", spec_path,
            "--rollout-ready-timeout-s", "240",
            "--",
            sys.executable, os.path.join(REPO, "main.py"), "serve",
            "--model", "phasenet=",
            "--window", str(WINDOW),
            "--max-batch", "4",
            "--max-delay-ms", "5",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True,
    )
    err_buf: list = []
    threading.Thread(
        target=_drain, args=(proc.stderr, err_buf), daemon=True
    ).start()
    router = None
    for _ in range(50):
        line = proc.stdout.readline()
        if not line:
            break
        m = re.search(r"ROUTER=http://([\d.]+):(\d+)", line)
        if m:
            router = f"http://{m.group(1)}:{m.group(2)}"
            break
    threading.Thread(
        target=_drain, args=(proc.stdout, []), daemon=True
    ).start()
    verdict = {"metric": "rollout_smoke", "ok": False}
    bench_ok = False
    try:
        if router is None:
            verdict["error"] = "no ROUTER line from supervise_fleet"
            return _finish(proc, err_buf, verdict, bench_ok)
        # Wait for both replicas probed-ready (first run pays compiles).
        from seist_tpu.serve.router import _http_request

        deadline = time.monotonic() + 300.0
        while time.monotonic() < deadline:
            try:
                _, _, body = _http_request(
                    router, "GET", "/router/replicas", timeout_s=3.0
                )
                reps = json.loads(body.decode()).get("replicas", [])
                if sum(
                    1 for r in reps if r.get("probe_state") == "ok"
                ) >= 2:
                    break
            except OSError:
                pass
            time.sleep(0.3)
        else:
            verdict["error"] = "fleet never warmed"
            return _finish(proc, err_buf, verdict, bench_ok)

        results = {}

        def run_bench():
            # Missing results["bench"] IS the recorded death signal the
            # main thread checks (threadlint thread-target-raises).
            try:
                out = os.path.join(tmp, "bench.json")
                rc = bench_serve.main([
                    "--url", router,
                    "--window", str(WINDOW),
                    "--model-name", "phasenet",
                    "--arrival-rps", "5",
                    "--duration-s", "90",
                    "--concurrency", "32",
                    "--timeout-ms", "30000",
                    "--expect-version", str(TARGET_VERSION),
                    "--output", out,
                ])
                with open(out) as f:
                    results["bench"] = (rc, json.load(f))
            except Exception as e:  # noqa: BLE001
                sys.stderr.write(f"[rollout_smoke] bench died: {e!r}\n")

        t = threading.Thread(target=run_bench)
        t.start()
        time.sleep(3.0)
        with open(spec_path, "w") as f:
            json.dump({"version": TARGET_VERSION}, f)
        proc.send_signal(signal.SIGHUP)
        t.join(timeout=300.0)
        if "bench" not in results:
            verdict["error"] = "bench never finished"
            return _finish(proc, err_buf, verdict, bench_ok)
        rc, res = results["bench"]
        verdict.update({
            "bench_rc": rc,
            "requests": res["requests"],
            "error_rate": res["error_rate"],
            "by_version": res["by_version"],
            "converged_at_s": res.get("converged_at_s"),
            "stale_after_convergence": res.get("stale_after_convergence"),
        })
        bench_ok = all([
            rc == 0,
            res["error_rate"] == 0.0,
            res.get("converged_at_s", -1) > 0,
            res.get("stale_after_convergence", -1) == 0,
            res["by_version"].get("1", 0) > 0,
            res["by_version"].get(str(TARGET_VERSION), 0) > 0,
        ])
        return _finish(proc, err_buf, verdict, bench_ok)
    except BaseException:
        _finish(proc, err_buf, verdict, bench_ok)
        raise


def _finish(proc, err_buf, verdict, bench_ok) -> int:
    """Tear the fleet down, fold the supervisor-log checks into the
    verdict, print it, return the exit code."""
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=90)
    except subprocess.TimeoutExpired:
        proc.kill()
    err = "".join(err_buf)
    verdict["rollout_log_ok"] = bool(
        re.search(rf"rollout complete: version {TARGET_VERSION}", err)
        and all(f"rollout: draining replica {i}" in err for i in (0, 1))
    )
    verdict["ok"] = bool(bench_ok and verdict["rollout_log_ok"])
    print(json.dumps(verdict), flush=True)
    if not verdict["ok"]:
        sys.stderr.write(err[-4000:])
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
