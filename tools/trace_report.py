"""Cross-process trace stitcher: pull one request's span segments from
every fleet endpoint's ``GET /traces/<id>``, assemble the parent/child
tree, and print where the milliseconds went.

Each process on the request path (bench client -> router -> replica)
keeps only its OWN span segments (seist_tpu/obs/trace.py); the trace id
is the join key and the ``traceparent`` parent span ids are the edges:
the router's per-attempt span id travels downstream in the header, so a
replica's ``server:/predict`` root parents to the exact attempt that
carried it. Stitching is therefore a pure merge — no clock coordination
beyond the hosts' wall clocks (sub-ms on one box; skew across boxes
shows up as child-outside-parent, flagged in the report).

    # the id comes from a response's `traceparent` header, a bench
    # exemplar (bench_serve JSON `trace_exemplars`), or GET /traces
    python tools/trace_report.py --trace <32-hex-id> \
        --endpoint http://127.0.0.1:8080 \
        --endpoint http://127.0.0.1:18100 --endpoint http://127.0.0.1:18101

    # discover replica endpoints from the router, pick exemplars from a
    # bench_serve --output JSON:
    python tools/trace_report.py --from-bench bench.json \
        --router http://127.0.0.1:8080

Exit codes: 0 = stitched, 1 = no segments found anywhere, 2 = usage.
Used by ``make trace-smoke`` (tools/trace_smoke.py) and the serve-chaos
trace acceptance test; jax-free (front-tier safe).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))

from seist_tpu.serve.router import _http_request  # noqa: E402 (jax-free)


def fetch_trace(endpoint: str, trace_id: str,
                timeout_s: float = 5.0) -> Optional[Dict[str, Any]]:
    """GET <endpoint>/traces/<id>; None on 404/network failure (a
    process that sampled the trace out, restarted, or is gone — the
    stitch uses whatever segments survive)."""
    import http.client

    try:
        status, _, body = _http_request(
            endpoint, "GET", f"/traces/{trace_id}", timeout_s=timeout_s
        )
    except (OSError, http.client.HTTPException):
        return None
    if status != 200:
        return None
    try:
        payload = json.loads(body.decode())
    except ValueError:
        return None
    return payload if isinstance(payload, dict) else None


def replica_endpoints(router_url: str,
                      timeout_s: float = 5.0) -> List[str]:
    """The router's registry as scrape-able base URLs."""
    import http.client

    try:
        status, _, body = _http_request(
            router_url, "GET", "/router/replicas", timeout_s=timeout_s
        )
        if status != 200:
            return []
        payload = json.loads(body.decode())
        return [r["url"] for r in payload.get("replicas", [])]
    except (OSError, ValueError, KeyError, http.client.HTTPException):
        return []


# ------------------------------------------------------------- stitching
class StitchedTrace:
    """The merged cross-process view of one trace."""

    def __init__(self, trace_id: str, spans: List[Dict[str, Any]],
                 flags: Sequence[str]):
        self.trace_id = trace_id
        self.spans = spans
        self.flags = sorted(set(flags))
        by_id = {s["span_id"]: s for s in spans}
        self.roots: List[Dict[str, Any]] = []
        self.children: Dict[str, List[Dict[str, Any]]] = {}
        for s in spans:
            parent = s.get("parent_id")
            if parent and parent in by_id:
                self.children.setdefault(parent, []).append(s)
            else:
                # Orphans (parent process lost/sampled out) surface as
                # extra roots instead of disappearing.
                self.roots.append(s)
        for kids in self.children.values():
            kids.sort(key=lambda s: s.get("t0", 0.0))
        self.roots.sort(key=lambda s: s.get("t0", 0.0))

    @property
    def total_ms(self) -> float:
        """The stitched tree's total: the primary (earliest) root span's
        duration — the top of the request as the outermost process saw
        it. (Wall extent across all spans can exceed this only via
        cross-host clock skew; hedged attempts overlap INSIDE it.)"""
        return float(self.roots[0]["dur_ms"]) if self.roots else 0.0

    def span_sum_ms(self) -> float:
        """Sum of leaf-level exclusive durations is meaningless under
        hedging (parallel attempts); the acceptance metric is the root
        total vs the client-observed latency."""
        return self.total_ms

    def processes(self) -> List[str]:
        return sorted({s.get("process", "?") for s in self.spans})

    def find(self, name: str) -> List[Dict[str, Any]]:
        return [s for s in self.spans if s.get("name") == name]

    # ------------------------------------------------------------ rendering
    def format(self) -> str:
        lines = [
            f"trace {self.trace_id}  total {self.total_ms:.1f} ms  "
            f"processes={','.join(self.processes())}"
            + (f"  flags={','.join(self.flags)}" if self.flags else "")
        ]

        def walk(span: Dict[str, Any], depth: int, last: bool) -> None:
            pad = "   " * (depth - 1) + ("└─ " if last else "├─ ") \
                if depth else ""
            ann = span.get("annotations") or {}
            ann_str = " ".join(
                f"{k}={v}" for k, v in sorted(ann.items())
            )
            lines.append(
                f"{pad}{span.get('name', '?')}  "
                f"{span.get('dur_ms', 0.0):.1f} ms  "
                f"[{span.get('process', '?')}]"
                + (f"  {ann_str}" if ann_str else "")
            )
            kids = self.children.get(span["span_id"], [])
            for i, kid in enumerate(kids):
                walk(kid, depth + 1, i == len(kids) - 1)

        for i, root in enumerate(self.roots):
            walk(root, 0, i == len(self.roots) - 1)
        return "\n".join(lines)


def stitch(segments: Sequence[Optional[Dict[str, Any]]],
           trace_id: str = "") -> StitchedTrace:
    """Merge per-process ``/traces/<id>`` payloads (Nones skipped) into
    one tree; span ids dedup (the same endpoint fetched twice is
    harmless)."""
    seen: Dict[str, Dict[str, Any]] = {}
    flags: List[str] = []
    for seg in segments:
        if not seg:
            continue
        trace_id = trace_id or seg.get("trace_id", "")
        flags.extend(seg.get("flags", ()))
        for span in seg.get("spans", ()):
            sid = span.get("span_id")
            if sid and sid not in seen:
                s = dict(span)
                s.setdefault("process", seg.get("process", "?"))
                seen[sid] = s
    return StitchedTrace(trace_id, list(seen.values()), flags)


def stitch_from_endpoints(trace_id: str,
                          endpoints: Sequence[str]) -> StitchedTrace:
    return stitch(
        [fetch_trace(ep, trace_id) for ep in endpoints], trace_id
    )


# ------------------------------------------------------------------ CLI
def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="stitch one request's distributed trace across the "
        "fleet's /traces endpoints"
    )
    ap.add_argument("--trace", action="append", default=[],
                    metavar="TRACE_ID", help="trace id(s) to stitch")
    ap.add_argument("--from-bench", default="",
                    help="bench_serve --output JSON: stitch its "
                    "trace_exemplars (slowest + failed)")
    ap.add_argument("--endpoint", action="append", default=[],
                    metavar="URL", help="a /traces-serving endpoint "
                    "(router, replica, train worker), repeatable")
    ap.add_argument("--router", default="",
                    help="router URL: also auto-discovers the replica "
                    "endpoints from its registry")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable JSON instead of the tree")
    args = ap.parse_args(argv)

    trace_ids = list(args.trace)
    if args.from_bench:
        with open(args.from_bench) as f:
            bench = json.load(f)
        exemplars = bench.get("trace_exemplars", {})
        trace_ids.extend(
            e["trace_id"]
            for group in ("failed", "slowest")
            for e in exemplars.get(group, ())
            if e.get("trace_id")
        )
    endpoints = list(args.endpoint)
    if args.router:
        endpoints.append(args.router)
        endpoints.extend(replica_endpoints(args.router))
    if not trace_ids:
        ap.error("no trace ids (--trace or --from-bench)")
    if not endpoints:
        ap.error("no endpoints (--endpoint or --router)")

    found_any = False
    out_json: List[Dict[str, Any]] = []
    for tid in dict.fromkeys(trace_ids):  # dedup, keep order
        st = stitch_from_endpoints(tid, endpoints)
        if not st.spans:
            print(f"trace {tid}: no segments at any endpoint",
                  file=sys.stderr)
            continue
        found_any = True
        if args.json:
            out_json.append({
                "trace_id": tid,
                "total_ms": st.total_ms,
                "flags": st.flags,
                "processes": st.processes(),
                "spans": st.spans,
            })
        else:
            print(st.format())
            print()
    if args.json:
        print(json.dumps(out_json))
    return 0 if found_any else 1


if __name__ == "__main__":
    sys.exit(main())
