"""Observability smoke (``make obs-smoke``; docs/OBSERVABILITY.md).

One subprocess train run proves the telemetry plane end to end:

1. boots a tiny CPU train run with ``--metrics-port -1`` (ephemeral) and
   an injected data-plane stall (``SEIST_FAULT_IO_STALL_*``) two batches
   in, with a short ``--data-watchdog-sec``;
2. while the loader is wedged (the watchdog's grace window), scrapes the
   live endpoint: ``/metrics`` must serve Prometheus text with the span
   histograms, ``/metrics.json`` + ``/flight`` must serve JSON, and
   ``POST /profile`` must accept a capture request;
3. the stall watchdog then trips: the run must exit with the
   clean-preempt code (75) and leave a flight-recorder dump containing
   the final steps' records and their host_wait/step_dispatch spans.

Prints one JSON result line on stdout; exit 0 iff every assertion held.
Wired into the chaos lane via tests/test_obs_e2e.py.
"""

from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys
import tempfile
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PREEMPT_EXIT_CODE = 75
ENDPOINT_RE = re.compile(r"metrics endpoint: (http://127\.0\.0\.1:\d+)/metrics")


def _fail(msg: str, **extra) -> None:
    print(json.dumps({"ok": False, "error": msg, **extra}))
    sys.exit(1)


def _get(url: str, timeout: float = 5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


def main() -> None:
    log_base = tempfile.mkdtemp(prefix="obs_smoke_")
    out_path = os.path.join(log_base, "stdout.log")
    cmd = [
        sys.executable, "main.py",
        "--mode", "train",
        "--model-name", "phasenet",
        "--dataset-name", "synthetic",
        "--synthetic-events", "48",
        "--batch-size", "8",
        "--in-samples", "256",
        "--epochs", "1",
        "--workers", "2",
        "--augmentation", "0",
        "--use-tensorboard", "0",
        "--log-step", "1",
        "--log-base", log_base,
        "--metrics-port", "-1",
        "--data-watchdog-sec", "12",
    ]
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        # Wedge the loader at batch 2 of epoch 0: steps 0-1 complete
        # (spans + flight records exist), then the run hangs long enough
        # to scrape the live endpoint before the watchdog trips.
        SEIST_FAULT_IO_STALL_BATCH="2",
        SEIST_FAULT_IO_STALL_SEC="600",
    )
    with open(out_path, "w") as out_f:
        proc = subprocess.Popen(
            cmd, cwd=_REPO, env=env, stdout=out_f, stderr=subprocess.STDOUT
        )
        try:
            # -- find the ephemeral endpoint in the run log ---------------
            base_url = None
            deadline = time.time() + 240  # cold jit compile dominates
            while time.time() < deadline and base_url is None:
                if proc.poll() is not None:
                    _fail(
                        f"run exited rc={proc.returncode} before the "
                        "metrics endpoint came up",
                        log_tail=open(out_path).read()[-2000:],
                    )
                m = ENDPOINT_RE.search(open(out_path).read())
                if m:
                    base_url = m.group(1)
                else:
                    time.sleep(0.5)
            if base_url is None:
                proc.kill()
                _fail("metrics endpoint never logged",
                      log_tail=open(out_path).read()[-2000:])

            # -- scrape the live plane (stall grace window) ---------------
            # Wait until at least one step's spans landed.
            prom = ""
            deadline = time.time() + 200
            while time.time() < deadline:
                status, prom = _get(base_url + "/metrics")
                if status == 200 and "seist_step_dispatch_ms_count" in prom:
                    break
                time.sleep(0.5)
            checks = {
                "prom_step_dispatch": "seist_step_dispatch_ms_count" in prom,
                "prom_host_wait": "seist_host_wait_ms_count" in prom,
                "prom_data_plane": "seist_data_plane_reads" in prom,
                "prom_loss_gauge": "seist_train_loss" in prom,
            }
            status, snap = _get(base_url + "/metrics.json")
            checks["json_snapshot"] = (
                status == 200 and "histograms" in json.loads(snap)
            )
            status, fl = _get(base_url + "/flight")
            flight_live = json.loads(fl)
            checks["flight_live_steps"] = (
                status == 200 and len(flight_live.get("steps", [])) >= 1
            )
            req = urllib.request.Request(
                base_url + "/profile?steps=2", method="POST", data=b""
            )
            with urllib.request.urlopen(req, timeout=5) as r:
                checks["profile_trigger"] = (
                    json.loads(r.read())["requested_steps"] == 2
                )

            # -- watchdog trip: rc 75 + flight dump -----------------------
            try:
                rc = proc.wait(timeout=120)
            except subprocess.TimeoutExpired:
                # The regression this smoke exists to catch: the watchdog
                # never tripped and the stalled run hung. Report it on
                # the one-line JSON contract, not as a traceback.
                proc.kill()
                _fail(
                    "stall watchdog never tripped within 120 s "
                    "(run still alive)",
                    checks=checks,
                    log_tail=open(out_path).read()[-2000:],
                )
        finally:
            if proc.poll() is None:
                proc.kill()

    checks["exit_code_75"] = rc == PREEMPT_EXIT_CODE
    dumps = sorted(glob.glob(
        os.path.join(log_base, "*", "flight", "flight_stall_watchdog_*.json")
    ))
    checks["dump_exists"] = bool(dumps)
    if dumps:
        dump = json.load(open(dumps[0]))
        span_names = {s["name"] for s in dump.get("spans", [])}
        checks["dump_reason"] = dump.get("reason") == "stall_watchdog"
        checks["dump_has_steps"] = len(dump.get("steps", [])) >= 1
        checks["dump_span_kinds"] = {"host_wait", "step_dispatch"} <= span_names
        checks["dump_thread_stacks"] = "seist-data-watchdog" in str(
            dump.get("thread_stacks", "")
        )

    ok = all(checks.values())
    print(json.dumps({
        "ok": ok,
        "rc": rc,
        "checks": checks,
        "dump": dumps[0] if dumps else None,
        "log_base": log_base,
    }))
    if not ok:
        print(open(out_path).read()[-3000:], file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
