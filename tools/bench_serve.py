"""Serving latency/throughput bench -> BENCH-style one-line JSON.

Drives the in-process ServeService (no sockets — measures batching +
forward + decode, not loopback TCP) with a closed-loop client pool, then
reports client-observed latency percentiles, throughput and the
batch-fill ratio from /metrics:

    python tools/bench_serve.py --model-name phasenet --window 256 \
        --requests 64 --concurrency 8 [--checkpoint CKPT] \
        [--output BENCH_serve.json]

Emits {"metric": "serve_predict_latency", "p50_ms": ..., "p99_ms": ...,
"throughput_rps": ..., "batch_fill_ratio": ...} — the same trajectory
shape as the BENCH_*.json training numbers. `make serve-smoke` runs a
small CPU configuration of exactly this.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from concurrent.futures import ThreadPoolExecutor
from datetime import datetime, timezone

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))


def main() -> None:
    ap = argparse.ArgumentParser(description="serve micro-batching bench")
    ap.add_argument("--model-name", default="phasenet")
    ap.add_argument("--checkpoint", default="",
                    help="optional; fresh-init weights when omitted")
    ap.add_argument("--window", type=int, default=256)
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-delay-ms", type=float, default=10.0)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--timeout-ms", type=float, default=60_000.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--output", default="", help="also write JSON here")
    args = ap.parse_args()

    from seist_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()

    import numpy as np

    from seist_tpu.serve import BatcherConfig, ModelPool, ServeService
    from seist_tpu.utils.profiling import stopwatch

    pool = ModelPool(
        [(args.model_name, args.checkpoint)], window=args.window,
        seed=args.seed,
    )
    service = ServeService(
        pool,
        BatcherConfig(
            max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms,
            max_queue=args.max_queue,
        ),
    )
    entry = pool.get(args.model_name)
    rng = np.random.default_rng(args.seed)
    traces = [
        rng.standard_normal((args.window, entry.in_channels))
        .astype(np.float32).tolist()
        for _ in range(min(args.requests, 32))  # cycle a small pool
    ]
    options = {"timeout_ms": args.timeout_ms}
    if entry.is_picker:
        options.update(ppk_threshold=0.05, spk_threshold=0.05)

    latencies_ms = []

    def one(i: int) -> None:
        with stopwatch() as elapsed:
            service.predict(traces[i % len(traces)], options=options)
        latencies_ms.append(elapsed() * 1000.0)

    with stopwatch() as wall:
        with ThreadPoolExecutor(args.concurrency) as ex:
            list(ex.map(one, range(args.requests)))
    service.shutdown()

    lat = np.asarray(latencies_ms)
    stats = service.metrics()["models"][args.model_name]
    import jax

    result = {
        "metric": "serve_predict_latency",
        "model": args.model_name,
        "window": args.window,
        "requests": args.requests,
        "concurrency": args.concurrency,
        "max_batch": args.max_batch,
        "max_delay_ms": args.max_delay_ms,
        "p50_ms": round(float(np.percentile(lat, 50)), 3),
        "p90_ms": round(float(np.percentile(lat, 90)), 3),
        "p99_ms": round(float(np.percentile(lat, 99)), 3),
        "mean_ms": round(float(lat.mean()), 3),
        "throughput_rps": round(args.requests / wall(), 2),
        "batch_fill_ratio": round(stats["batch_fill_ratio"], 4),
        "forwards": stats["forwards"],
        "completed": stats["completed"],
        "device": jax.devices()[0].device_kind,
        "measured_at": datetime.now(timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"
        ),
    }
    line = json.dumps(result)
    print(line)
    if args.output:
        with open(args.output, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
