"""Serving latency/throughput bench + SLO gate -> BENCH-style JSON.

Two client modes against two kinds of target:

* **closed-loop** (default): ``--concurrency`` workers each fire the next
  request as soon as the previous answers — measures the service's
  best-case batching behavior.
* **open-loop** (``--arrival-rps R``): requests are *launched on a
  Poisson-less fixed-interval arrival clock* regardless of completions —
  the production traffic model (arXiv:2605.25645: closed-loop numbers
  flatter a service because overload slows the offered load down).
  Combined with ``--slo-p99-ms`` this is the ROADMAP SLO harness: exit 3
  when the p99 (or the error budget, ``--max-error-rate``) is violated.

* **in-process** (default): builds a ServeService in this process — no
  sockets, measures batching + forward + decode.
* **HTTP** (``--url http://host:port``): drives a live replica or the
  fleet router over real sockets — the serve-chaos lane's client.

Every request error is caught and *accounted*, never aborts the bench:
the JSON carries ``error_rate`` and per-status counts (a shed 503 and a
queue-full 429 are different statuses by design — docs/SERVING.md).

    python tools/bench_serve.py --model-name phasenet --window 256 \
        --requests 64 --concurrency 8 [--checkpoint CKPT]
    python tools/bench_serve.py --url http://127.0.0.1:8080 \
        --arrival-rps 200 --requests 400 --priority alert \
        --slo-p99-ms 250 --window 256

`make serve-smoke` runs a small CPU configuration of the in-process mode.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from datetime import datetime, timezone
from typing import Any, Dict, List, Optional, Tuple

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))

#: exit code for an SLO-gate violation (distinct from crash=1/usage=2)
SLO_EXIT_CODE = 3


def get_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(description="serve bench + SLO gate")
    ap.add_argument("--model-name", default="phasenet")
    ap.add_argument("--checkpoint", default="",
                    help="optional; fresh-init weights when omitted")
    ap.add_argument("--window", type=int, default=256)
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--concurrency", type=int, default=8,
                    help="closed-loop workers; in open-loop mode the "
                    "client-side in-flight cap is 4x this (burst "
                    "headroom so overload is shed by the SERVICE, not "
                    "dropped at the client)")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-delay-ms", type=float, default=10.0)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--timeout-ms", type=float, default=60_000.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--output", default="", help="also write JSON here")
    # --- new: target / traffic shape / gate -------------------------------
    ap.add_argument("--url", default="",
                    help="drive a live HTTP endpoint (replica or router) "
                    "instead of an in-process service")
    ap.add_argument("--in-channels", type=int, default=3,
                    help="trace channels for --url mode (in-process mode "
                    "reads it from the model)")
    ap.add_argument("--priority", default="",
                    help="request tier: alert | interactive | batch "
                    "(empty = service default)")
    ap.add_argument("--tasks", default="",
                    help="comma-separated task heads for multi-task "
                    "fan-out (e.g. dpk,emg,dis): --model-name is then a "
                    "SeisT group prefix (e.g. seist_s) served on one "
                    "shared trunk; every response is checked to contain "
                    "ALL requested heads (missing_head error otherwise)")
    ap.add_argument("--variant", default="",
                    help="serving weight variant (fp32 | bf16 | int8); "
                    "in-process mode loads fp32 + the requested variant")
    ap.add_argument("--arrival-rps", type=float, default=0.0,
                    help="open-loop arrival rate (0 = closed loop)")
    ap.add_argument("--duration-s", type=float, default=0.0,
                    help="sustained-load mode: keep offering load for "
                    "this many seconds (open-loop: arrivals until the "
                    "deadline; closed-loop: workers loop until it) "
                    "instead of a fixed --requests count — the client "
                    "shape a rolling restart is measured under")
    ap.add_argument("--expect-version", type=int, default=0,
                    help="rollout acceptance gate: poll the router's "
                    "/router/replicas until every replica is ready on "
                    "this model version (convergence), then require "
                    "ZERO responses launched after convergence to carry "
                    "another version (stale_after_convergence == 0); "
                    "exit 1 otherwise. Requires --url (router). Every "
                    "response's model_version is counted in by_version "
                    "regardless")
    ap.add_argument("--slo-p99-ms", type=float, default=0.0,
                    help=f"gate: exit {SLO_EXIT_CODE} if p99 of SUCCESSFUL "
                    "requests exceeds this (0 = no gate)")
    ap.add_argument("--max-error-rate", type=float, default=0.0,
                    help="gate companion: tolerated error_rate before the "
                    "SLO gate trips (default 0 = any error trips it when "
                    "--slo-p99-ms is set)")
    ap.add_argument("--trace-log", default="",
                    help="also write one JSONL line per request "
                    "({trace_id, status, latency_ms}) — the lookup table "
                    "for stitching ANY request with tools/trace_report.py "
                    "(the output JSON always carries the slowest-N and "
                    "failed exemplars)")
    # High-fan-in streaming mode (POST /stream): N stations on an
    # open-loop packet cadence, per-station latency accounting.
    ap.add_argument("--stream-stations", type=int, default=0,
                    help="streaming bench: drive this many stations "
                    "through POST /stream on an open-loop per-station "
                    "packet cadence (0 = normal /predict bench)")
    ap.add_argument("--stream-cadence-s", type=float, default=0.0,
                    help="seconds between one station's packets "
                    "(0 = real time: packet_samples / 50 Hz)")
    ap.add_argument("--stream-packet-samples", type=int, default=0,
                    help="samples per packet (0 = window // 2, one "
                    "stride per packet at the default session stride)")
    return ap.parse_args(argv)


class _Stats:
    """Thread-safe per-request accounting: latencies of successes, error
    counts by HTTP status and by serve error code, and the per-request
    trace ids so a bench run hands you the exact traces to pull from
    ``GET /traces/<id>`` (p99 exemplars + every failure)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.latencies_ms: List[float] = []
        self.successes: List[Dict[str, Any]] = []  # {trace_id, latency_ms}
        self.failed: List[Dict[str, Any]] = []  # {trace_id, status, code}
        self.by_status: Dict[str, int] = {}
        self.by_code: Dict[str, int] = {}
        #: responses per served model_version ("unknown" when absent) —
        #: the rollout acceptance accounting (docs/SERVING.md).
        self.by_version: Dict[str, int] = {}
        #: (launched_at monotonic, version) per success, for the
        #: stale-after-convergence gate.
        self._versioned: List[Tuple[float, int]] = []
        self.ok = 0
        self.errors = 0

    def success(
        self,
        latency_ms: float,
        trace_id: str = "",
        version: Optional[int] = None,
        launched_at: float = 0.0,
    ) -> None:
        with self._lock:
            self.ok += 1
            self.by_status["200"] = self.by_status.get("200", 0) + 1
            key = str(version) if version is not None else "unknown"
            self.by_version[key] = self.by_version.get(key, 0) + 1
            if version is not None:
                self._versioned.append((launched_at, int(version)))
            self.latencies_ms.append(latency_ms)
            if trace_id:
                self.successes.append({
                    "trace_id": trace_id,
                    "latency_ms": round(latency_ms, 3),
                })

    def stale_after(self, converged_at: float, expect: int) -> int:
        """Successes LAUNCHED after the fleet converged on ``expect``
        that still reported another version — the zero-staleness gate's
        numerator. Launch time (not completion) is the honest clock: a
        request sent pre-convergence may legitimately answer old."""
        with self._lock:
            return sum(
                1 for launched, v in self._versioned
                if launched > converged_at and v != expect
            )

    def error(self, status: int, code: str, trace_id: str = "",
              latency_ms: float = 0.0) -> None:
        with self._lock:
            self.errors += 1
            key = str(status)
            self.by_status[key] = self.by_status.get(key, 0) + 1
            if code:
                self.by_code[code] = self.by_code.get(code, 0) + 1
            if trace_id:
                self.failed.append({
                    "trace_id": trace_id,
                    "status": status,
                    "code": code,
                    "latency_ms": round(latency_ms, 3),
                })

    def exemplars(self, slowest_n: int = 5,
                  failed_cap: int = 32) -> Dict[str, Any]:
        """The JSON block: trace ids of the slowest-N successes (the p99
        suspects) and every failed request (capped, count reported)."""
        with self._lock:
            successes = list(self.successes)
            failed = list(self.failed)
        slowest = sorted(
            successes, key=lambda e: e["latency_ms"], reverse=True
        )[:slowest_n]
        return {
            "slowest": slowest,
            "failed": failed[:failed_cap],
            "failed_total": len(failed),
        }


class _ConvergenceWatch:
    """Poll ``<router>/router/replicas`` until every listed replica is
    probe-ready AND reports only ``expect_version`` — the client-side
    definition of "the roll converged". ``converged_at`` (monotonic) is
    None until then."""

    def __init__(self, url: str, expect_version: int, poll_s: float = 0.3):
        self.url = url
        self.expect_version = int(expect_version)
        self.poll_s = poll_s
        self.converged_at: Optional[float] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="bench-converge", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def _converged(self, payload: Dict[str, Any]) -> bool:
        replicas = payload.get("replicas") or []
        if not replicas:
            return False
        for r in replicas:
            versions = r.get("versions") or {}
            if not r.get("ready") or not versions:
                return False
            try:
                if any(
                    int(v) != self.expect_version
                    for v in versions.values()
                ):
                    return False
            except (TypeError, ValueError):
                return False
        return True

    def _loop(self) -> None:
        # The whole body under try: a watcher surprise must not kill the
        # gate silently mid-bench (threadlint thread-target-raises) —
        # converged_at just stays None and the gate fails loudly.
        try:
            from seist_tpu.serve.router import _http_request

            while not self._stop.is_set() and self.converged_at is None:
                try:
                    status, _, body = _http_request(
                        self.url, "GET", "/router/replicas", timeout_s=2.0
                    )
                    if status == 200 and self._converged(
                        json.loads(body.decode())
                    ):
                        self.converged_at = time.monotonic()
                        return
                except Exception:  # noqa: BLE001 — poll again next tick
                    pass
                self._stop.wait(self.poll_s)
        except BaseException as e:  # noqa: BLE001
            print(f"[bench_serve] convergence watcher died: {e!r}",
                  file=sys.stderr, flush=True)


def _http_client(url: str, timeout_ms: float):
    """-> fn(payload_dict, traceparent) that POSTs /predict and returns
    (status, body dict); network failures surface as status 0. Transport
    is the router's own jax-free helper so the bench client and the
    front tier can't drift on HTTP semantics. The client IS the trace
    edge: the minted ``traceparent`` rides the request header."""
    import http.client

    from seist_tpu.serve.router import _http_request

    def call(payload: Dict[str, Any], traceparent: str = ""):
        body = json.dumps(payload).encode()
        headers = {"traceparent": traceparent} if traceparent else None
        try:
            status, _, raw = _http_request(
                url, "POST", "/predict", body,
                timeout_s=timeout_ms / 1000.0 + 5.0,
                headers=headers,
            )
        except (OSError, http.client.HTTPException) as e:
            return 0, {"error": "unreachable", "message": str(e)}
        try:
            out = json.loads(raw)
        except ValueError:
            out = {}
        # A non-object error body (some LBs answer 503 with a bare JSON
        # string) must not crash the accounting downstream.
        return status, out if isinstance(out, dict) else {"error": str(out)}

    return call


def main(argv: Optional[List[str]] = None) -> int:
    args = get_args(argv)

    if not args.url:
        # --url mode must run from jax-free front-tier boxes (the same
        # constraint as serve/router.py): nothing below may import jax.
        from seist_tpu.utils.platform import honor_jax_platforms

        honor_jax_platforms()

    if args.stream_stations > 0:
        return _run_stream_bench(args)

    import numpy as np

    # jax-free (obs/trace.py is stdlib + the bus): the bench client is
    # the trace edge — it mints every request's traceparent, so the ids
    # in its JSON are the exact handles for GET /traces/<id>.
    from seist_tpu.obs import trace as obs_trace
    from seist_tpu.utils.profiling import stopwatch

    options: Dict[str, Any] = {"timeout_ms": args.timeout_ms}
    if args.priority:
        options["priority"] = args.priority
    if args.variant:
        options["variant"] = args.variant
    tasks = [t for t in args.tasks.split(",") if t] if args.tasks else None

    service = None
    if args.url:
        in_channels = args.in_channels
        call = _http_client(args.url, args.timeout_ms)

        def one_request(waveform, traceparent: str) -> Any:
            payload = {"data": waveform, "options": options}
            if args.model_name:
                payload["model"] = args.model_name
            if tasks:
                payload["tasks"] = tasks
            return call(payload, traceparent)

    else:
        from seist_tpu.serve import BatcherConfig, ModelPool, ServeService
        from seist_tpu.serve.protocol import ServeError

        variants = ("fp32",) + ((args.variant,) if args.variant else ())
        if tasks:
            # Multi-task fan-out: --model-name is the SeisT group prefix;
            # one shared trunk serves every requested head.
            pool = ModelPool(
                groups=[(args.model_name, [(t, "") for t in tasks])],
                window=args.window, seed=args.seed, variants=variants,
            )
        else:
            pool = ModelPool(
                [(args.model_name, args.checkpoint)], window=args.window,
                seed=args.seed, variants=variants,
            )
        service = ServeService(
            pool,
            BatcherConfig(
                max_batch=args.max_batch,
                max_delay_ms=args.max_delay_ms,
                max_queue=args.max_queue,
            ),
        )
        entry = pool.get(args.model_name)
        in_channels = entry.in_channels
        if entry.is_picker and not tasks:
            options.update(ppk_threshold=0.05, spk_threshold=0.05)

        def one_request(waveform, traceparent: str) -> Any:
            # In-process mode: this process IS the server, so the trace
            # plays the HTTP handler's part (mint -> spans -> finish).
            rt = obs_trace.RequestTrace(traceparent,
                                        name="server:/predict")
            try:
                result = service.predict(
                    waveform, options=options, tasks=tasks, trace=rt
                )
                rt.finish(200)
                return 200, result
            except ServeError as e:
                if e.code == "shed":
                    rt.flag("shed")
                rt.finish(e.status)
                return e.status, e.payload()
            except BaseException:
                rt.finish(0)
                raise

    rng = np.random.default_rng(args.seed)
    traces = [
        rng.standard_normal((args.window, in_channels))
        .astype(np.float32).tolist()
        for _ in range(min(args.requests, 32))  # cycle a small pool
    ]

    stats = _Stats()

    def one(i: int) -> None:
        traceparent = obs_trace.mint_traceparent()
        trace_id = traceparent.split("-")[1]
        launched_at = time.monotonic()
        with stopwatch() as elapsed:
            try:
                status, body = one_request(
                    traces[i % len(traces)], traceparent
                )
            except Exception as e:  # noqa: BLE001
                # The docstring contract: every request error is counted,
                # never aborts the bench. A raise here would abort the
                # closed-loop ex.map — or, worse, vanish inside an
                # open-loop daemon thread so the request is counted
                # neither ok nor error and the SLO gate reads a fake pass.
                status, body = 0, {"error": "client_exception",
                                   "message": repr(e)}
        if status == 200 and tasks:
            # Multi-task acceptance: a 200 that silently dropped a head
            # is an error, not a success — the fan-out contract is that
            # ONE trunk run answers EVERY requested head.
            answered = body.get("tasks") or {}
            if sorted(answered) != sorted(tasks):
                status = 0
                body = {"error": "missing_head",
                        "message": f"answered {sorted(answered)} of "
                                   f"{sorted(tasks)}"}
        latency_ms = elapsed() * 1000.0
        if status == 200:
            version = body.get("model_version")
            try:
                version = int(version) if version is not None else None
            except (TypeError, ValueError):
                version = None
            stats.success(latency_ms, trace_id=trace_id, version=version,
                          launched_at=launched_at)
        else:
            stats.error(status, str(body.get("error", "")),
                        trace_id=trace_id, latency_ms=latency_ms)

    # Rollout convergence watcher: a background poll of the router's
    # /router/replicas that records the moment EVERY replica is ready on
    # --expect-version — the timestamp the staleness gate compares
    # per-request launch times against.
    watch: Optional[_ConvergenceWatch] = None
    if args.expect_version > 0 and args.url:
        watch = _ConvergenceWatch(args.url, args.expect_version)
        watch.start()

    t_start = time.monotonic()
    with stopwatch() as wall:
        if args.arrival_rps > 0:
            _drive_open_loop(one, args.requests, args.arrival_rps,
                             args.concurrency, stats,
                             duration_s=args.duration_s)
        elif args.duration_s > 0:
            _drive_closed_loop_for(one, args.concurrency, args.duration_s)
        else:
            with ThreadPoolExecutor(args.concurrency) as ex:
                # ex.map would abort the whole bench on the first raised
                # error; one() catches per-request instead.
                list(ex.map(one, range(args.requests)))
    wall_s = wall()
    if watch is not None:
        watch.stop()

    batcher_stats: Dict[str, Any] = {}
    fanout_stats: Dict[str, Any] = {}
    if service is not None:
        metrics = service.metrics()
        key = args.model_name
        if args.variant and args.variant != "fp32":
            key = f"{args.model_name}@{args.variant}"
        batcher_stats = metrics["models"][key]
        fanout_stats = metrics.get("fanout", {}).get(args.model_name, {})
        service.shutdown()

    lat = np.asarray(stats.latencies_ms) if stats.latencies_ms else None
    total = stats.ok + stats.errors
    error_rate = stats.errors / total if total else 0.0

    def pct(q: float) -> float:
        return round(float(np.percentile(lat, q)), 3) if lat is not None else -1.0

    if args.url:
        device = "remote"
    else:
        import jax

        device = jax.devices()[0].device_kind

    result = {
        "metric": "serve_predict_latency",
        "model": args.model_name,
        "target": args.url or "in-process",
        "mode": "open-loop" if args.arrival_rps > 0 else "closed-loop",
        "window": args.window,
        # Sustained-load mode offers whatever fits the duration; report
        # what was actually driven, not the unused --requests default.
        "requests": total if args.duration_s > 0 else args.requests,
        "duration_s": args.duration_s,
        "concurrency": args.concurrency,
        "arrival_rps": args.arrival_rps,
        "priority": args.priority or "default",
        "tasks": tasks or [],
        "variant": args.variant or "fp32",
        "max_batch": args.max_batch,
        "max_delay_ms": args.max_delay_ms,
        "p50_ms": pct(50),
        "p90_ms": pct(90),
        "p99_ms": pct(99),
        "mean_ms": round(float(lat.mean()), 3) if lat is not None else -1.0,
        "throughput_rps": round(stats.ok / wall_s, 2) if wall_s else 0.0,
        "ok": stats.ok,
        "errors": stats.errors,
        "error_rate": round(error_rate, 4),
        "by_status": dict(sorted(stats.by_status.items())),
        "by_error_code": dict(sorted(stats.by_code.items())),
        # Served model versions per response — the live-rollout
        # accounting (docs/SERVING.md "Live rollout").
        "by_version": dict(sorted(stats.by_version.items())),
        "device": device,
        # The handles for `python tools/trace_report.py --from-bench`:
        # p99 suspects + every failure, by trace id. Failed exemplars are
        # flagged on the servers and evicted last; slowest-N SUCCESSES
        # are unflagged, so on a bench larger than the servers' trace
        # ring they may already be evicted by the time you pull them.
        "trace_exemplars": stats.exemplars(),
        "measured_at": datetime.now(timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"
        ),
    }
    if args.trace_log:
        with open(args.trace_log, "w") as f:
            for e in stats.successes:
                f.write(json.dumps({**e, "status": 200}) + "\n")
            for e in stats.failed:
                f.write(json.dumps(e) + "\n")
    trace_capacity = int(
        float(os.environ.get("SEIST_TRACE_CAPACITY", "") or 256)
    )
    if args.requests > trace_capacity:
        # Tail retention evicts unflagged (successful) traces first, so
        # the slowest-N exemplars of a big bench likely 404 on
        # GET /traces/<id> unless the serving processes keep more.
        print(
            f"[bench_serve] note: {args.requests} requests > trace ring "
            f"capacity (~{trace_capacity}); slowest-N exemplars may be "
            "evicted on the servers — raise SEIST_TRACE_CAPACITY on the "
            "fleet or set SEIST_TRACE_SLO_MS to flag slow requests for "
            "retention",
            file=sys.stderr, flush=True,
        )
    if batcher_stats:
        result["batch_fill_ratio"] = round(
            batcher_stats["batch_fill_ratio"], 4
        )
        result["forwards"] = batcher_stats["forwards"]
        result["completed"] = batcher_stats["completed"]
    if fanout_stats:
        result["trunk_runs"] = fanout_stats.get("trunk_runs", 0)
        result["head_runs"] = fanout_stats.get("head_runs", {})
        result["trunk_flops_saved"] = fanout_stats.get(
            "trunk_flops_saved", 0.0
        )

    rc = 0
    if args.expect_version > 0:
        # The rollout acceptance gate: the fleet must converge on the
        # expected version during the bench, and once it has, every
        # subsequently-launched response must carry it.
        result["expected_version"] = args.expect_version
        if watch is None:
            result["converged_at_s"] = -1.0
            result["stale_after_convergence"] = -1
            print("[bench_serve] --expect-version needs --url (router)",
                  file=sys.stderr, flush=True)
            rc = 1
        elif watch.converged_at is None:
            result["converged_at_s"] = -1.0
            result["stale_after_convergence"] = -1
            print(
                f"[bench_serve] ROLLOUT GATE FAILED: fleet never "
                f"converged on version {args.expect_version}",
                file=sys.stderr, flush=True,
            )
            rc = 1
        else:
            stale = stats.stale_after(
                watch.converged_at, args.expect_version
            )
            result["converged_at_s"] = round(
                watch.converged_at - t_start, 3
            )
            result["stale_after_convergence"] = stale
            if stale:
                print(
                    f"[bench_serve] ROLLOUT GATE FAILED: {stale} "
                    f"stale-version responses after convergence "
                    f"(by_version={result['by_version']})",
                    file=sys.stderr, flush=True,
                )
                rc = 1
    if tasks:
        missing = stats.by_code.get("missing_head", 0)
        result["fanout_complete"] = missing == 0 and stats.ok > 0
        if not result["fanout_complete"]:
            print(
                f"[bench_serve] FAN-OUT INCOMPLETE: {missing} responses "
                f"missing heads, {stats.ok} complete",
                file=sys.stderr, flush=True,
            )
            rc = 1
    if args.slo_p99_ms > 0:
        violations = []
        if lat is None:
            violations.append("no successful requests")
        elif result["p99_ms"] > args.slo_p99_ms:
            violations.append(
                f"p99 {result['p99_ms']:.1f} ms > SLO {args.slo_p99_ms:.1f} ms"
            )
        if error_rate > args.max_error_rate:
            violations.append(
                f"error_rate {error_rate:.4f} > {args.max_error_rate:.4f}"
            )
        if violations:
            result["slo_violations"] = violations
            print(f"[bench_serve] SLO GATE FAILED: {'; '.join(violations)}",
                  file=sys.stderr, flush=True)
            rc = SLO_EXIT_CODE
        else:
            result["slo_violations"] = []

    line = json.dumps(result)
    print(line)
    if args.output:
        with open(args.output, "w") as f:
            f.write(line + "\n")
    return rc


def _run_stream_bench(args) -> int:
    """``--stream-stations N``: the high-fan-in streaming client. N
    stations each POST /stream packets on their own open-loop cadence
    (launch at t0 + k*cadence regardless of completions — the production
    telemetry model: a seismic network does not slow down because the
    server is busy). ``--concurrency`` workers each OWN stations
    ``w::W``, preserving per-station packet ordering (a station's seq
    numbers must arrive in order; different stations are independent).

    The JSON carries aggregate packet-latency percentiles PLUS
    per-station accounting — percentiles over station mean latencies,
    the worst stations by mean, and per-station failure ledgers
    (by_status, dropped/duplicated/resumed packet counts) — so one hot
    or unlucky station can't hide in (or masquerade as) a fleet-wide
    tail. Connection errors and 5xx are RETRIED with the same seq
    (reconnect-with-resume) instead of abandoning the station: during a
    fleet failover the retry lands on a survivor and the packet counts
    as ``resumed``, so a chaos run's "dropped" number is honest
    client-observed loss, not transport noise. ``--slo-p99-ms`` gates
    the aggregate p99 exactly like the /predict bench."""
    import numpy as np

    n_st = int(args.stream_stations)
    duration = args.duration_s or 10.0
    pkt = args.stream_packet_samples or args.window // 2
    cadence = args.stream_cadence_s or pkt / 50.0
    options: Dict[str, Any] = {"timeout_ms": args.timeout_ms}
    if args.priority:
        options["priority"] = args.priority

    service = None
    if args.url:
        import http.client

        from seist_tpu.serve.router import _http_request

        def send(body: Dict[str, Any]):
            raw = json.dumps(body).encode()
            try:
                status, _, resp = _http_request(
                    args.url, "POST", "/stream", raw,
                    timeout_s=args.timeout_ms / 1000.0 + 5.0,
                )
            except (OSError, http.client.HTTPException) as e:
                return 0, {"error": "unreachable", "message": str(e)}
            try:
                out = json.loads(resp)
            except ValueError:
                out = {}
            return status, out if isinstance(out, dict) else {}

    else:
        from seist_tpu.serve import BatcherConfig, ModelPool, ServeService
        from seist_tpu.serve.protocol import ServeError

        pool = ModelPool(
            [(args.model_name, args.checkpoint)], window=args.window,
            seed=args.seed,
        )
        service = ServeService(
            pool,
            BatcherConfig(
                max_batch=args.max_batch,
                max_delay_ms=args.max_delay_ms,
                max_queue=args.max_queue,
            ),
            stream_config={"max_stations": max(4096, 2 * n_st)},
        )
        options.update(ppk_threshold=0.05, spk_threshold=0.05)

        def send(body: Dict[str, Any]):
            try:
                return 200, service.stream(body)
            except ServeError as e:
                return e.status, e.payload()

    rng = np.random.default_rng(args.seed)
    # A small shared packet pool: per-station payload identity doesn't
    # matter for latency, and N_stations x duration packets would not
    # fit memory at thousand-station scale.
    packets = [
        rng.standard_normal((pkt, args.in_channels))
        .astype(np.float32).tolist()
        for _ in range(16)
    ]
    # Station grid over ~2 deg so coordinates are plausible and the
    # association path runs (alerts on synthetic noise are fine — the
    # bench measures the pipeline, not seismology).
    side = max(1, int(np.ceil(np.sqrt(n_st))))
    stations = [
        {"id": f"BN{i:05d}", "network": "BN",
         "lat": round(34.0 + 2.0 * (i // side) / side, 4),
         "lon": round(-118.0 + 2.0 * (i % side) / side, 4)}
        for i in range(n_st)
    ]

    lock = threading.Lock()
    agg = {"ok": 0, "errors": 0, "windows": 0, "picks": 0, "alerts": 0,
           "dropped_windows": 0, "by_status": {},
           "dropped_packets": 0, "duplicate_packets": 0,
           "resumed_packets": 0}
    latencies: List[float] = []
    per_station: Dict[str, List[float]] = {s["id"]: [] for s in stations}
    #: per-station failure ledger: the chaos lane's client-side truth.
    st_acc: Dict[str, Dict[str, Any]] = {
        s["id"]: {"by_status": {}, "dropped": 0, "duplicates": 0,
                  "resumed": 0}
        for s in stations
    }
    #: reconnect-with-resume budget per packet: transport errors and
    #: 5xx re-send the SAME seq (idempotent server-side — a replayed
    #: packet the first send actually reached dedups as a duplicate).
    max_retries = 3
    n_workers = max(1, min(args.concurrency, n_st))
    t0 = time.monotonic()
    deadline = t0 + duration

    def worker(w: int) -> None:
        # Whole body under try: (threadlint thread-target-raises).
        try:
            mine = stations[w::n_workers]
            seqs = {s["id"]: 0 for s in mine}
            rounds = 0
            while True:
                for st in mine:
                    seqs[st["id"]] += 1
                    body = {
                        "station": st,
                        "data": packets[
                            (rounds + hash(st["id"])) % len(packets)
                        ],
                        "seq": seqs[st["id"]],
                        "options": options,
                    }
                    if args.model_name:
                        body["model"] = args.model_name
                    attempts = 0
                    while True:
                        t_send = time.monotonic()
                        status, resp = send(body)
                        lat_ms = (time.monotonic() - t_send) * 1000.0
                        acc = st_acc[st["id"]]
                        with lock:
                            agg["by_status"][status] = (
                                agg["by_status"].get(status, 0) + 1
                            )
                            acc["by_status"][status] = (
                                acc["by_status"].get(status, 0) + 1
                            )
                            if status == 200:
                                agg["ok"] += 1
                                latencies.append(lat_ms)
                                per_station[st["id"]].append(lat_ms)
                                agg["windows"] += resp.get("windows", 0)
                                agg["picks"] += (
                                    len(resp.get("ppk", []))
                                    + len(resp.get("spk", []))
                                    + len(resp.get("det", []))
                                )
                                agg["alerts"] += len(
                                    resp.get("alerts", [])
                                )
                                agg["dropped_windows"] = max(
                                    agg["dropped_windows"],
                                    resp.get("dropped_windows", 0),
                                )
                                if resp.get("duplicate"):
                                    acc["duplicates"] += 1
                                    agg["duplicate_packets"] += 1
                                if attempts:
                                    acc["resumed"] += 1
                                    agg["resumed_packets"] += 1
                                break
                            retryable = (
                                status == 0 or status >= 500
                            ) and attempts < max_retries                                 and time.monotonic() < deadline
                            if not retryable:
                                agg["errors"] += 1
                                acc["dropped"] += 1
                                agg["dropped_packets"] += 1
                                break
                        # Reconnect-with-resume: same seq, brief
                        # backoff — a failover needs a beat for the
                        # router to re-home the station.
                        attempts += 1
                        time.sleep(0.2 * attempts)
                rounds += 1
                # Open loop: the next round launches on the cadence
                # clock, not after completions.
                target = t0 + rounds * cadence
                now = time.monotonic()
                if now >= deadline:
                    return
                if target > now:
                    time.sleep(min(target, deadline) - now)
        except BaseException as e:  # noqa: BLE001
            print(f"[bench_serve] stream worker {w} died: {e!r}",
                  file=sys.stderr, flush=True)

    threads = [
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(n_workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.monotonic() - t0

    stream_stats: Dict[str, Any] = {}
    if service is not None:
        stream_stats = service.metrics()["stream"].get(args.model_name, {})
        service.shutdown()

    lat = np.asarray(latencies) if latencies else None

    def pct(a, q):
        return round(float(np.percentile(a, q)), 3) if a is not None and len(a) else -1.0

    means = {
        sid: float(np.mean(v)) for sid, v in per_station.items() if v
    }
    mean_arr = np.asarray(list(means.values())) if means else None
    worst = sorted(means.items(), key=lambda kv: -kv[1])[:5]
    total = agg["ok"] + agg["errors"]
    result = {
        "metric": "serve_stream_latency",
        "model": args.model_name,
        "target": args.url or "in-process",
        "mode": "stream-open-loop",
        "stations": n_st,
        "concurrency": n_workers,
        "cadence_s": round(cadence, 4),
        "packet_samples": pkt,
        "duration_s": round(wall_s, 3),
        "packets": total,
        "ok": agg["ok"],
        "errors": agg["errors"],
        "error_rate": round(agg["errors"] / total, 4) if total else 0.0,
        "by_status": dict(sorted(agg["by_status"].items())),
        "windows": agg["windows"],
        "picks": agg["picks"],
        "alerts": agg["alerts"],
        "p50_ms": pct(lat, 50),
        "p90_ms": pct(lat, 90),
        "p99_ms": pct(lat, 99),
        "mean_ms": round(float(lat.mean()), 3) if lat is not None else -1.0,
        "packets_per_s": round(agg["ok"] / wall_s, 2) if wall_s else 0.0,
        # Per-station accounting: a single hot station must be visible.
        "station_mean_ms": {
            "p50": pct(mean_arr, 50),
            "p99": pct(mean_arr, 99),
            "max": round(float(mean_arr.max()), 3) if mean_arr is not None else -1.0,
        },
        "worst_stations": [
            {"id": sid, "mean_ms": round(m, 3)} for sid, m in worst
        ],
        "stations_reporting": len(means),
        "dropped_packets": agg["dropped_packets"],
        "duplicate_packets": agg["duplicate_packets"],
        "resumed_packets": agg["resumed_packets"],
        # Only stations that saw trouble (capped): a thousand clean
        # ledgers would drown the artifact.
        "station_failures": {
            sid: acc
            for sid, acc in sorted(
                st_acc.items(),
                key=lambda kv: -(kv[1]["dropped"] + kv[1]["resumed"]),
            )[:20]
            if acc["dropped"] or acc["resumed"] or acc["duplicates"]
            or set(acc["by_status"]) - {200}
        },
        "stream_stats": stream_stats,
        "measured_at": datetime.now(timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"
        ),
    }
    rc = 0
    if args.slo_p99_ms > 0:
        violations = []
        if lat is None:
            violations.append("no successful packets")
        elif result["p99_ms"] > args.slo_p99_ms:
            violations.append(
                f"p99 {result['p99_ms']:.1f} ms > SLO "
                f"{args.slo_p99_ms:.1f} ms"
            )
        if result["error_rate"] > args.max_error_rate:
            violations.append(
                f"error_rate {result['error_rate']:.4f} > "
                f"{args.max_error_rate:.4f}"
            )
        result["slo_violations"] = violations
        if violations:
            print(
                f"[bench_serve] SLO GATE FAILED: {'; '.join(violations)}",
                file=sys.stderr, flush=True,
            )
            rc = SLO_EXIT_CODE
    line = json.dumps(result)
    print(line)
    if args.output:
        with open(args.output, "w") as f:
            f.write(line + "\n")
    return rc


def _drive_closed_loop_for(one, concurrency: int, duration_s: float) -> None:
    """Sustained closed-loop: ``concurrency`` workers each fire the next
    request as soon as the previous answers, until the deadline — the
    fixed-duration client a rolling restart is benched under (total
    request count is whatever the service sustained)."""
    deadline = time.monotonic() + duration_s
    counter = iter(range(1 << 62))
    counter_lock = threading.Lock()

    def worker() -> None:
        # one() accounts every exception itself; the loop shape is the
        # only logic here (threadlint thread-target-raises).
        try:
            while time.monotonic() < deadline:
                with counter_lock:
                    i = next(counter)
                one(i)
        except BaseException as e:  # noqa: BLE001
            print(f"[bench_serve] closed-loop worker died: {e!r}",
                  file=sys.stderr, flush=True)

    threads = [
        threading.Thread(target=worker, daemon=True)
        for _ in range(max(1, concurrency))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def _drive_open_loop(
    one, n_requests: int, arrival_rps: float, max_inflight: int,
    stats: "_Stats", duration_s: float = 0.0,
) -> None:
    """Launch request i at t0 + i/rps on a worker thread, independent of
    completions (the open-loop arrival model). The thread pool is capped
    at ``4 * max_inflight`` — the 4x headroom lets a backlog build so an
    overloaded SERVICE gets to exercise its shedding tiers instead of the
    client silently throttling arrivals. Past that cap, further arrivals
    are dropped ON THE CLIENT and counted as status 0 ``client_overrun``
    errors — an open-loop bench that quietly stopped offering load would
    otherwise report a fake SLO pass.

    ``duration_s > 0`` switches from a fixed request count to sustained
    load: arrivals keep coming on the same clock until the deadline."""
    interval = 1.0 / arrival_rps
    cap = max(1, max_inflight) * 4
    sem = threading.Semaphore(cap)
    n_over = 0
    threads: List[threading.Thread] = []
    t0 = time.monotonic()
    if duration_s > 0:
        deadline = t0 + duration_s

        def arrivals():
            i = 0
            while time.monotonic() < deadline:
                yield i
                i += 1

        schedule = arrivals()
    else:
        schedule = iter(range(n_requests))
    for i in schedule:
        target = t0 + i * interval
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        if not sem.acquire(blocking=False):
            n_over += 1
            stats.error(0, "client_overrun")
            continue

        def run(idx: int) -> None:
            try:
                one(idx)
            finally:
                sem.release()

        # threadlint: disable=thread-target-raises -- one() accounts every
        # exception as a status-0 client_exception itself; the try/finally
        # only guarantees the in-flight semaphore is returned.
        t = threading.Thread(target=run, args=(i,), daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    if n_over:
        print(f"[bench_serve] WARNING: {n_over} arrivals dropped client-side "
              f"(in-flight cap {cap}); offered load was lower than requested",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    sys.exit(main())
