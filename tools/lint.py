"""Combined static-analysis gate: jaxlint + threadlint + detlint +
irlint in ONE interpreter invocation (``make lint``).

The four analyzers share the engine frontend (tools/jaxlint/__main__.py
``run``); this runner additionally shares the FILE WALK — every source
file under the AST analyzers' paths is read exactly once into a source
cache all three AST passes consume — and combines the exit codes (worst
wins, usage errors beat findings). irlint's manifest walk happens once
as well; its extra flags keep their defaults here (use ``python -m
tools.irlint`` to vary them).

    python -m tools.lint              # the full gate
    python -m tools.lint --skip-ir    # no program lowering (fast loop)
    python -m tools.lint --skip-det   # skip the determinism catalog

Exit codes: 0 all clean, 1 new findings in any analyzer, 2 usage/parse/
lowering error in any analyzer.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List, Optional, Sequence

# irlint lowers real programs: the backend must be pinned BEFORE the
# first jax import (a lint gate must never touch the TPU tunnel).
from tools.irlint.manifest import ensure_cpu_backend

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: (analyzer, lint paths) — the same path sets the standalone gates use.
AST_ANALYZERS = (
    ("jaxlint", ("seist_tpu",)),
    ("threadlint", ("seist_tpu", "tools")),
    ("detlint", ("seist_tpu", "tools")),
)


def _prewalk(paths: Sequence[str]) -> Dict[str, str]:
    """ONE os.walk + read over the union of all analyzers' paths."""
    from tools.jaxlint.engine import iter_python_files

    cache: Dict[str, str] = {}
    for p in iter_python_files(sorted(set(paths)), _REPO_ROOT):
        ap = os.path.abspath(p)
        with open(ap, encoding="utf-8") as f:
            cache[ap] = f.read()
    return cache


def main(argv: Optional[Sequence[str]] = None) -> int:
    ensure_cpu_backend()
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument(
        "--skip-ir",
        action="store_true",
        help="run only the AST analyzers (no program lowering)",
    )
    ap.add_argument(
        "--skip-det",
        action="store_true",
        help="skip the determinism catalog (detlint)",
    )
    args = ap.parse_args(argv)

    from tools.jaxlint.__main__ import run
    from tools.jaxlint.rules import RULES as JAX_RULES
    from tools.jaxlint.rules import RULES_BY_NAME as JAX_BY_NAME
    from tools.threadlint.rules import RULES as THREAD_RULES
    from tools.threadlint.rules import RULES_BY_NAME as THREAD_BY_NAME
    from tools.detlint.rules import RULES as DET_RULES
    from tools.detlint.rules import RULES_BY_NAME as DET_BY_NAME

    ast_analyzers = tuple(
        (tag, paths)
        for tag, paths in AST_ANALYZERS
        if not (tag == "detlint" and args.skip_det)
    )
    all_paths: List[str] = []
    for _tag, paths in ast_analyzers:
        all_paths.extend(paths)
    cache = _prewalk(all_paths)

    rcs: Dict[str, int] = {}
    print("== jaxlint ==")
    rcs["jaxlint"] = run(
        list(AST_ANALYZERS[0][1]),
        tag="jaxlint",
        catalog=JAX_RULES,
        rules_by_name=JAX_BY_NAME,
        default_baseline=os.path.join(
            _REPO_ROOT, "tools", "jaxlint_baseline.json"
        ),
        docs="docs/STATIC_ANALYSIS.md",
        source_cache=cache,
    )
    print("== threadlint ==")
    rcs["threadlint"] = run(
        list(AST_ANALYZERS[1][1]),
        tag="threadlint",
        catalog=THREAD_RULES,
        rules_by_name=THREAD_BY_NAME,
        default_baseline=os.path.join(
            _REPO_ROOT, "tools", "threadlint_baseline.json"
        ),
        docs="docs/STATIC_ANALYSIS.md",
        source_cache=cache,
    )
    if not args.skip_det:
        print("== detlint ==")
        rcs["detlint"] = run(
            list(AST_ANALYZERS[2][1]),
            tag="detlint",
            catalog=DET_RULES,
            rules_by_name=DET_BY_NAME,
            default_baseline=os.path.join(
                _REPO_ROOT, "tools", "detlint_baseline.json"
            ),
            docs="docs/STATIC_ANALYSIS.md",
            refuse_empty_baseline_update=True,
            source_cache=cache,
        )
    if not args.skip_ir:
        print("== irlint ==")
        from tools.irlint.__main__ import main as irlint_main

        rcs["irlint"] = irlint_main([])

    # Usage/lowering errors (2) dominate findings (1) dominate clean (0).
    worst = max(rcs.values())
    summary = ", ".join(f"{tag}={rc}" for tag, rc in rcs.items())
    print(f"lint: {summary} -> exit {worst}")
    return worst


if __name__ == "__main__":
    sys.exit(main())
