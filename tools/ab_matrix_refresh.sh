#!/bin/bash
# Watcher 5: after tools/ab_impls2.sh (IMPL AB2 DONE), refresh the full
# bf16 per-config matrix at the new default lowerings and capture an
# eval-mode matrix, into separate files so the old tables remain as the
# round-2 historical record.
LOG=/root/repo/tools/ab_phase_split.log
until grep -q "IMPL AB2 DONE" "$LOG" 2>/dev/null; do sleep 120; done

cd /root/repo
echo "=== bf16 matrix refresh $(date)" >> "$LOG"
if BENCH_DTYPE=bf16 timeout 10800 python tools/bench_matrix.py --steps 15 \
    --out tools/bench_matrix_bf16_r2b.json >> "$LOG" 2>/dev/null; then
  train_rc=ok
else
  train_rc="FAILED rc=$?"
fi
echo "=== eval matrix $(date)" >> "$LOG"
if BENCH_DTYPE=bf16 timeout 7200 python tools/bench_matrix.py --steps 15 \
    --mode eval --out tools/bench_matrix_eval.json >> "$LOG" 2>/dev/null; then
  eval_rc=ok
else
  eval_rc="FAILED rc=$?"
fi
echo "MATRIX REFRESH DONE (train: $train_rc, eval: $eval_rc) $(date)" >> "$LOG"
