"""cProfile the input pipeline to find where the per-sample time goes.

    python tools/profile_loader.py [n_batches] [batch_size]

Prints the top cumulative-time functions for a full-augmentation
synthetic-dataset run (same path as tools/bench_loader.py measures).
"""

from __future__ import annotations

import cProfile
import os
import pstats
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> None:
    n_batches = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 128

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.data import pipeline

    seist_tpu.load_all()
    spec = taskspec.get_task_spec("seist_l_dpk")
    dataset = pipeline.from_task_spec(
        spec,
        "synthetic",
        "train",
        seed=0,
        in_samples=8192,
        augmentation=True,
        dataset_kwargs={"num_events": batch * 4},
    )
    # workers=1 so the profile sees the work inline, not in pool threads.
    loader = pipeline.Loader(
        dataset, batch, shuffle=True, drop_last=True, num_workers=1, seed=0
    )
    it = iter(loader)
    next(it)  # warm

    prof = cProfile.Profile()
    prof.enable()
    for _ in range(n_batches):
        try:
            next(it)
        except StopIteration:
            loader.set_epoch(loader.epoch + 1)
            it = iter(loader)
            next(it)
    prof.disable()
    stats = pstats.Stats(prof)
    stats.sort_stats("cumulative").print_stats(25)


if __name__ == "__main__":
    main()
