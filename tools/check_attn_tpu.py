"""On-device check of the fused pooled-KV attention kernel (head-folded).

Compiles the Pallas kernel on the real TPU at the SeisT stage shapes and
compares forward + gradients against the einsum reference (same math, same
counter-based dropout PRNG). Run on a live chip:

    python tools/check_attn_tpu.py

Prints one OK/FAIL line per case; exit code 0 iff all pass.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from seist_tpu.ops.pallas_attention import (
        _einsum_attention,
        fused_pooled_attention,
    )

    assert jax.default_backend() == "tpu", jax.default_backend()
    rng = np.random.default_rng(0)
    failures = 0
    # (n, l, m, h, e): SeisT stage shapes (stage0 L=1024 r=8 H=3 E=8 at
    # seist_l) plus an H=1 degenerate and a non-multiple-of-8 E.
    cases = [
        (8, 1024, 128, 3, 8),
        (8, 512, 128, 1, 8),
        (8, 256, 128, 2, 16),
        (4, 128, 128, 2, 32),
        (4, 64, 16, 3, 24),
    ]
    for n, l, m, h, e in cases:
        q = jnp.asarray(rng.normal(size=(n, l, h, e)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(n, m, h, e)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(n, m, h, e)), jnp.float32)
        scale = 1.0 / np.sqrt(e)
        seed = jnp.asarray([1234], jnp.int32)

        def loss_fused(q, k, v):
            o = fused_pooled_attention(
                q, k, v, scale, dropout_rate=0.2, dropout_seed=seed
            )
            return (o**2).sum()

        def loss_einsum(q, k, v):
            o = _einsum_attention(
                q, k, v, scale, dropout_rate=0.2, dropout_seed=seed
            )
            return (o**2).sum()

        try:
            fwd_k = jax.jit(
                lambda q, k, v: fused_pooled_attention(q, k, v, scale)
            )(q, k, v)
            fwd_e = jax.jit(
                lambda q, k, v: _einsum_attention(q, k, v, scale)
            )(q, k, v)
            # Tolerances are sized for TPU fp32-via-MXU numerics (measured
            # 2026-08-02): both the kernel's dots and XLA's default-precision
            # einsum multiply bf16-rounded inputs with f32 accumulation, so
            # they track each other to ~3e-4 fwd / ~2e-2 on the
            # cancellation-heavy dk — while a logic bug (e.g. a dropout-mask
            # divergence) shifts elements by O(1). Bit-level parity of the
            # mask math is asserted by the CPU interpret-mode unit tests.
            np.testing.assert_allclose(
                np.asarray(fwd_k), np.asarray(fwd_e), rtol=1e-3, atol=1e-3
            )
            gk_f = jax.jit(jax.grad(loss_fused, argnums=(0, 1, 2)))
            ge_f = jax.jit(jax.grad(loss_einsum, argnums=(0, 1, 2)))
            gk = gk_f(q, k, v)
            ge = ge_f(q, k, v)
            # dq/dv track within ~1e-3 (measured 2026-08-02); only dk is
            # cancellation-heavy (softmax-vjp ds.T @ q summed over L) and
            # needs the wide band. Keep detection power where numerics allow.
            grad_tol = {"q": 5e-3, "k": 5e-2, "v": 5e-3}
            for a, b, nm in zip(gk, ge, "qkv"):
                np.testing.assert_allclose(
                    np.asarray(a),
                    np.asarray(b),
                    rtol=grad_tol[nm],
                    atol=grad_tol[nm],
                    err_msg=f"d{nm}",
                )

            # Microbench: fused vs einsum fwd+bwd (20 reps after warmup),
            # reusing the already-compiled grad wrappers above.
            import time

            def t(fn):
                fn(q, k, v)[0].block_until_ready()
                t0 = time.perf_counter()
                for _ in range(20):
                    out = fn(q, k, v)
                out[0].block_until_ready()
                return (time.perf_counter() - t0) / 20 * 1e6

            us_k, us_e = t(gk_f), t(ge_f)
            print(
                f"OK   n={n} l={l} m={m} h={h} e={e}  "
                f"fwd+bwd fused {us_k:.0f}us vs einsum {us_e:.0f}us "
                f"({us_e / us_k:.2f}x)"
            )
        except Exception as exc:  # noqa: BLE001 - report and continue
            failures += 1
            msg = str(exc).splitlines()[0][:160] if str(exc) else repr(exc)
            print(f"FAIL n={n} l={l} m={m} h={h} e={e}: {msg}")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
