"""Stream smoke (`make stream-smoke`): a REAL phasenet serve replica is
driven over HTTP by a 50-station streaming network for 30 s of waveform
per station, then audited on the two invariants the streaming plane
sells (docs/SERVING.md "Streaming inference"):

* **zero dropped alert-tier windows** — every due window of every
  session rode the alert tier through the batcher; no 429/503, no
  coverage holes, no degraded sessions;
* **streaming<->offline parity on sampled stations** — 3 stations'
  full records are re-picked through ``POST /annotate`` with the same
  options and the pick sets must agree. The gate is tolerance-based,
  not exact: the offline path batches windows into the largest warm
  bucket while the mux submits singles, and XLA fuses the two batch
  shapes differently, so a pick whose peak probability sits within
  float-rounding of the threshold can legitimately appear on one side
  only (the EXACT serve-plane pin lives in tests/test_serve_stream.py
  against a batch-invariant model). Each side may strand at most 10%
  of the union, and matched picks must land within +-2 samples.

Prints one JSON verdict line; exit 0/1.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

WINDOW = 256
STATIONS = 50
RECORD_S = 30.0
FS = 50
PACKET = WINDOW // 2
WORKERS = 8
SAMPLED = 3  # stations re-picked offline for the parity gate
OPTS = {"ppk_threshold": 0.3, "spk_threshold": 0.3, "det_threshold": 0.3,
        "record_max_events": 700}


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _drain(pipe, buf):
    try:
        for line in pipe:
            buf.append(line)
    except Exception as e:  # noqa: BLE001
        buf.append(f"[stream_smoke] pipe drain died: {e!r}\n")


def _post(url, path, body, timeout_s=60.0):
    from seist_tpu.serve.router import _http_request

    status, _, resp = _http_request(
        url, "POST", path, json.dumps(body).encode(), timeout_s=timeout_s
    )
    return status, json.loads(resp)


def _match(a, b, tol=2):
    """Greedy one-to-one matching of two ascending pick lists within
    ``tol`` samples; returns the number matched."""
    n, i, j = 0, 0, 0
    a, b = sorted(a), sorted(b)
    while i < len(a) and j < len(b):
        if abs(a[i] - b[j]) <= tol:
            n += 1
            i += 1
            j += 1
        elif a[i] < b[j]:
            i += 1
        else:
            j += 1
    return n


def _parity(stream_picks, offline, verdict_rows, sid):
    """Tolerance gate for one station (module docstring)."""
    ok = True
    for phase in ("ppk", "spk"):
        s = stream_picks[phase]
        o = [p["sample"] for p in offline[phase]]
        matched = _match(s, o)
        union = len(s) + len(o) - matched
        stranded = union - matched
        row_ok = union == 0 or stranded <= max(1, int(0.1 * union))
        verdict_rows.append({
            "station": sid, "phase": phase, "stream": len(s),
            "offline": len(o), "matched": matched, "ok": row_ok,
        })
        ok = ok and row_ok
    return ok


def main() -> int:
    import shutil
    import tempfile

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    port = _free_port()
    # Journal plane rides the smoke too: every feed journals
    # (--stream-journal-every-s 0) so the durability path — snapshot,
    # atomic write, clean-close removal — is exercised at full cadence
    # under a real model, and the verdict gates journal_writes > 0.
    journal_dir = tempfile.mkdtemp(prefix="stream_smoke_journal_")
    proc = subprocess.Popen(
        [
            sys.executable, os.path.join(REPO, "main.py"), "serve",
            "--model", "phasenet=",
            "--window", str(WINDOW),
            "--port", str(port),
            "--max-batch", "8",
            "--max-delay-ms", "5",
            "--max-queue", "512",
            "--stream-journal-dir", journal_dir,
            "--stream-journal-every-s", "0",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True,
    )
    out_buf, err_buf = [], []
    threading.Thread(target=_drain, args=(proc.stdout, out_buf),
                     daemon=True).start()
    threading.Thread(target=_drain, args=(proc.stderr, err_buf),
                     daemon=True).start()
    url = f"http://127.0.0.1:{port}"
    verdict = {"metric": "stream_smoke", "ok": False}
    try:
        from seist_tpu.serve.router import _http_request

        deadline = time.monotonic() + 300.0
        while time.monotonic() < deadline:
            try:
                status, _, _ = _http_request(
                    url, "GET", "/healthz/ready", timeout_s=3.0
                )
                if status == 200:
                    break
            except OSError:
                pass
            time.sleep(0.3)
        else:
            verdict["error"] = "replica never became ready"
            return _finish(proc, err_buf, verdict)

        L = int(RECORD_S * FS)
        rng = np.random.default_rng(0)
        waves = {
            f"SM{i:03d}": rng.standard_normal((L, 3)).astype(np.float32)
            for i in range(STATIONS)
        }
        sids = list(waves)
        lock = threading.Lock()
        tally = {"packets": 0, "rejects": 0, "dropped": 0, "degraded": 0}
        stream_picks = {
            sid: {"ppk": [], "spk": []} for sid in sids[:SAMPLED]
        }

        def worker(w):
            # Whole body under try: (threadlint thread-target-raises).
            try:
                mine = sids[w::WORKERS]
                n_rounds = (L + PACKET - 1) // PACKET
                for r in range(n_rounds + 1):
                    for sid in mine:
                        body = {
                            "model": "phasenet",
                            "station": {"id": sid, "network": "SM"},
                            "seq": r + 1,
                            "options": OPTS,
                        }
                        if r < n_rounds:
                            body["data"] = (
                                waves[sid][r * PACKET : (r + 1) * PACKET].tolist()
                            )
                        else:
                            body["end"] = True
                        try:
                            status, resp = _post(url, "/stream", body)
                        except Exception as e:  # noqa: BLE001
                            with lock:
                                tally["rejects"] += 1
                            sys.stderr.write(f"[stream_smoke] {sid}: {e!r}\n")
                            continue
                        with lock:
                            tally["packets"] += 1
                            if status != 200:
                                tally["rejects"] += 1
                                continue
                            tally["dropped"] = max(
                                tally["dropped"], resp["dropped_windows"]
                            )
                            tally["degraded"] += bool(resp["degraded"])
                            if sid in stream_picks:
                                for ph in ("ppk", "spk"):
                                    stream_picks[sid][ph] += [
                                        p["sample"] for p in resp[ph]
                                    ]
            except BaseException as e:  # noqa: BLE001
                with lock:
                    tally["rejects"] += 1
                sys.stderr.write(f"[stream_smoke] worker {w} died: {e!r}\n")

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(WORKERS)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        verdict["stream_wall_s"] = round(time.monotonic() - t0, 3)
        verdict.update(tally)

        status, _, body = _http_request(url, "GET", "/metrics", timeout_s=10.0)
        stream_stats = json.loads(body).get("stream", {}).get("phasenet", {})
        verdict["stream_stats"] = stream_stats

        rows = []
        parity_ok = True
        for sid in sids[:SAMPLED]:
            status, offline = _post(url, "/annotate", {
                "model": "phasenet",
                "data": waves[sid].tolist(),
                "options": OPTS,
            }, timeout_s=120.0)
            if status != 200:
                rows.append({"station": sid, "error": offline})
                parity_ok = False
                continue
            parity_ok = _parity(
                stream_picks[sid], offline, rows, sid
            ) and parity_ok
        verdict["parity"] = rows

        # Cleanly-closed sessions remove their journals (no failover
        # handoff needed) — writes happened, files are gone.
        leftover = []
        for root, _dirs, files in os.walk(journal_dir):
            leftover += [f for f in files if f.endswith(".npz")]
        verdict["journal_leftover_files"] = len(leftover)

        verdict["ok"] = bool(
            tally["rejects"] == 0
            and tally["dropped"] == 0
            and tally["degraded"] == 0
            and stream_stats.get("windows_dropped") == 0.0
            and stream_stats.get("sessions_closed") == float(STATIONS)
            and stream_stats.get("journal_writes", 0.0) > 0.0
            and stream_stats.get("restores_failed", 0.0) == 0.0
            and not leftover
            and parity_ok
        )
        return _finish(proc, err_buf, verdict)
    except BaseException:
        _finish(proc, err_buf, verdict)
        raise
    finally:
        shutil.rmtree(journal_dir, ignore_errors=True)


def _finish(proc, err_buf, verdict) -> int:
    proc.send_signal(signal.SIGINT)
    try:
        proc.wait(timeout=60)
    except subprocess.TimeoutExpired:
        proc.kill()
    print(json.dumps(verdict), flush=True)
    if not verdict["ok"]:
        sys.stderr.write("".join(err_buf)[-4000:])
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
