"""End-to-end metric parity: torch reference vs this framework, same data.

The accuracy half of the north-star ("P/S-pick F1 parity with the reference",
BASELINE.md) cannot be run on real PNW/DiTing archives in this sandbox (no
datasets on disk, zero egress) — so this harness constructs the strongest
available evidence: BOTH frameworks evaluate the SAME published reference
weights on the SAME on-disk DiTing-light-format fixture through their FULL
test pipelines (reader -> split -> preprocess -> forward -> postprocess ->
metrics), and the per-task metrics are compared.

Exactness levers:
* fixture traces are exactly ``--in-samples`` long, making the reference's
  randomized eval window cut a no-op (ref preprocess.py:207-219) — model
  inputs are bit-identical;
* both sides read the identical CSV+HDF5 bytes and use the same pandas
  ``sample(frac=1, random_state=seed)`` shuffle + contiguous split (ref
  diting.py:281-299); the harness asserts the test-split ev_id lists match
  before comparing metrics;
* the reference's missing deps are stubbed read-only in the driver
  (tools/_ref_eval_driver.py) — /root/reference is never modified.

Usage:
    python tools/parity_eval.py [--model-name seist_s_dpk] [--n-events 240]

Writes <workdir>/parity_eval_result.json and prints a comparison table.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_TOOLS)
sys.path.insert(0, _TOOLS)
sys.path.insert(0, _REPO)

from fixtures import write_diting_light_fixture  # noqa: E402


def _run(cmd, env=None, timeout=3600) -> str:
    print("+", " ".join(cmd), file=sys.stderr, flush=True)
    r = subprocess.run(
        cmd, capture_output=True, text=True, env=env, timeout=timeout
    )
    if r.returncode != 0:
        sys.stderr.write(r.stdout[-2000:] + "\n" + r.stderr[-4000:] + "\n")
        raise RuntimeError(f"{cmd[1]} failed rc={r.returncode}")
    return r.stdout


def _make_random_init_pth(
    model_name: str, in_samples: int, seed: int, out_path: str
) -> None:
    """Seeded random-init torch state-dict from the READ-ONLY reference
    registry (shared timm stub from tools/bench_reference.py)."""
    import torch

    from bench_reference import _install_timm_stub

    _install_timm_stub()
    if "/root/reference" not in sys.path:
        sys.path.insert(0, "/root/reference")
    from models import create_model as torch_create  # reference registry

    from seist_tpu import taskspec

    torch.manual_seed(seed)
    tm = torch_create(
        model_name,
        in_channels=taskspec.get_num_inchannels(model_name),
        in_samples=in_samples,
    )
    torch.save(tm.state_dict(), out_path)
    print(f"random-init state dict -> {out_path}", file=sys.stderr)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-name", default="seist_s_dpk")
    ap.add_argument("--n-events", type=int, default=240)
    ap.add_argument("--in-samples", type=int, default=8192)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--seed", type=int, default=3)
    # 0.05/0.05 split -> 90% of events land in the test split (the only
    # split this harness evaluates).
    ap.add_argument("--train-size", type=float, default=0.05)
    ap.add_argument("--val-size", type=float, default=0.05)
    ap.add_argument(
        "--workdir", default=os.path.join(_REPO, "logs", "parity_eval")
    )
    ap.add_argument("--keep-workdir", action="store_true")
    ap.add_argument(
        "--random-init-seed",
        type=int,
        default=None,
        help="for models WITHOUT a published reference checkpoint (e.g. "
        "eqtransformer — the 18 shipped .pth are all seist variants): "
        "generate a seeded random-init torch state-dict and run both "
        "pipelines with it. The metrics are then meaningless as accuracy "
        "but must still MATCH — this compares the pipelines, not the "
        "model quality.",
    )
    args = ap.parse_args()

    os.makedirs(args.workdir, exist_ok=True)
    pth = os.path.join(
        "/root/reference/pretrained", f"{args.model_name}_diting.pth"
    )
    if not os.path.exists(pth):
        if args.random_init_seed is None:
            raise FileNotFoundError(
                f"{pth} (pass --random-init-seed N to compare pipelines "
                "with generated weights)"
            )
        # Cache key carries seed AND in_samples: a bare model-name key
        # would silently reuse stale weights when either changes (and the
        # imported-orbax cache below must track the same identity or the
        # two sides could load different weights).
        tag = f"{args.model_name}_s{args.random_init_seed}_l{args.in_samples}"
        pth = os.path.join(args.workdir, f"random_{tag}.pth")
        if not os.path.exists(pth):
            _make_random_init_pth(
                args.model_name, args.in_samples, args.random_init_seed, pth
            )

    fixture = os.path.join(args.workdir, "diting_fixture")
    if not os.path.exists(os.path.join(fixture, "DiTing330km_light.csv")):
        print("writing fixture ...", file=sys.stderr, flush=True)
        write_diting_light_fixture(
            fixture,
            n_events=args.n_events,
            trace_samples=args.in_samples,
        )

    common = [
        "--mode", "test",
        "--model-name", args.model_name,
        "--dataset-name", "diting_light",
        "--data", fixture,
        "--seed", str(args.seed),
        "--batch-size", str(args.batch_size),
        "--workers", "0",  # inline loading on this 1-core host (ours clamps to 1 thread)
        "--in-samples", str(args.in_samples),
        "--train-size", str(args.train_size),
        "--val-size", str(args.val_size),
        "--save-test-results", "false",
        "--use-tensorboard", "false",
    ]

    # --- reference side (torch, CPU) ---
    ref_log = os.path.join(args.workdir, "ref_logs")
    out = _run(
        [
            sys.executable, os.path.join(_TOOLS, "_ref_eval_driver.py"),
            *common,
            "--device", "cpu",
            "--use-torch-compile", "false",
            "--checkpoint", pth,
            "--log-base", ref_log,
        ]
    )
    ref = json.loads(
        [ln for ln in out.splitlines() if ln.startswith("PARITY_JSON ")][-1][
            len("PARITY_JSON "):
        ]
    )

    # --- our side: import weights, then the production test CLI ---
    # Key the imported-orbax cache by the SOURCE .pth filename so the
    # random-init tag (seed/in_samples) flows through.
    ckpt = os.path.join(
        args.workdir, "imported", os.path.splitext(os.path.basename(pth))[0]
    )
    if not os.path.exists(ckpt):
        _run(
            [
                sys.executable, os.path.join(_TOOLS, "import_pretrained.py"),
                "--pth", pth,
                "--model-name", args.model_name,
                "--in-samples", str(args.in_samples),
                "--out", ckpt,
            ]
        )
    ours_log = os.path.join(args.workdir, "ours_logs", "run")
    shutil.rmtree(ours_log, ignore_errors=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    _run(
        [
            sys.executable, os.path.join(_REPO, "main.py"),
            *common,
            "--checkpoint", ckpt,
            "--log-base", ours_log,
        ],
        env=env,
    )
    # main.py derives the log dir from --checkpoint when set (reference
    # contract, ref main.py:184-188) — find the metrics JSON where the run
    # actually wrote it.
    metrics_files = []
    for root in (ours_log, os.path.dirname(ckpt)):
        for dirpath, _, files in os.walk(root):
            metrics_files += [
                os.path.join(dirpath, f)
                for f in files
                if f.startswith("test_metrics_")
            ]
    if not metrics_files:
        raise RuntimeError("our test run produced no test_metrics_*.json")
    with open(max(metrics_files, key=os.path.getmtime)) as f:
        ours = json.load(f)

    # --- compare ---
    # Split identity check: metrics are only comparable if both frameworks
    # put the SAME events in the test split (both use pandas
    # sample(frac=1, random_state=seed) + contiguous ranges — ref
    # diting.py:281-299).
    import seist_tpu.data  # noqa: F401  (dataset registration; CPU-only path)
    from seist_tpu.registry import DATASETS

    ours_ds = DATASETS.create(
        "diting_light",
        seed=args.seed,
        mode="test",
        data_dir=fixture,
        shuffle=True,
        data_split=True,
        train_size=args.train_size,
        val_size=args.val_size,
    )
    our_ev_ids = [int(v) for v in ours_ds._meta_data["ev_id"]]
    if our_ev_ids != ref["ev_ids"]:
        raise RuntimeError(
            f"test splits differ: ref {len(ref['ev_ids'])} events, "
            f"ours {len(our_ev_ids)} — metric comparison would be invalid"
        )
    print(f"test split identical on both sides: {len(our_ev_ids)} events")
    rows, max_abs = [], 0.0
    for task, ref_m in sorted(ref["metrics"].items()):
        our_m = ours["metrics"].get(task, {})
        for name, rv in sorted(ref_m.items()):
            ov = our_m.get(name, float("nan"))
            d = abs(ov - rv)
            max_abs = max(max_abs, d if d == d else float("inf"))
            rows.append((task, name, rv, ov, d))
    print(f"\n{'task':8s} {'metric':10s} {'reference':>12s} "
          f"{'ours':>12s} {'|diff|':>10s}")
    for task, name, rv, ov, d in rows:
        print(f"{task:8s} {name:10s} {rv:12.6f} {ov:12.6f} {d:10.2e}")
    print(f"\nloss: ref {ref['loss']:.6f}  ours {ours['loss']:.6f}")
    print(f"max metric |diff|: {max_abs:.3e}")

    result = {
        "model": args.model_name,
        "n_test_events": len(ref.get("ev_ids", [])),
        "reference": ref["metrics"],
        "ours": ours["metrics"],
        "ref_loss": ref["loss"],
        "our_loss": ours["loss"],
        "max_abs_diff": max_abs,
    }
    out_path = os.path.join(args.workdir, "parity_eval_result.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(f"saved: {out_path}")


if __name__ == "__main__":
    main()
