#!/bin/bash
# Watcher 3: after tools/ab_phase_split.sh finishes (ALL DONE marker),
# isolate the contribution of each lowering, same session:
#   - SEIST_STEM_IMPL=fused     (composed DSConv + one-conv stems)
#   - SEIST_DSCONV_IMPL=paths   (phase-split shift-FMA stems, no composed)
#   - matrix-comparable b256 rows for seist_s/l_dpk at the new default
#   - eval-mode numbers for the flagship + phasenet
LOG=/root/repo/tools/ab_phase_split.log
until grep -q "ALL DONE" "$LOG" 2>/dev/null; do sleep 120; done

run() {  # $1 = tag, rest = env overrides
  tag=$1; shift
  echo "=== impl A/B: $tag $(date)" >> "$LOG"
  (cd /root/repo && env "$@" BENCH_STEPS=15 BENCH_PROBE_ATTEMPTS=1 \
     BENCH_PROBE_TIMEOUT=120 timeout 900 python bench.py 2>/dev/null) >> "$LOG"
}
run "fused stem b512"        SEIST_STEM_IMPL=fused
run "paths dsconv b512"      SEIST_DSCONV_IMPL=paths
run "default b256"           BENCH_BATCH=256
run "fused stem b256"        SEIST_STEM_IMPL=fused BENCH_BATCH=256
run "eval seist_l b256"      BENCH_MODE=eval BENCH_BATCH=256
run "eval phasenet b256"     BENCH_MODE=eval BENCH_MODEL=phasenet BENCH_BATCH=256
echo "IMPL AB DONE $(date)" >> "$LOG"
