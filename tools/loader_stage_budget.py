"""Per-stage cost budget of the input pipeline, in ms per waveform.

Times each loader stage in isolation on the real-format reader path
(VERDICT r2 #6: "publish a per-stage cost breakdown that lets a reader
verify the claim"):

  read      — dataset reader: h5py waveform read + metadata row
  augment   — DataPreprocessor.process with augmentation (window, the nine
              augmentations, normalize)
  labels    — soft-label + metrics-target generation
  assembly  — np.stack of a full batch + meta json

Prints one JSON line with ms/wf per stage and the implied serial wf/s.

    python tools/loader_stage_budget.py [n_samples] [batch]

Env: BENCH_DATASET (diting_light | synthetic | packed), BENCH_SAMPLES (8192).
``packed`` measures the packed-shard repack of the SAME diting_light
fixture (tools/pack_dataset.py): the read-stage delta vs diting_light is
the h5py per-sample API tax the offline repack removes.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 600
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 200
    in_samples = int(os.environ.get("BENCH_SAMPLES", 8192))
    dataset_name = os.environ.get("BENCH_DATASET", "diting_light")

    import numpy as np

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.data import pipeline

    seist_tpu.load_all()
    spec = taskspec.get_task_spec("seist_l_dpk")
    ds_kw: dict = {}
    data_dir = ""
    if dataset_name == "synthetic":
        ds_kw = {"num_events": max(512, n)}
    elif dataset_name == "packed":
        # The packed-shard repack of the SAME fixture (VERDICT r4 #8):
        # read-stage delta vs diting_light is the measured h5py tax.
        from tools.fixtures import ensure_packed_fixture

        data_dir = ensure_packed_fixture(max(1000, n), in_samples)
    else:
        from tools.fixtures import write_diting_light_fixture

        n_events = max(1000, n)
        data_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            os.pardir,
            "logs",
            f"loader_fixture_{n_events}x{in_samples}",
        )
        marker = os.path.join(data_dir, ".complete")
        if not os.path.exists(marker):
            write_diting_light_fixture(
                data_dir, n_events=n_events, trace_samples=in_samples
            )
            with open(marker, "w") as f:
                f.write("ok\n")

    ds = pipeline.from_task_spec(
        spec,
        dataset_name,
        "train",
        seed=0,
        in_samples=in_samples,
        augmentation=True,
        data_dir=data_dir,
        dataset_kwargs=ds_kw,
    )
    reader = ds._dataset
    pre = ds.preprocessor
    size = len(reader)
    idxs = [i % size for i in range(n)]

    # Warm caches (h5 handles, soft-label windows, native dlopen).
    for i in idxs[:20]:
        ds[i]

    def timed(fn, items):
        t0 = time.perf_counter()
        out = [fn(x) for x in items]
        return (time.perf_counter() - t0) / len(items) * 1e3, out

    # read
    ms_read, events = timed(lambda i: reader[i], idxs)

    # augment (process mutates a copy; per-sample rng like the real path)
    def aug(pair):
        i, (event, _meta) = pair
        rng = np.random.default_rng(np.random.SeedSequence([0, 0, i]))
        return pre.process(event=dict(event), augmentation=True, rng=rng)

    ms_aug, processed = timed(aug, list(enumerate(events)))

    # labels
    def labels(event):
        inputs = pre.get_inputs(event, ds._input_names)
        lt = pre.get_targets_for_loss(event, ds._label_names)
        mt = pre.get_targets_for_metrics(
            event, max_event_num=1, task_names=ds._task_names
        )
        return inputs, lt, mt

    ms_labels, samples = timed(labels, processed)

    # assembly (stack into batches + meta json, as Loader.__iter__ does)
    metas = [m for _, m in events]

    def assemble(lo):
        part = samples[lo : lo + batch]
        inputs = pipeline._stack([s[0] for s in part])
        lt = pipeline._stack([s[1] for s in part])
        mt = {k: np.stack([s[2][k] for s in part]) for k in part[0][2]}
        mj = [
            json.dumps({k: str(v) for k, v in dict(m).items()})
            for m in metas[lo : lo + batch]
        ]
        return inputs, lt, mt, mj

    starts = list(range(0, n - batch + 1, batch)) or [0]
    t0 = time.perf_counter()
    for lo in starts:
        assemble(lo)
    ms_asm = (time.perf_counter() - t0) / (len(starts) * batch) * 1e3

    total = ms_read + ms_aug + ms_labels + ms_asm
    print(
        json.dumps(
            {
                "metric": "loader_stage_budget",
                "unit": "ms/waveform",
                "dataset": dataset_name,
                "in_samples": in_samples,
                "read": round(ms_read, 3),
                "augment": round(ms_aug, 3),
                "labels": round(ms_labels, 3),
                "assembly": round(ms_asm, 3),
                "total": round(total, 3),
                "implied_serial_wfs": round(1e3 / total, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
