"""Batch-fleet scaling lane: 3 lease workers vs 1, byte-identity gated
-> BENCH_batch_fleet_r01.json.

The fleet's economic claim is linear-ish scaling — N workers re-pick an
archive ~N x faster than one, because leases partition the units with
no coordination on the hot path (one acquire + a heartbeat per unit,
against seconds of device compute). This lane measures it: the same
synthetic packed archive re-picked (a) by one fleet worker and (b) by a
3-worker fleet under tools/supervise_repick.py, wall-clock compared
AFTER each worker's warm-up (compile time is a fixed per-process cost
the persistent XLA cache amortizes; the scaling story is about the feed
loop).

Two gates, one hard and one hardware-conditional:

* **byte-identity (hard)** — sha256(catalog.jsonl) of the 3-worker
  fleet EQUALS the 1-worker run's. Fleet concurrency may never cost
  bytes; a scaling number for a diverging catalog would be meaningless.
* **scaling (>= --min-speedup, chips only)** — on a single-core CI host
  3 compute-bound workers just time-slice one CPU, so the gate is
  recorded as ``pending`` (the quant_smoke ``tpu_run: pending`` idiom)
  and the measured speedup is logged, not enforced. On a >= 3-core
  host (or a real slice) it gates.

Writes the BENCH JSON (--out) and prints it. Exit 0 iff every
applicable gate holds.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict

from tools.batch_chaos import BATCH, BPC, COMMIT, _pack, _repick_args

_DEF_OUT = "BENCH_batch_fleet_r01.json"


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _last_json(text: str, role: str) -> Dict[str, Any]:
    for line in reversed(text.strip().splitlines()):
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if d.get("role") == role:
            return d
    raise SystemExit(f"no '{role}' verdict in output: {text[-400:]}")


def _run_fleet(archive: str, out: str, workers: int, slow_ms: int) -> Dict[str, Any]:
    lease_dir = os.path.join(out, "leases")
    env = dict(os.environ)
    env["SEIST_FAULT_REPICK_SLOW_MS"] = str(slow_ms)
    proc = subprocess.run(
        [sys.executable, "-m", "tools.supervise_repick",
         *_repick_args(archive, out),
         "--workers", str(workers), "--lease-dir", lease_dir,
         "--timeout-s", "420"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=600,
    )
    if proc.returncode != 0:
        print(proc.stdout[-3000:], file=sys.stderr)
        raise SystemExit(f"{workers}-worker fleet rc={proc.returncode}")
    return _last_json(proc.stdout, "supervisor")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.bench_batch_fleet",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--out", default=_DEF_OUT)
    ap.add_argument("--min-speedup", type=float, default=1.8,
                    help="3-vs-1 wall-clock gate (>= 3 cores only)")
    ap.add_argument("--slow-ms", type=int, default=150,
                    help="per-device-call sleep standing in for real "
                    "device latency — sleeps overlap across workers "
                    "even on one core, so the lease plane's overhead "
                    "is what the ratio exposes")
    ap.add_argument("--keep", action="store_true")
    args = ap.parse_args(argv)

    from seist_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()
    import jax

    cores = os.cpu_count() or 1
    root = tempfile.mkdtemp(prefix="bench_batch_fleet_")
    try:
        archive = os.path.join(root, "archive")
        _pack(archive)
        sup1 = _run_fleet(
            archive, os.path.join(root, "one"), 1, args.slow_ms
        )
        sup3 = _run_fleet(
            archive, os.path.join(root, "three"), 3, args.slow_ms
        )
        sha1 = _sha256(os.path.join(root, "one", "catalog.jsonl"))
        sha3 = _sha256(os.path.join(root, "three", "catalog.jsonl"))
        speedup = round(sup1["wall_s"] / sup3["wall_s"], 2)
        scaling_gated = cores >= 3
        identical = sha1 == sha3
        ok = identical and (speedup >= args.min_speedup or not scaling_gated)
        bench = {
            "metric": "batch_fleet_scaling_3v1",
            "value": speedup,
            "unit": "wall-clock speedup, 3-worker lease fleet vs 1 "
                    "(supervise_repick end-to-end incl. merge)",
            "gate_min_speedup": args.min_speedup,
            "scaling_gate": (
                "enforced" if scaling_gated
                else f"pending ({cores} core host: 3 compute-bound "
                     "workers time-slice one CPU; chip run pending)"
            ),
            "byte_identical": identical,
            "sha256": sha3,
            "wall_s": {"workers_1": sup1["wall_s"],
                       "workers_3": sup3["wall_s"]},
            "rows": sup3.get("rows"),
            "units": sup3.get("units"),
            "lease_ops_3w": sup3.get("lease"),
            "config": {
                "model": "phasenet", "batch": BATCH,
                "batches_per_call": BPC, "commit_every": COMMIT,
                "slow_ms": args.slow_ms, "host_cores": cores,
            },
            "device": jax.devices()[0].platform,
            "backend": jax.default_backend(),
            "measured_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "pass": bool(ok),
        }
        with open(args.out, "w") as f:
            json.dump(bench, f, indent=1)
            f.write("\n")
        print(json.dumps(bench))
        return 0 if ok else 1
    finally:
        if not args.keep:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
