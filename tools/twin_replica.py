"""One HTTP replica of the digital twin's deterministic picker.

The stream-chaos lane (`make stream-chaos`) needs a REAL fleet — three
processes behind the router, SIGKILL-able mid-mainshock — but the gates
need the twin's computable ground truth, which a checkpointed model
cannot give. This bridges the two: the exact ``twinpick`` z-outlier
service ``tools/twin.py`` drives in-process, wrapped in the serving
stack's HTTP front-end with the durability plane on (per-station
journals + alert WAL under ``--journal-dir``, shared by the fleet — the
sharing IS the failover channel).

Launched by ``tools/supervise_fleet.py`` exactly like a ``main.py
serve`` replica::

    python tools/supervise_fleet.py --replicas 3 -- \
        python tools/twin_replica.py --journal-dir /tmp/j

Signals follow the serve CLI's contract: SIGTERM = managed preemption
(drain, flush journals via ``shutdown(drain=True)``, exit
``PREEMPT_EXIT_CODE`` so the supervisor relaunches), SIGINT = operator
stop (exit 0). A SIGKILL — the chaos lane's weapon — runs nothing at
all, which is the point: recovery must come from the journals the mux
wrote BEFORE the crash.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
from types import SimpleNamespace
from typing import List, Optional

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))
sys.path.insert(0, _TOOLS)


def get_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(description="twin picker HTTP replica")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--window", type=int, default=256)
    ap.add_argument("--stations", type=int, default=200,
                    help="station capacity hint (mux max_stations)")
    ap.add_argument("--min-stations", type=int, default=4)
    ap.add_argument("--journal-dir", default=None,
                    help="shared fleet journal/WAL root (unset = none)")
    ap.add_argument("--journal-every-s", type=float, default=0.5,
                    help="per-station journal cadence; the chaos default "
                    "is tight so a SIGKILL loses sub-second state")
    ap.add_argument("--dedup-window-s", type=float, default=2.0)
    return ap.parse_args(argv)


def main(argv: Optional[List[str]] = None) -> int:
    args = get_args(argv)
    import twin

    from seist_tpu.serve.server import (
        PREEMPT_EXIT_CODE,
        start_http_server,
    )
    from seist_tpu.utils.logger import logger

    service = twin._make_service(SimpleNamespace(
        window=args.window,
        stations=args.stations,
        min_stations=args.min_stations,
        journal_dir=args.journal_dir,
        journal_every_s=args.journal_every_s,
        assoc_dedup_window_s=args.dedup_window_s,
    ))
    server = start_http_server(service, args.host, args.port)
    host, port = server.server_address[:2]
    logger.info(f"[twin-replica] listening on http://{host}:{port} "
                f"journal_dir={args.journal_dir or '-'}")

    stop = threading.Event()
    exit_code = {"rc": 0}

    def _term(signum, frame):
        if signum == signal.SIGTERM:
            exit_code["rc"] = PREEMPT_EXIT_CODE
        # threadlint: disable=signal-handler-unsafe -- flag store +
        # edge-triggered publish; main thread is parked in stop.wait.
        service.begin_drain()
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not stop.wait(1.0):  # timed: a lost set() can't park forever
        pass
    rc = exit_code["rc"]
    logger.info("[twin-replica] draining...")
    # drain=True closes every stream mux: sessions journal their final
    # state (the clean-handoff half of failover; SIGKILL skips this).
    service.shutdown(drain=True)
    server.shutdown()
    logger.info(f"[twin-replica] stopped (rc={rc})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
