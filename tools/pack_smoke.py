"""Packed data-plane smoke (`make pack-smoke`): parallel pack -> train
parity.

1. Packs the synthetic dataset with TWO shard-parallel workers
   (tools/pack_dataset.py machinery) and cross-checks the plan against a
   serial pack (bit-identical shards — the parallel-pack contract).
2. Trains the same tiny config for 2 epochs on the UNPACKED source and
   on the packed output at the same seed, and asserts loss-curve parity:
   the packed reader serves identical Events, the seeded shuffle/split
   matches, and the per-sample (seed, epoch, idx) RNG is path-invariant,
   so the two loss curves must agree to float tolerance.

Prints ONE JSON verdict line; exits non-zero on any parity failure.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
from types import SimpleNamespace


def _train_args(**over):
    d = dict(
        mode="train",
        model_name="phasenet",
        checkpoint="",
        # Seed 0 on purpose: pack sources are constructed with seed=0
        # (content-generating datasets like synthetic derive WAVEFORMS
        # from it, not just the split), so the parity run must train at
        # the same seed to read the same bytes on both paths.
        seed=0,
        log_base="",
        log_step=100,
        use_tensorboard=False,
        save_test_results=False,
        data="",
        dataset_name="synthetic",
        data_split=True,
        train_size=0.8,
        val_size=0.1,
        shuffle=True,
        workers=2,
        in_samples=512,
        label_width=0.5,
        label_shape="gaussian",
        coda_ratio=2.0,
        norm_mode="std",
        min_snr=-float("inf"),
        p_position_ratio=-1,
        augmentation=True,
        add_event_rate=0.0,
        max_event_num=1,
        shift_event_rate=0.2,
        add_noise_rate=0.2,
        add_gap_rate=0.0,
        min_event_gap=0.5,
        drop_channel_rate=0.0,
        scale_amplitude_rate=0.0,
        pre_emphasis_rate=0.0,
        pre_emphasis_ratio=0.97,
        generate_noise_rate=0.0,
        mask_percent=0,
        noise_percent=0,
        epochs=2,
        patience=30,
        steps=0,
        start_epoch=0,
        batch_size=8,
        optim="Adam",
        momentum=0.9,
        weight_decay=0.0,
        use_lr_scheduler=True,
        lr_scheduler_mode="exp_range",
        base_lr=8e-5,
        max_lr=1e-3,
        warmup_steps=2000,
        down_steps=3000,
        time_threshold=0.1,
        min_peak_dist=1.0,
        ppk_threshold=0.3,
        spk_threshold=0.3,
        det_threshold=0.5,
        max_detect_event_num=1,
        dataset_kwargs={"num_events": 40, "trace_samples": 1536},
    )
    d.update(over)
    return SimpleNamespace(**d)


def main() -> int:
    import numpy as np

    import seist_tpu
    from seist_tpu.data.packed import PackSource, pack_sources
    from seist_tpu.train.worker import train_worker
    from seist_tpu.utils.logger import logger

    seist_tpu.load_all()
    os.makedirs("logs", exist_ok=True)  # gitignored; absent on fresh clones
    work = tempfile.mkdtemp(prefix="pack_smoke_", dir="logs")
    src = lambda: PackSource(  # noqa: E731 - tiny local factory
        name="synthetic",
        dataset_kwargs={
            "num_events": 40, "trace_samples": 1536, "cache": False,
        },
    )

    # -- 1. parallel pack, cross-checked against serial ------------------
    par = pack_sources(
        [src()], os.path.join(work, "packed"), num_workers=2,
        samples_per_shard=8,
    )
    ser = pack_sources(
        [src()], os.path.join(work, "packed_serial"), samples_per_shard=8
    )
    pack_identical = True
    for shard in range(par["shards"]):
        a = os.path.join(work, "packed", f"shard_{shard:05d}.bin")
        b = os.path.join(work, "packed_serial", f"shard_{shard:05d}.bin")
        with open(a, "rb") as fa, open(b, "rb") as fb:
            if fa.read() != fb.read():
                pack_identical = False

    # -- 2. 2-epoch loss-curve parity: source vs packed ------------------
    def run(name, **over):
        logdir = os.path.join(work, name)
        logger.set_logdir(logdir)
        train_worker(_train_args(**over))
        return np.load(os.path.join(logdir, "train_losses.npy"))

    losses_src = run("train_source")
    losses_packed = run(
        "train_packed",
        dataset_name="packed",
        data=os.path.join(work, "packed"),
        dataset_kwargs={},
    )
    delta = float(np.max(np.abs(losses_src - losses_packed)))
    parity = bool(
        losses_src.shape == losses_packed.shape
        and np.allclose(losses_src, losses_packed, rtol=1e-5, atol=1e-7)
    )

    verdict = {
        "metric": "pack_smoke",
        "pack_workers": 2,
        "pack_bit_identical": pack_identical,
        "epochs": 2,
        "steps": int(losses_src.shape[0]),
        "loss_parity": parity,
        "max_loss_delta": delta,
        "pack": {k: par[k] for k in ("shards", "samples", "bytes", "wall_s")},
        "pass": parity and pack_identical,
    }
    print(json.dumps(verdict))
    if verdict["pass"]:
        shutil.rmtree(work, ignore_errors=True)
        return 0
    print(f"pack-smoke artifacts kept at {work}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
