"""Import a reference torch checkpoint (.pth) into an orbax checkpoint.

User-facing path for the reference's 18 published SeisT weights
(``/root/reference/pretrained/*.pth``, download table ref README.md:136-184):
convert the raw torch state-dict (layout mapping in tools/parity.py) and
write a params+batch_stats orbax checkpoint that ``--checkpoint`` (test
mode / resume) and ``demo_predict.py`` consume directly.

    python tools/import_pretrained.py \
        --pth /root/reference/pretrained/seist_s_dpk_diting.pth \
        --model-name seist_s_dpk --out ./imported/seist_s_dpk

Then:

    python demo_predict.py --model-name seist_s_dpk \
        --checkpoint ./imported/seist_s_dpk
"""

from __future__ import annotations

import argparse
import os
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _TOOLS)  # for `parity`
sys.path.insert(0, os.path.dirname(_TOOLS))  # for `seist_tpu` without install


def main() -> None:
    parser = argparse.ArgumentParser(
        description="torch .pth -> orbax checkpoint importer"
    )
    parser.add_argument("--pth", required=True, type=str,
                        help="path to the torch state-dict (.pth)")
    parser.add_argument("--model-name", required=True, type=str,
                        help="registered model name, e.g. seist_s_dpk")
    parser.add_argument("--in-samples", default=8192, type=int)
    parser.add_argument("--in-channels", default=None, type=int,
                        help="default: the model's task-spec input count "
                        "(3 for most, 2 for ditingmotion's [z, dz])")
    parser.add_argument("--out", required=True, type=str,
                        help="output orbax checkpoint directory")
    args = parser.parse_args()

    # Pure host-side conversion (shape-only trace + numpy + orbax): force
    # the CPU backend — importing jax with the TPU tunnel down would
    # otherwise hang minutes in backend init for no benefit.
    import jax

    jax.config.update("jax_platforms", "cpu")

    import torch

    import seist_tpu
    from parity import convert_state_dict
    from seist_tpu.models import api

    seist_tpu.load_all()

    sd = torch.load(args.pth, map_location="cpu", weights_only=True)
    # The shipped .pth files are raw state-dicts; full training checkpoints
    # nest the weights under 'model_dict' (ref _factory.py:59-87,101-102).
    if "model_dict" in sd:
        sd = sd["model_dict"]
    sd = {
        k.removeprefix("module.").removeprefix("_orig_mod."): v
        for k, v in sd.items()
    }

    if args.in_channels is None:
        from seist_tpu import taskspec

        try:
            args.in_channels = taskspec.get_num_inchannels(args.model_name)
        except KeyError:
            # distpt_network has no task spec (ref ships its config
            # commented out); every spec-less model takes 3-channel input.
            args.in_channels = 3

    model = api.create_model(
        args.model_name,
        in_channels=args.in_channels,
        in_samples=args.in_samples,
    )
    shapes = api.param_shapes(
        model, in_samples=args.in_samples, in_channels=args.in_channels
    )
    converted = convert_state_dict(sd, shapes)

    import orbax.checkpoint as ocp

    payload = {
        "params": converted["params"],
        "batch_stats": converted.get("batch_stats", {}),
        "meta": {"epoch": -1, "loss": float("inf"), "step": 0},
    }
    out = os.path.abspath(args.out)
    with ocp.StandardCheckpointer() as saver:
        saver.save(out, payload, force=True)
    n = sum(
        int(v.size)
        for v in __import__("jax").tree_util.tree_leaves(payload["params"])
    )
    print(f"Imported {args.pth} -> {out} ({n:,} params)")


if __name__ == "__main__":
    main()
