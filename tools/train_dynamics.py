"""Training-dynamics parity harness: torch reference vs seist_tpu (VERDICT r3 #5).

Forward/gradient parity (tools/parity.py) proves single-step math; this tool
probes what those tests cannot see — BN-momentum convention, LR-schedule
shape, optimizer-epsilon, loss-scaling drift — by training BOTH frameworks
from the IDENTICAL initialization on byte-identical fixture batches in the
same order with the same cyclic LR schedule, and recording the full loss
trajectories:

  * per-step train loss (ref training/train.py:90-135: loss on the train=True
    forward of each batch, recorded before the optimizer step applies)
  * per-epoch val loss (ref training/train.py:397-410 -> validate.py:54-127:
    eval-mode forward, which runs on BN *running* stats — the only place a
    BN-momentum drift can show up)

Models (--model): phasenet (plain conv/BN/softmax/CE) and seist_s_dpk
(the flagship family: multi-path stems, grouped convs, pooled attention,
DropPath residuals, BCE) — each with every drop rate zeroed, because
dropout masks are framework-RNG-specific and must be excluded from a
trajectory comparison; everything else under the reference's CyclicLR
(train.py:343-354) is deterministic and directly comparable.

Usage (each side prints one JSON line and optionally writes it to --out):
    python tools/train_dynamics.py --side torch --out /tmp/torch.json
    python tools/train_dynamics.py --side jax --init /tmp/dyn_init.npz \
        --out /tmp/jax.json

The torch side writes its INITIAL state-dict to --init (npz) so the jax side
trains from the converted identical weights. tests/test_train_dynamics.py
runs both and asserts the trajectories agree within tolerance.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# One config both sides share — keep in lockstep with the test.
CFG = {
    "model": "phasenet",
    "in_samples": 512,
    "batch": 8,
    "steps_per_epoch": 8,
    "epochs": 6,
    "val_n": 32,
    "base_lr": 8e-5,
    "max_lr": 1e-3,
    "warmup_steps": 16,
    "down_steps": 32,
    "data_seed": 123,
    "init_seed": 7,
}

# Per-model specifics: kwargs that zero every dropout (masks are
# framework-RNG-specific and must be excluded from a trajectory
# comparison; both factories accept the same names), the label layout,
# and the reference loss. phasenet: softmax CE over (non, ppk, spk)
# (ref config.py:67-75); seist dpk family: sigmoid BCE over
# (det, ppk, spk) with weights [[.5],[1],[1]] (ref config.py:138) —
# covering the flagship architecture's attention / DropPath / grouped
# convs / multi-stem dynamics, not just phasenet's plain conv+BN.
MODELS = {
    "phasenet": {
        "zero_drop_kwargs": {"drop_rate": 0.0},
        "labels": "non_ppk_spk",
        "ref_loss": "ce",
    },
    "seist_s_dpk": {
        "zero_drop_kwargs": {
            "path_drop_rate": 0.0,
            "attn_drop_rate": 0.0,
            "key_drop_rate": 0.0,
            "mlp_drop_rate": 0.0,
            "other_drop_rate": 0.0,
        },
        "labels": "det_ppk_spk",
        "ref_loss": "bce_dpk",
    },
}


def make_data(cfg=CFG):
    """Deterministic synthetic picks, identical bytes for both sides.

    Returns (x, y) with torch layout (N, C, L) fp32; the jax side
    transposes to channels-last. Labels are (non, ppk, spk) prob curves
    (gaussian sigma=10, the reference's label quirk preprocess.py:698).
    """
    n = cfg["batch"] * cfg["steps_per_epoch"] + cfg["val_n"]
    L = cfg["in_samples"]
    rng = np.random.default_rng(cfg["data_seed"])
    t = np.arange(L, dtype=np.float32)
    x = rng.standard_normal((n, 3, L)).astype(np.float32) * 0.1
    tp = rng.integers(L // 8, L // 2, size=n)
    ts = tp + rng.integers(L // 16, L // 4, size=n)
    y = np.zeros((n, 3, L), np.float32)
    for i in range(n):
        env_p = np.where(t >= tp[i], np.exp(-(t - tp[i]) / (L / 8)), 0.0)
        env_s = np.where(t >= ts[i], np.exp(-(t - ts[i]) / (L / 8)), 0.0)
        x[i] += np.sin(2 * np.pi * t / 11.0) * env_p
        x[i, 1:] += 1.5 * np.sin(2 * np.pi * t / 17.0) * env_s
        y[i, 1] = np.exp(-((t - tp[i]) ** 2) / (2 * 10.0**2))
        y[i, 2] = np.exp(-((t - ts[i]) ** 2) / (2 * 10.0**2))
    # Per-sample std normalization (norm_mode="std", ref preprocess.py):
    x /= x.std(axis=(1, 2), keepdims=True) + 1e-12
    if MODELS[cfg["model"]]["labels"] == "det_ppk_spk":
        # det: 1 over [tp, ts + 0.4*(ts-tp)] (the reference's coda-scaled
        # detection span; exact shape is irrelevant here — both sides
        # train on the identical bytes).
        for i in range(n):
            end = ts[i] + 0.4 * (ts[i] - tp[i])
            y[i, 0] = ((t >= tp[i]) & (t <= end)).astype(np.float32)
    else:
        y[:, 0] = np.clip(1.0 - y[:, 1] - y[:, 2], 0.0, 1.0)
    n_train = cfg["batch"] * cfg["steps_per_epoch"]
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])


def run_torch(init_path: str, cfg=CFG) -> dict:
    import torch

    from tools.bench_reference import _install_timm_stub

    _install_timm_stub()  # reference seist.py imports timm's DropPath
    sys.path.insert(0, "/root/reference")
    from models import create_model  # reference models/_factory.py
    from models.loss import BCELoss, CELoss  # reference models/loss.py

    spec = MODELS[cfg["model"]]
    torch.manual_seed(cfg["init_seed"])
    model = create_model(
        cfg["model"],
        in_channels=3,
        in_samples=cfg["in_samples"],
        **spec["zero_drop_kwargs"],
    )
    # Persist the initial weights for the jax side (npz of numpy arrays).
    np.savez(
        init_path,
        **{k: v.detach().cpu().numpy() for k, v in model.state_dict().items()},
    )

    if spec["ref_loss"] == "bce_dpk":
        loss_fn = BCELoss(weight=[[0.5], [1], [1]])  # ref config.py:138
    else:
        loss_fn = CELoss(weight=[[1], [1], [1]])
    opt = torch.optim.Adam(model.parameters(), lr=cfg["base_lr"])
    total = cfg["epochs"] * cfg["steps_per_epoch"]
    sched = torch.optim.lr_scheduler.CyclicLR(
        opt,
        base_lr=cfg["base_lr"],
        max_lr=cfg["max_lr"],
        step_size_up=cfg["warmup_steps"],
        step_size_down=cfg["down_steps"],
        mode="exp_range",
        gamma=cfg["base_lr"] ** ((total * 2) ** -1),  # ref train.py:350
        cycle_momentum=False,
    )

    (xt, yt), (xv, yv) = make_data(cfg)
    xt, yt = torch.from_numpy(xt), torch.from_numpy(yt)
    xv, yv = torch.from_numpy(xv), torch.from_numpy(yv)
    b = cfg["batch"]

    train_losses, val_losses = [], []
    for _epoch in range(cfg["epochs"]):
        model.train()
        for s in range(cfg["steps_per_epoch"]):
            xb, yb = xt[s * b : (s + 1) * b], yt[s * b : (s + 1) * b]
            opt.zero_grad()
            loss = loss_fn(model(xb), yb)
            loss.backward()
            opt.step()
            sched.step()  # per optimizer step, ref train.py:115
            train_losses.append(float(loss.item()))
        model.eval()
        with torch.no_grad():
            val_losses.append(float(loss_fn(model(xv), yv).item()))
    return {
        "side": "torch",
        "train_loss_per_step": train_losses,
        "val_loss_per_epoch": val_losses,
        "config": cfg,
    }


def run_jax(init_path: str, cfg=CFG) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.models import api
    from seist_tpu.train import (
        build_cyclic_schedule,
        build_optimizer,
        create_train_state,
        make_eval_step,
        make_train_step,
    )
    from tools.parity import convert_state_dict

    seist_tpu.load_all()
    model = api.create_model(
        cfg["model"],
        in_samples=cfg["in_samples"],
        **MODELS[cfg["model"]]["zero_drop_kwargs"],
    )
    variables = api.init_variables(
        model, in_samples=cfg["in_samples"], batch_size=cfg["batch"]
    )
    sd = dict(np.load(init_path))
    variables = convert_state_dict(sd, variables)

    total = cfg["epochs"] * cfg["steps_per_epoch"]
    sched = build_cyclic_schedule(
        cfg["base_lr"],
        cfg["max_lr"],
        total_steps=total,
        warmup_steps=cfg["warmup_steps"],
        down_steps=cfg["down_steps"],
    )
    state = create_train_state(model, variables, build_optimizer("adam", sched))

    spec = taskspec.get_task_spec(cfg["model"])
    loss_fn = taskspec.make_loss(cfg["model"])
    train_step = jax.jit(make_train_step(spec, loss_fn))
    eval_step = jax.jit(make_eval_step(spec, loss_fn))

    (xt, yt), (xv, yv) = make_data(cfg)
    # channels-last for this framework
    xt, yt = xt.transpose(0, 2, 1), yt.transpose(0, 2, 1)
    xv, yv = xv.transpose(0, 2, 1), yv.transpose(0, 2, 1)
    b = cfg["batch"]
    rng = jax.random.PRNGKey(0)  # drop_rate=0: stream is never consumed
    vmask = jnp.ones((xv.shape[0],), jnp.float32)

    train_losses, val_losses = [], []
    for _epoch in range(cfg["epochs"]):
        for s in range(cfg["steps_per_epoch"]):
            xb, yb = xt[s * b : (s + 1) * b], yt[s * b : (s + 1) * b]
            state, loss, _ = train_step(state, jnp.asarray(xb), jnp.asarray(yb), rng)
            train_losses.append(float(loss))
        vloss, _ = eval_step(state, jnp.asarray(xv), jnp.asarray(yv), vmask)
        val_losses.append(float(vloss))
    return {
        "side": "jax",
        "train_loss_per_step": train_losses,
        "val_loss_per_epoch": val_losses,
        "config": cfg,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--side", choices=("torch", "jax"), required=True)
    ap.add_argument("--model", choices=sorted(MODELS), default="phasenet")
    ap.add_argument(
        "--init",
        default=os.path.join(_REPO, "logs", "dyn_init.npz"),
        help="npz path the torch side writes / the jax side reads",
    )
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    os.makedirs(os.path.dirname(os.path.abspath(args.init)), exist_ok=True)

    cfg = dict(CFG, model=args.model)
    result = (
        run_torch(args.init, cfg)
        if args.side == "torch"
        else run_jax(args.init, cfg)
    )
    line = json.dumps(result)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line)
    print(line)


if __name__ == "__main__":
    main()
