"""Training-dynamics parity harness: torch reference vs seist_tpu (VERDICT r3 #5).

Forward/gradient parity (tools/parity.py) proves single-step math; this tool
probes what those tests cannot see — BN-momentum convention, LR-schedule
shape, optimizer-epsilon, loss-scaling drift — by training BOTH frameworks
from the IDENTICAL initialization on byte-identical fixture batches in the
same order with the same cyclic LR schedule, and recording the full loss
trajectories:

  * per-step train loss (ref training/train.py:90-135: loss on the train=True
    forward of each batch, recorded before the optimizer step applies)
  * per-epoch val loss (ref training/train.py:397-410 -> validate.py:54-127:
    eval-mode forward, which runs on BN *running* stats — the only place a
    BN-momentum drift can show up)

Models (--model): phasenet (plain conv/BN/softmax/CE), seist_s_dpk (the
flagship family: multi-path stems, grouped convs, pooled attention,
DropPath residuals, BCE), eqtransformer (scan-BiLSTM + banded additive
attention — the recurrent dynamics), magnet (conv+BiLSTM regression
under the sum-reduced MousaviLoss, with the val-MAE metric),
ditingmotion ((z, dz) input into dual softmax heads under
CombinationLoss of two FocalLosses — the multi-head focal family),
seist_s_pmp (classification head, CE, with the accuracy metric), and
seist_s_dpk_droppath (stochastic depth ON with the per-sample DropPath
uniforms injected identically on both sides). The
zero-drop lanes zero every drop rate because free-running dropout masks
are framework-RNG-specific; the droppath lane instead shares the masks,
closing that excluded axis (VERDICT r4 #6). Everything else under the
reference's CyclicLR (train.py:343-354) is deterministic and directly
comparable. Each epoch also records per-epoch val metrics through ONE
shared numpy scorer (P/S pick F1; accuracy for pmp and the motion
polarity head; magnitude-head MAE for the magnet regression lane).

Usage (each side prints one JSON line and optionally writes it to --out):
    python tools/train_dynamics.py --side torch --out /tmp/torch.json
    python tools/train_dynamics.py --side jax --init /tmp/dyn_init.npz \
        --out /tmp/jax.json

The torch side writes its INITIAL state-dict to --init (npz) so the jax side
trains from the converted identical weights. tests/test_train_dynamics.py
runs both and asserts the trajectories agree within tolerance.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# One config both sides share — keep in lockstep with the test.
CFG = {
    "model": "phasenet",
    "in_samples": 512,
    "batch": 8,
    "steps_per_epoch": 8,
    "epochs": 6,
    "val_n": 32,
    "base_lr": 8e-5,
    "max_lr": 1e-3,
    "warmup_steps": 16,
    "down_steps": 32,
    "data_seed": 123,
    "init_seed": 7,
}

# Per-model specifics: kwargs that zero every dropout (masks are
# framework-RNG-specific and must be excluded from a trajectory
# comparison; both factories accept the same names), the label layout,
# and the reference loss. phasenet: softmax CE over (non, ppk, spk)
# (ref config.py:67-75); seist dpk family: sigmoid BCE over
# (det, ppk, spk) with weights [[.5],[1],[1]] (ref config.py:138) —
# covering the flagship architecture's attention / DropPath / grouped
# convs / multi-stem dynamics, not just phasenet's plain conv+BN.
MODELS = {
    "phasenet": {
        "zero_drop_kwargs": {"drop_rate": 0.0},
        "labels": "non_ppk_spk",
        "ref_loss": "ce",
    },
    "seist_s_dpk": {
        "zero_drop_kwargs": {
            "path_drop_rate": 0.0,
            "attn_drop_rate": 0.0,
            "key_drop_rate": 0.0,
            "mlp_drop_rate": 0.0,
            "other_drop_rate": 0.0,
        },
        "labels": "det_ppk_spk",
        "ref_loss": "bce_dpk",
    },
    # EQTransformer lane: scan-BiLSTM + banded additive attention + 3
    # decoders under the same BCE/CyclicLR — the recurrent-model
    # dynamics (ref eqtransformer.py:532 drop_rate=0.1 zeroed; L1 grad
    # hooks default-off in both frameworks).
    "eqtransformer": {
        "zero_drop_kwargs": {"drop_rate": 0.0},
        "labels": "det_ppk_spk",
        "ref_loss": "bce_dpk",
    },
    # Multi-head focal lane: DiTingMotion — (z, dz) 2-channel input into
    # two softmax heads (clarity, polarity) under CombinationLoss of two
    # FocalLosses (ref config.py:127-135) — the last loss family. The
    # polarity class is the P-wavelet sign (learnable); clarity is an
    # independent random class (no signal by construction — its loss
    # floors, which both sides must agree on too).
    "ditingmotion": {
        "zero_drop_kwargs": {"drop_rate": 0.0},
        "labels": "clr_pmp_onehot",
        "ref_loss": "focal_combo",
        "in_channels": 2,
    },
    # Regression lane: MagNet — conv+BiLSTM into (mag, log-var) under the
    # sum-reduced MousaviLoss (ref loss.py:193-210), the remaining loss
    # family (regression + heteroscedastic sum reduction). The synthetic
    # magnitude IS the P-wavelet amplitude (make_data), so it is
    # learnable; the per-epoch metric is val MAE on the mag head.
    "magnet": {
        "zero_drop_kwargs": {"drop_rate": 0.0},
        "labels": "emg_value",
        "ref_loss": "mousavi",
        # Why this lane diverges faster than every other (measured, not
        # guessed): at the shared init the frameworks' gradients agree
        # to 1.2e-6 worst-leaf, but Adam's first updates are
        # ~lr*sign(g) — coordinates where g is near zero FLIP SIGN
        # under fp-level noise, giving macroscopic 2*lr parameter
        # deltas. The dense-loss lanes average that away over 8192x3
        # outputs; MagNet's sum-reduced scalar objective (plus a
        # log-var head with large curvature at init) feels it
        # immediately: step-0 loss exact, step-1 rel drift ~5e-4
        # regardless of LR. A gentler ceiling (identical on both
        # sides) keeps the trajectory in a comparable regime.
        "cfg_overrides": {"max_lr": 3e-4},
    },
    # Classification lane (VERDICT r4 #6, metric half): first-motion
    # polarity, CE over a (N, 2) softmax — the accuracy-metric dynamics.
    # The synthetic data encodes the class as the SIGN of the P wavelet
    # (make_data), so polarity is learnable from the waveform.
    "seist_s_pmp": {
        "zero_drop_kwargs": {
            "path_drop_rate": 0.0,
            "attn_drop_rate": 0.0,
            "key_drop_rate": 0.0,
            "mlp_drop_rate": 0.0,
            "other_drop_rate": 0.0,
        },
        "labels": "pmp_onehot",
        "ref_loss": "ce_pmp",
    },
    # Dropout-ON lane (VERDICT r4 #6): stochastic depth active, with the
    # per-sample DropPath uniforms INJECTED identically on both sides
    # (torch: the timm-stub's DropPath.inject; jax: models/common.py
    # droppath_mask_injection) — the technique ring attention's
    # dropout-parity test already uses, applied cross-framework. Element
    # dropouts stay 0: their masks live in layout-specific activations
    # and (for attention probs) inside the fused kernel's counter PRNG.
    "seist_s_dpk_droppath": {
        "factory": "seist_s_dpk",
        "zero_drop_kwargs": {
            "path_drop_rate": 0.2,
            "attn_drop_rate": 0.0,
            "key_drop_rate": 0.0,
            "mlp_drop_rate": 0.0,
            "other_drop_rate": 0.0,
        },
        "labels": "det_ppk_spk",
        "ref_loss": "bce_dpk",
        "inject_droppath": True,
    },
}

# Rows available per forward for injected DropPath uniforms; each call
# consumes one row, both sides in call order. Far above seist_s's actual
# call count (asserted equal across sides by the test).
MAX_DROPPATH_CALLS = 64


def droppath_uniforms(cfg: dict, global_step: int) -> np.ndarray:
    """The SHARED per-step uniform draws for injected DropPath — both
    sides regenerate this exact array from the config seed."""
    rng = np.random.default_rng([cfg["data_seed"], 777, global_step])
    return rng.random((MAX_DROPPATH_CALLS, cfg["batch"]), dtype=np.float32)


def class_accuracy(probs_nc, true_cls):
    """argmax accuracy on (N, num_classes) eval-mode probabilities — the
    shared scorer for the pmp lane (both sides run this exact code)."""
    return round(
        float((np.argmax(probs_nc, axis=1) == np.asarray(true_cls)).mean()), 4
    )


def value_mae(preds_n2, true_vals):
    """MAE of the magnitude head (column 0 of MagNet's (mag, log-var)
    output) — the shared scorer for the emg regression lane."""
    return round(
        float(
            np.mean(np.abs(np.asarray(preds_n2)[:, 0] - np.asarray(true_vals)))
        ),
        4,
    )


def pick_f1(probs_nlc, true_p, true_s, thresh=0.3, tol=25):
    """P/S pick F1 on eval-mode probabilities — the ONE scorer both sides
    run, so the metric trajectories are comparable by construction.
    ``probs_nlc``: (N, L, 3) channels-last with (det|non, ppk, spk);
    per trace: the argmax of a phase curve is the pick when it clears
    ``thresh``, a hit when within ``tol`` samples of the true arrival
    (ref utils/metrics.py's greedy match at its default tolerance)."""
    out = {}
    for name, ch, true in (("p", 1, true_p), ("s", 2, true_s)):
        tp = fp = fn = 0
        for i in range(probs_nlc.shape[0]):
            curve = probs_nlc[i, :, ch]
            j = int(np.argmax(curve))
            if curve[j] < thresh:
                fn += 1
            elif abs(j - int(true[i])) <= tol:
                tp += 1
            else:
                fp += 1
                fn += 1
        out[name] = round(2 * tp / max(2 * tp + fp + fn, 1), 4)
    return out


def lane_cfg(model: str, base=CFG) -> dict:
    """The ONE place a lane's effective config is assembled: CFG +
    the lane's cfg_overrides (e.g. magnet's gentler max_lr). run_torch
    and run_jax re-apply it defensively (idempotent), so direct callers
    that build ``dict(CFG, model=...)`` still train at the calibrated
    config."""
    cfg = dict(base, model=model)
    cfg.update(MODELS[model].get("cfg_overrides", {}))
    return cfg


def make_data(cfg=CFG):
    """Deterministic synthetic picks, identical bytes for both sides.

    Returns (x, y) with torch layout (N, C, L) fp32; the jax side
    transposes to channels-last. Labels are (non, ppk, spk) prob curves
    (gaussian sigma=10, the reference's label quirk preprocess.py:698).
    """
    n = cfg["batch"] * cfg["steps_per_epoch"] + cfg["val_n"]
    L = cfg["in_samples"]
    rng = np.random.default_rng(cfg["data_seed"])
    t = np.arange(L, dtype=np.float32)
    x = rng.standard_normal((n, 3, L)).astype(np.float32) * 0.1
    tp = rng.integers(L // 8, L // 2, size=n)
    ts = tp + rng.integers(L // 16, L // 4, size=n)
    labels_kind = MODELS[cfg["model"]]["labels"]
    is_pmp = labels_kind == "pmp_onehot"
    is_emg = labels_kind == "emg_value"
    is_motion = labels_kind == "clr_pmp_onehot"
    n_train = cfg["batch"] * cfg["steps_per_epoch"]
    # pmp lane: the class IS the P-wavelet polarity, so accuracy is
    # learnable from the waveform (class 1 flips the P onset sign).
    # emg lane: the magnitude IS the P-wavelet amplitude (relative to
    # the fixed noise floor, which survives per-sample normalization).
    # Both draws happen unconditionally AFTER every draw the other lanes
    # consume, so their data bytes are unchanged (asserted by the
    # byte-stability check in this file's history).
    cls = rng.integers(0, 2, size=n)
    amp = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    clr = rng.integers(0, 2, size=n)  # motion lane only; drawn last
    pol = (1.0 - 2.0 * cls) if (is_pmp or is_motion) else np.ones(n)
    scale = amp if is_emg else np.ones(n, np.float32)
    y = np.zeros((n, 3, L), np.float32)
    for i in range(n):
        env_p = np.where(t >= tp[i], np.exp(-(t - tp[i]) / (L / 8)), 0.0)
        env_s = np.where(t >= ts[i], np.exp(-(t - ts[i]) / (L / 8)), 0.0)
        x[i] += scale[i] * pol[i] * np.sin(2 * np.pi * t / 11.0) * env_p
        x[i, 1:] += 1.5 * np.sin(2 * np.pi * t / 17.0) * env_s
        if not (is_pmp or is_emg or is_motion):
            y[i, 1] = np.exp(-((t - tp[i]) ** 2) / (2 * 10.0**2))
            y[i, 2] = np.exp(-((t - ts[i]) ** 2) / (2 * 10.0**2))
    # Per-sample std normalization (norm_mode="std", ref preprocess.py):
    x /= x.std(axis=(1, 2), keepdims=True) + 1e-12
    if is_motion:
        # (z, dz): the vertical component and its sample derivative —
        # DiTingMotion's 2-channel input contract (ref config.py:129).
        z = x[:, 0]
        dz = np.gradient(z, axis=-1).astype(np.float32)
        x = np.stack([z, dz], axis=1)  # (n, 2, L)
        # y: (n, 2 heads, 2 classes) — [clarity, polarity] one-hots.
        eye = np.eye(2, dtype=np.float32)
        y = np.stack([eye[clr], eye[cls]], axis=1)
        return (
            (x[:n_train], y[:n_train]),
            (x[n_train:], y[n_train:]),
            cls[n_train:],  # true val polarity for the accuracy scorer
        )
    if is_pmp:
        y = np.eye(2, dtype=np.float32)[cls]  # (n, 2) one-hot
        return (
            (x[:n_train], y[:n_train]),
            (x[n_train:], y[n_train:]),
            cls[n_train:],  # true val classes for the accuracy scorer
        )
    if is_emg:
        y = amp.reshape(-1, 1)  # (n, 1) magnitude targets
        return (
            (x[:n_train], y[:n_train]),
            (x[n_train:], y[n_train:]),
            amp[n_train:],  # true val magnitudes for the MAE scorer
        )
    if labels_kind == "det_ppk_spk":
        # det: 1 over [tp, ts + 0.4*(ts-tp)] (the reference's coda-scaled
        # detection span; exact shape is irrelevant here — both sides
        # train on the identical bytes).
        for i in range(n):
            end = ts[i] + 0.4 * (ts[i] - tp[i])
            y[i, 0] = ((t >= tp[i]) & (t <= end)).astype(np.float32)
    else:
        y[:, 0] = np.clip(1.0 - y[:, 1] - y[:, 2], 0.0, 1.0)
    return (
        (x[:n_train], y[:n_train]),
        (x[n_train:], y[n_train:]),
        (tp[n_train:], ts[n_train:]),  # true val picks for the F1 scorer
    )


def run_torch(init_path: str, cfg=CFG) -> dict:
    cfg = lane_cfg(cfg["model"], cfg)  # idempotent (see lane_cfg)
    import torch

    from tools.bench_reference import _install_timm_stub

    _install_timm_stub()  # reference seist.py imports timm's DropPath
    sys.path.insert(0, "/root/reference")
    from models import create_model  # reference models/_factory.py
    from models.loss import BCELoss, CELoss  # reference models/loss.py

    spec = MODELS[cfg["model"]]
    torch.manual_seed(cfg["init_seed"])
    if spec["ref_loss"] == "ce_pmp":
        # The reference's seist_*_pmp factories hard-code their drop
        # rates (ref seist.py:987-1000), so passing zeroed rates through
        # create_model raises "multiple values". Build the same model
        # directly: the factory body with the rates zeroed.
        from functools import partial

        import torch.nn as nn
        from models.seist import HeadClassification, SeismogramTransformer_S

        model = SeismogramTransformer_S(
            in_channels=3,
            in_samples=cfg["in_samples"],
            output_head=partial(
                HeadClassification,
                out_act_layer=partial(nn.Softmax, dim=-1),
                num_classes=2,
            ),
            **spec["zero_drop_kwargs"],
        )
    else:
        model = create_model(
            spec.get("factory", cfg["model"]),
            in_channels=spec.get("in_channels", 3),
            in_samples=cfg["in_samples"],
            **spec["zero_drop_kwargs"],
        )
    # Persist the initial weights for the jax side (npz of numpy arrays).
    np.savez(
        init_path,
        **{k: v.detach().cpu().numpy() for k, v in model.state_dict().items()},
    )

    if spec["ref_loss"] == "bce_dpk":
        loss_fn = BCELoss(weight=[[0.5], [1], [1]])  # ref config.py:138
    elif spec["ref_loss"] == "ce_pmp":
        loss_fn = CELoss(weight=[1, 1])  # ref config.py:147-148 (flat)
    elif spec["ref_loss"] == "mousavi":
        from models.loss import MousaviLoss  # ref loss.py:193-210

        loss_fn = MousaviLoss()
    elif spec["ref_loss"] == "focal_combo":
        from models.loss import CombinationLoss, FocalLoss  # ref config.py:128

        loss_fn = CombinationLoss(losses=[FocalLoss, FocalLoss])
    else:
        loss_fn = CELoss(weight=[[1], [1], [1]])
    opt = torch.optim.Adam(model.parameters(), lr=cfg["base_lr"])
    total = cfg["epochs"] * cfg["steps_per_epoch"]
    sched = torch.optim.lr_scheduler.CyclicLR(
        opt,
        base_lr=cfg["base_lr"],
        max_lr=cfg["max_lr"],
        step_size_up=cfg["warmup_steps"],
        step_size_down=cfg["down_steps"],
        mode="exp_range",
        gamma=cfg["base_lr"] ** ((total * 2) ** -1),  # ref train.py:350
        cycle_momentum=False,
    )

    is_pmp = spec["labels"] == "pmp_onehot"
    is_emg = spec["labels"] == "emg_value"
    is_motion = spec["labels"] == "clr_pmp_onehot"
    (xt, yt), (xv, yv), val_truth = make_data(cfg)
    xt, yt = torch.from_numpy(xt), torch.from_numpy(yt)
    xv, yv = torch.from_numpy(xv), torch.from_numpy(yv)
    b = cfg["batch"]

    def to_targets(yb):
        # motion: per-head list [clarity, polarity] (ref CombinationLoss)
        return [yb[:, 0], yb[:, 1]] if is_motion else yb

    inject = spec.get("inject_droppath", False)
    StubDropPath = sys.modules["timm.models.layers"].DropPath
    dp_calls = 0

    train_losses, val_losses = [], []
    f1_p, f1_s = [], []
    for epoch in range(cfg["epochs"]):
        model.train()
        for s in range(cfg["steps_per_epoch"]):
            xb, yb = xt[s * b : (s + 1) * b], yt[s * b : (s + 1) * b]
            if inject:
                gstep = epoch * cfg["steps_per_epoch"] + s
                StubDropPath.inject = {
                    "uniforms": torch.from_numpy(droppath_uniforms(cfg, gstep)),
                    "i": 0,
                }
            opt.zero_grad()
            loss = loss_fn(model(xb), to_targets(yb))
            if inject:
                dp_calls = StubDropPath.inject["i"]
                StubDropPath.inject = None
            loss.backward()
            opt.step()
            sched.step()  # per optimizer step, ref train.py:115
            train_losses.append(float(loss.item()))
        model.eval()
        with torch.no_grad():
            val_out = model(xv)
            val_losses.append(float(loss_fn(val_out, to_targets(yv)).item()))
        if is_pmp:
            f1_p.append(class_accuracy(val_out.detach().numpy(), val_truth))
        elif is_motion:
            # polarity head (index 1 of [clarity, polarity])
            f1_p.append(
                class_accuracy(val_out[1].detach().numpy(), val_truth)
            )
        elif is_emg:
            f1_p.append(value_mae(val_out.detach().numpy(), val_truth))
        else:
            # channels-last for the shared scorer
            f1 = pick_f1(
                val_out.detach().numpy().transpose(0, 2, 1), *val_truth
            )
            f1_p.append(f1["p"])
            f1_s.append(f1["s"])
    result = {
        "side": "torch",
        "train_loss_per_step": train_losses,
        "val_loss_per_epoch": val_losses,
        "droppath_calls_per_forward": dp_calls,
        "config": cfg,
    }
    if is_pmp or is_motion:
        result["val_acc_per_epoch"] = f1_p
    elif is_emg:
        result["val_mae_per_epoch"] = f1_p
    else:
        result["val_f1_p_per_epoch"] = f1_p
        result["val_f1_s_per_epoch"] = f1_s
    return result


def run_jax(init_path: str, cfg=CFG) -> dict:
    cfg = lane_cfg(cfg["model"], cfg)  # idempotent (see lane_cfg)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.models import api
    from seist_tpu.train import (
        build_cyclic_schedule,
        build_optimizer,
        create_train_state,
        make_eval_step,
        make_train_step,
    )
    from tools.parity import convert_state_dict

    seist_tpu.load_all()
    mspec = MODELS[cfg["model"]]
    model = api.create_model(
        mspec.get("factory", cfg["model"]),
        in_channels=mspec.get("in_channels", 3),
        in_samples=cfg["in_samples"],
        **mspec["zero_drop_kwargs"],
    )
    variables = api.init_variables(
        model,
        in_samples=cfg["in_samples"],
        in_channels=mspec.get("in_channels", 3),
        batch_size=cfg["batch"],
    )
    sd = dict(np.load(init_path))
    variables = convert_state_dict(sd, variables)

    total = cfg["epochs"] * cfg["steps_per_epoch"]
    sched = build_cyclic_schedule(
        cfg["base_lr"],
        cfg["max_lr"],
        total_steps=total,
        warmup_steps=cfg["warmup_steps"],
        down_steps=cfg["down_steps"],
    )
    state = create_train_state(model, variables, build_optimizer("adam", sched))

    task = mspec.get("factory", cfg["model"])
    spec = taskspec.get_task_spec(task)
    loss_fn = taskspec.make_loss(task)
    inject = mspec.get("inject_droppath", False)
    dp_probe = {}
    if inject:
        # Same semantics as make_train_step (shared _forward_loss body:
        # BN mutation, task transforms, fp32 compute) with the per-step
        # DropPath uniforms threaded through as a traced argument and
        # routed to every DropPath call via the injection context
        # (models/common.py). The rng arg is unused: element dropouts
        # are all 0 and DropPath reads the injected rows.
        from seist_tpu.models.common import droppath_mask_injection
        from seist_tpu.train.precision import cast_to_float32
        from seist_tpu.train.step import _forward_loss

        def train_step_inj(state, x, y, uniforms):
            def apply_fn(variables, inputs, **kw):
                with droppath_mask_injection(uniforms) as rec:
                    out = model.apply(variables, inputs, **kw)
                dp_probe["calls"] = rec["i"]  # trace-time capture
                return out

            fwd = _forward_loss(spec, loss_fn, jnp.float32, apply_fn)
            (loss, (_outputs, new_stats)), grads = jax.value_and_grad(
                fwd, has_aux=True
            )(state.params, state.batch_stats, x, y, jax.random.PRNGKey(0))
            state = state.apply_gradients(grads=grads)
            if new_stats is not None:
                state = state.replace(batch_stats=cast_to_float32(new_stats))
            return state, loss

        train_step = jax.jit(train_step_inj)
    else:
        train_step = jax.jit(make_train_step(spec, loss_fn))
    eval_step = jax.jit(make_eval_step(spec, loss_fn))

    is_pmp = mspec["labels"] == "pmp_onehot"
    is_emg = mspec["labels"] == "emg_value"
    is_motion = mspec["labels"] == "clr_pmp_onehot"
    (xt, yt), (xv, yv), val_truth = make_data(cfg)
    # channels-last for this framework (pmp (N,2) / emg (N,1) / motion
    # (N,2,2) labels have no L axis)
    xt, xv = xt.transpose(0, 2, 1), xv.transpose(0, 2, 1)
    if not (is_pmp or is_emg or is_motion):
        yt, yv = yt.transpose(0, 2, 1), yv.transpose(0, 2, 1)
    b = cfg["batch"]

    def to_targets(yb):
        # motion: per-head tuple (clarity, polarity) — a jax pytree the
        # jitted step threads like any other target structure.
        if is_motion:
            a = jnp.asarray(yb)
            return (a[:, 0], a[:, 1])
        return jnp.asarray(yb)
    rng = jax.random.PRNGKey(0)  # drop_rate=0: stream is never consumed
    vmask = jnp.ones((xv.shape[0],), jnp.float32)

    train_losses, val_losses = [], []
    f1_p, f1_s = [], []
    for epoch in range(cfg["epochs"]):
        for s in range(cfg["steps_per_epoch"]):
            xb, yb = xt[s * b : (s + 1) * b], yt[s * b : (s + 1) * b]
            if inject:
                gstep = epoch * cfg["steps_per_epoch"] + s
                state, loss = train_step(
                    state,
                    jnp.asarray(xb),
                    jnp.asarray(yb),
                    jnp.asarray(droppath_uniforms(cfg, gstep)),
                )
            else:
                state, loss, _ = train_step(
                    state, jnp.asarray(xb), to_targets(yb), rng
                )
            train_losses.append(float(loss))
        vloss, vout = eval_step(state, jnp.asarray(xv), to_targets(yv), vmask)
        val_losses.append(float(vloss))
        if is_pmp:
            f1_p.append(class_accuracy(np.asarray(vout), val_truth))
        elif is_motion:
            # polarity head (index 1 of (clarity, polarity))
            f1_p.append(class_accuracy(np.asarray(vout[1]), val_truth))
        elif is_emg:
            f1_p.append(value_mae(np.asarray(vout), val_truth))
        else:
            f1 = pick_f1(np.asarray(vout), *val_truth)
            f1_p.append(f1["p"])
            f1_s.append(f1["s"])
    result = {
        "side": "jax",
        "train_loss_per_step": train_losses,
        "val_loss_per_epoch": val_losses,
        "droppath_calls_per_forward": dp_probe.get("calls", 0),
        "config": cfg,
    }
    if is_pmp or is_motion:
        result["val_acc_per_epoch"] = f1_p
    elif is_emg:
        result["val_mae_per_epoch"] = f1_p
    else:
        result["val_f1_p_per_epoch"] = f1_p
        result["val_f1_s_per_epoch"] = f1_s
    return result


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--side", choices=("torch", "jax"), required=True)
    ap.add_argument("--model", choices=sorted(MODELS), default="phasenet")
    ap.add_argument(
        "--init",
        default=os.path.join(_REPO, "logs", "dyn_init.npz"),
        help="npz path the torch side writes / the jax side reads",
    )
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    os.makedirs(os.path.dirname(os.path.abspath(args.init)), exist_ok=True)

    cfg = lane_cfg(args.model)
    result = (
        run_torch(args.init, cfg)
        if args.side == "torch"
        else run_jax(args.init, cfg)
    )
    line = json.dumps(result)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line)
    print(line)


if __name__ == "__main__":
    main()
