#!/bin/bash
# Round-4 on-silicon evidence runner (VERDICT r3 #1-#4).
#
# Wraps the full round-3 sequence (tools/r3_silicon.sh: Mosaic attn check,
# on-chip golden parity through TPU-default lowerings, bracketed HEAD-vs-old
# A/B, per-lowering isolation, batch scaling, eval matrix, bf16 matrix) and
# appends the round-4 evidence: continuous-record stream throughput and a
# hard assert that the HEAD bench ran the FUSED attention kernel (a Mosaic
# rejection must fail loudly, never silently cost the +105% again).
#
# Usage:  bash tools/r4_silicon.sh            (log: tools/ab_r4.log)
# Skip r3 steps with R3_SKIP="tag1 tag2" as before.
set -u
LOG=/root/repo/tools/ab_r4.log
cd /root/repo

say() { echo "$*" >> "$LOG"; }

run_step() {  # run_step <tag> <timeout_s> [ENV=VAL ...] -- cmd...
  local tag=$1 to=$2; shift 2
  local envs=()
  while [ "$1" != "--" ]; do envs+=("$1"); shift; done
  shift
  say "=== $tag $(date -u +%FT%TZ)"
  if env "${envs[@]:-_=_}" timeout "$to" "$@" >> "$LOG" 2>&1; then
    say "STATUS ok $tag"
  else
    say "STATUS fail $tag rc=$?"
  fi
}

say "r4_silicon start $(date -u +%FT%TZ) HEAD=$(git rev-parse --short HEAD)"

B="BENCH_STEPS=15 BENCH_PROBE_ATTEMPTS=1 BENCH_PROBE_TIMEOUT=120"

# 1. PRIORITY FIRST (the tunnel can die any minute and has been down for
#    two rounds): a fresh headline bench at HEAD + the fused-kernel
#    assert. Everything else is gravy if the window closes after this.
#    The assert is config-matched because the cache is metric-keyed and
#    later sweeps (scale_b*, iso_*, matrix) overwrite the entry.
HEADLINE_START="$(date -u +%FT%TZ)"
run_step headline_for_assert 1200 $B BENCH_REQUIRE_FUSED=1 -- python bench.py
run_step kernel_status_assert 60 R4_START="$HEADLINE_START" -- \
  python - <<'EOF'
import json, os, sys
d = json.load(open("logs/last_bench.json"))
e = d.get("seist_l_dpk_train_throughput") or {}
start = os.environ["R4_START"]  # captured just before the headline bench
print("kernel_status:", json.dumps(e.get("kernel_status")),
      "measured_at:", e.get("measured_at"), "headline started:", start,
      "config:", {k: e.get(k) for k in ("batch", "dtype", "in_samples",
                                        "steps_per_call")})
want = {"batch": 512, "dtype": "bf16", "in_samples": 8192,
        "steps_per_call": 1}
assert all(e.get(k) == v for k, v in want.items()), (
    f"cache entry is not the headline config: {e}"
)
assert e.get("measured_at", "") >= start, (
    "seist_l_dpk cache entry predates the headline bench - no fresh "
    "measurement landed"
)
ks = e.get("kernel_status") or {}
assert ks.get("overall") == "fused", f"fused kernel NOT used: {ks}"
sys.exit(0)
EOF

# 1b. Channel-pad candidate (VERDICT r4 #2 escalation step 1; round 5):
#     lane-multiple out-channels in the composed/fused dense convs,
#     value-identical (tests/test_models.py::TestChannelPad). Runs
#     ADJACENT to the headline it is compared against, before the long
#     r3 sweep — the tunnel can die any minute. Promote the default
#     only on a measured win.
run_step iso_chanpad_128 1200 $B SEIST_CHANNEL_PAD=128 -- python bench.py
run_step iso_chanpad_8 1200 $B SEIST_CHANNEL_PAD=8 -- python bench.py

# 2. The QUICK round-3 evidence at today's HEAD (Mosaic attn check,
#    bracketed HEAD-vs-old A/B, lowering isolation, batch scaling, eval
#    matrix) — the two multi-hour tails (on-chip golden parity, full
#    bf16 matrix) are deferred to the end so a short tunnel window still
#    yields every A/B the lowering decisions need.
R3_SKIP="parity_tpu_lowerings matrix_bf16" bash tools/r3_silicon.sh "$LOG"

# 3. Continuous-record serving throughput (VERDICT r3 #3, deployment half).
#    BENCH_STEPS=3 (bench_stream's own default), not $B's 15: each step
#    annotates a full 600 s record, so 15 would blow the 900 s timeout.
run_step stream_seist_s 900 BENCH_STEPS=3 BENCH_PROBE_ATTEMPTS=1 BENCH_PROBE_TIMEOUT=120 BENCH_MODE=stream BENCH_MODEL=seist_s_dpk -- python bench.py
run_step stream_phasenet 900 BENCH_STEPS=3 BENCH_PROBE_ATTEMPTS=1 BENCH_PROBE_TIMEOUT=120 BENCH_MODE=stream BENCH_MODEL=phasenet -- python bench.py

# 4. Steady-state profile of the flagship step for the MFU breakdown
#    (stems <15% target; VERDICT r3 #2). bf16: the program the MFU claim
#    is measured on.
run_step profile_flagship 1200 _=_ -- python tools/profile_step.py \
  --model-name seist_l_dpk --batch 512 --dtype bf16 --steps 10 \
  --out logs/r4_trace

# 5. The long tails, now that every quick number is on disk: on-chip
#    golden parity through the TPU-default lowerings (~40 min), then the
#    canonical same-session bf16 matrix (up to 3 h).
R3_SKIP="attn_check head_b512_1 old_b512 head_b512_2 iso_default_b256 \
iso_dsconv_paths iso_stem_fused iso_attn_einsum iso_dwconv_grouped \
scale_b128 scale_b256 scale_b512 scale_b1024 eval_seist_l eval_seist_s \
eval_phasenet" bash tools/r3_silicon.sh "$LOG"

say "R4 ALL DONE $(date -u +%FT%TZ)"
