#!/bin/bash
# Round-4 on-silicon evidence runner (VERDICT r3 #1-#4).
#
# Wraps the full round-3 sequence (tools/r3_silicon.sh: Mosaic attn check,
# on-chip golden parity through TPU-default lowerings, bracketed HEAD-vs-old
# A/B, per-lowering isolation, batch scaling, eval matrix, bf16 matrix) and
# appends the round-4 evidence: continuous-record stream throughput and a
# hard assert that the HEAD bench ran the FUSED attention kernel (a Mosaic
# rejection must fail loudly, never silently cost the +105% again).
#
# Usage:  bash tools/r4_silicon.sh            (log: tools/ab_r4.log)
# Skip r3 steps with R3_SKIP="tag1 tag2" as before.
set -u
LOG=/root/repo/tools/ab_r4.log
R4_START="$(date -u +%FT%TZ)"  # freshness floor for the bench asserts
cd /root/repo

say() { echo "$*" >> "$LOG"; }

run_step() {  # run_step <tag> <timeout_s> [ENV=VAL ...] -- cmd...
  local tag=$1 to=$2; shift 2
  local envs=()
  while [ "$1" != "--" ]; do envs+=("$1"); shift; done
  shift
  say "=== $tag $(date -u +%FT%TZ)"
  if env "${envs[@]:-_=_}" timeout "$to" "$@" >> "$LOG" 2>&1; then
    say "STATUS ok $tag"
  else
    say "STATUS fail $tag rc=$?"
  fi
}

say "r4_silicon start $(date -u +%FT%TZ) HEAD=$(git rev-parse --short HEAD)"

# 1. The complete round-3 evidence sequence at today's HEAD.
bash tools/r3_silicon.sh "$LOG"

B="BENCH_STEPS=15 BENCH_PROBE_ATTEMPTS=1 BENCH_PROBE_TIMEOUT=120"

# 2. Kernel-status hard assert on the HEAD train bench (VERDICT r3 #4):
#    the seist_l_dpk cache entry must have been measured DURING this
#    script run (logs/last_bench.json only ever stores fresh successes,
#    so recency — not a 'cached' flag — is the freshness test) and must
#    report overall == "fused".
run_step kernel_status_assert 60 R4_START="$R4_START" -- \
  python - <<'EOF'
import json, os, sys
d = json.load(open("logs/last_bench.json"))
e = d.get("seist_l_dpk_train_throughput") or {}
start = os.environ["R4_START"]  # captured at script start
print("kernel_status:", json.dumps(e.get("kernel_status")),
      "measured_at:", e.get("measured_at"), "run started:", start)
assert e.get("measured_at", "") >= start, (
    "seist_l_dpk cache entry predates this run - the HEAD bench never "
    "landed a fresh measurement"
)
ks = e.get("kernel_status") or {}
assert ks.get("overall") == "fused", f"fused kernel NOT used: {ks}"
sys.exit(0)
EOF

# 3. Continuous-record serving throughput (VERDICT r3 #3, deployment half).
run_step stream_seist_s 900 $B BENCH_MODE=stream BENCH_MODEL=seist_s_dpk -- python bench.py
run_step stream_phasenet 900 $B BENCH_MODE=stream BENCH_MODEL=phasenet -- python bench.py

# 4. Steady-state profile of the flagship step for the MFU breakdown
#    (stems <15% target; VERDICT r3 #2).
run_step profile_flagship 1200 _=_ -- python tools/profile_step.py \
  --model-name seist_l_dpk --batch 512 --steps 10 --out logs/r4_trace

say "R4 ALL DONE $(date -u +%FT%TZ)"
