#!/bin/bash
# Round-3 on-silicon evidence runner (VERDICT r2 #1/#9).
#
# Runs the full silicon-proof sequence for the code at HEAD and writes ONE
# terminal "STATUS ok|fail <tag>" line per step to the log, so a dead
# tunnel or killed watcher can never again produce a log that just trails
# off (round 2 lost its headline numbers that way; bench.py now warns on
# any ab_*.log without a terminal status).
#
# Usage:  bash tools/r3_silicon.sh [LOG]      (default tools/ab_r3.log)
# Steps can be skipped by exporting R3_SKIP="tag1 tag2".
set -u
LOG=${1:-/root/repo/tools/ab_r3.log}
cd /root/repo

say() { echo "$*" >> "$LOG"; }

run_step() {  # run_step <tag> <timeout_s> <workdir> [ENV=VAL ...] -- cmd...
  local tag=$1 to=$2 wd=$3; shift 3
  local envs=()
  while [ "$1" != "--" ]; do envs+=("$1"); shift; done
  shift
  case " ${R3_SKIP:-} " in *" $tag "*) say "STATUS skip $tag"; return;; esac
  say "=== $tag $(date -u +%FT%TZ)"
  if (cd "$wd" && env "${envs[@]:-_=_}" timeout "$to" "$@" >> "$LOG" 2>&1); then
    say "STATUS ok $tag"
  else
    say "STATUS fail $tag rc=$?"
  fi
}

B="BENCH_STEPS=15 BENCH_PROBE_ATTEMPTS=1 BENCH_PROBE_TIMEOUT=120"

say "r3_silicon start $(date -u +%FT%TZ) HEAD=$(git rev-parse --short HEAD)"

# 1. Mosaic compile + numerics of the head-folded attention kernel.
run_step attn_check 900 /root/repo _=_ -- python tools/check_attn_tpu.py

# 1b. Golden parity on the chip, through the TPU-default lowerings
#     (published seist_s_dpk weights vs the torch reference).
run_step parity_tpu_lowerings 2400 /root/repo SEIST_TEST_TPU=1 -- \
  python -m pytest tests/test_golden_parity.py -k tpu_lowerings -q -p no:cacheprovider

# 2-4. HEAD vs pre-2b OLD (74aad2c, worktree /tmp/repo_head), bracketed
#      NEW->OLD->NEW to expose chip drift.
run_step head_b512_1 900 /root/repo $B -- python bench.py
run_step old_b512 900 /tmp/repo_head $B -- python bench.py
run_step head_b512_2 900 /root/repo $B -- python bench.py

# 5. Lowering isolation at b256 (matrix-comparable): each env flips ONE
#    default off to price its contribution.
run_step iso_default_b256 900 /root/repo $B BENCH_BATCH=256 -- python bench.py
run_step iso_dsconv_paths 900 /root/repo $B BENCH_BATCH=256 SEIST_DSCONV_IMPL=paths -- python bench.py
run_step iso_stem_fused 900 /root/repo $B BENCH_BATCH=256 SEIST_STEM_IMPL=fused -- python bench.py
run_step iso_attn_einsum 900 /root/repo $B BENCH_BATCH=256 SEIST_ATTN_IMPL=einsum -- python bench.py
run_step iso_dwconv_grouped 900 /root/repo $B BENCH_BATCH=256 SEIST_DWCONV_IMPL=grouped -- python bench.py

# 6. Single-chip batch-scaling curve (VERDICT #5).
for b in 128 256 512 1024; do
  run_step scale_b$b 900 /root/repo $B BENCH_BATCH=$b -- python bench.py
done

# 7. Eval/inference throughput (VERDICT #3).
run_step eval_seist_l 900 /root/repo $B BENCH_MODE=eval -- python bench.py
run_step eval_seist_s 900 /root/repo $B BENCH_MODE=eval BENCH_MODEL=seist_s_dpk -- python bench.py
run_step eval_phasenet 900 /root/repo $B BENCH_MODE=eval BENCH_MODEL=phasenet -- python bench.py

# 8. Canonical same-session bf16 matrix at the settled defaults.
run_step matrix_bf16 10800 /root/repo BENCH_DTYPE=bf16 -- python tools/bench_matrix.py --steps 15 --out tools/bench_matrix_r3.json

say "ALL DONE $(date -u +%FT%TZ)"
