"""Run the torch reference's test-mode eval on a fixture dataset; print JSON.

Executed as a subprocess by tools/parity_eval.py. Imports the reference
from /root/reference (read-only, never modified) with minimal stubs for its
three dependencies absent from this image:

* ``timm`` — only ``timm.models.layers.DropPath`` is used (ref
  models/seist.py:7); identity in eval mode, so a no-op module suffices.
* ``GPUtil`` — only consulted for an RTX-40xx NCCL workaround (ref
  utils/misc.py:154-164); never reached on CPU.
* ``obspy.signal.trigger.trigger_onset`` — reimplemented here in numpy with
  obspy's documented semantics (onset where charfct > thres1, extending to
  the LAST index where charfct > thres2 of the contiguous above-thres2
  region). The reference calls it with thres1 == thres2
  (ref training/postprocess.py:130), where this reduces to maximal
  above-threshold runs — the same semantics as our
  seist_tpu/ops/postprocess.py:detect_events, so the det-task comparison
  shares trigger semantics by construction.

Output (stdout, last line): JSON {"metrics": {task: {metric: value}},
"loss": float, "ev_ids": [...]}.
"""

from __future__ import annotations

import json
import sys
import types

import numpy as np


def _install_stubs() -> None:
    import torch.nn as nn

    class DropPath(nn.Module):  # identity at eval; p=0 equivalent
        def __init__(self, drop_prob=None):
            super().__init__()
            self.drop_prob = drop_prob

        def forward(self, x):
            return x

    timm = types.ModuleType("timm")
    models_m = types.ModuleType("timm.models")
    layers_m = types.ModuleType("timm.models.layers")
    layers_m.DropPath = DropPath
    timm.models = models_m
    models_m.layers = layers_m
    sys.modules.setdefault("timm", timm)
    sys.modules.setdefault("timm.models", models_m)
    sys.modules.setdefault("timm.models.layers", layers_m)

    gputil = types.ModuleType("GPUtil")
    gputil.getGPUs = lambda: []
    sys.modules.setdefault("GPUtil", gputil)

    def trigger_onset(charfct, thres1, thres2, max_len=9e99,
                      max_len_delete=False):
        charfct = np.asarray(charfct)
        above2 = charfct > thres2
        if not above2.any():
            return []
        # Maximal contiguous above-thres2 regions.
        idx = np.flatnonzero(above2)
        region_start = idx[np.concatenate([[True], np.diff(idx) > 1])]
        region_end = idx[np.concatenate([np.diff(idx) > 1, [True]])]
        picks = []
        for s, e in zip(region_start, region_end):
            seg = np.flatnonzero(charfct[s : e + 1] > thres1)
            if len(seg) == 0:
                continue
            on = int(s + seg[0])
            if e - on > max_len and max_len_delete:
                continue
            picks.append([on, int(min(e, on + max_len))])
        return np.array(picks, dtype=np.int64) if picks else []

    obspy = types.ModuleType("obspy")
    signal = types.ModuleType("obspy.signal")
    trigger = types.ModuleType("obspy.signal.trigger")
    trigger.trigger_onset = trigger_onset
    obspy.signal = signal
    signal.trigger = trigger
    sys.modules.setdefault("obspy", obspy)
    sys.modules.setdefault("obspy.signal", signal)
    sys.modules.setdefault("obspy.signal.trigger", trigger)


def main() -> None:
    _install_stubs()
    sys.path.insert(0, "/root/reference")

    import torch

    from main import get_args  # reference CLI defaults are the contract
    from config import Config
    from models import create_model, load_checkpoint
    from training.preprocess import SeismicDataset
    from training.validate import validate
    from utils import logger, setup_seed

    args = get_args()
    device = torch.device("cpu")
    logger.set_logdir(args.log_base)
    logger.set_logger("global")
    setup_seed(args.seed)

    model_inputs, model_labels, model_tasks = Config.get_model_config_(
        args.model_name, "inputs", "labels", "eval"
    )
    in_channels = Config.get_num_inchannels(model_name=args.model_name)
    test_dataset = SeismicDataset(
        args=args,
        input_names=model_inputs,
        label_names=model_labels,
        task_names=model_tasks,
        mode="test",
    )
    test_loader = torch.utils.data.DataLoader(
        test_dataset,
        batch_size=args.batch_size,
        shuffle=False,
        num_workers=args.workers,
    )

    checkpoint = load_checkpoint(args.checkpoint, device=device)
    model = create_model(
        model_name=args.model_name,
        in_channels=in_channels,
        in_samples=args.in_samples,
    )
    if checkpoint is not None and "model_dict" in checkpoint:
        model.load_state_dict(checkpoint["model_dict"])
    model = model.to(device)

    loss_fn = Config.get_loss(model_name=args.model_name).to(device)

    loss, metrics_merged = validate(
        args, model_tasks, model, loss_fn, test_loader, 0, device,
        testing=True,
    )

    out = {
        "loss": float(loss),
        "metrics": {
            task: {
                name: float(m.get_metric(name)) for name in m.metric_names()
            }
            for task, m in metrics_merged.items()
        },
        "ev_ids": [
            int(v) for v in test_dataset._dataset._meta_data["ev_id"]
        ],
    }
    print("\nPARITY_JSON " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
