"""threadlint rule catalog — concurrency & lifecycle hazards, repo-tuned.

Every rule is a pure function of one
:class:`~tools.jaxlint.engine.ModuleInfo`. Static analysis cannot prove
which thread executes a statement, so — like jaxlint — the catalog trades
soundness for signal and encodes the conventions this repo actually
relies on:

* **Lock discipline is inferred, not declared**: an attribute written
  under ``with self._lock`` anywhere in a class is presumed lock-guarded
  everywhere; ``__init__`` (happens-before publication) and methods named
  ``*_locked`` (the caller-holds-the-lock convention, e.g.
  ``CircuitBreaker._open_locked``) are the two sanctioned unguarded
  contexts.
* **Signal handlers flip flags**: anything beyond assignments,
  ``Event.set()`` and the blessed exit funnels (``io_guard.hard_exit``,
  ``os._exit``, ``os.kill``) is flagged — handlers run at arbitrary
  bytecode boundaries *on the main thread*, so a non-reentrant lock the
  main path also takes (logging's, a trigger's) is a self-deadlock.
* **Exit codes are a contract**: 0/1/2 + ``PREEMPT_EXIT_CODE`` (75, the
  supervisor relaunch signal — docs/FAULT_TOLERANCE.md); ``os._exit``
  lives only inside the ``io_guard.hard_exit`` funnel.
* **request_queue_size is pinned**: socketserver's backlog-5 default
  silently drops SYNs under conn-per-request load (client retransmit
  clusters at 1/3/7/15/31 s while the service idles — the PR 7 root
  cause, encoded here so it can never regress).

False positives are expected to be rare and cheap: suppress inline with
``# threadlint: disable=<rule> -- <rationale>`` or accept into
tools/threadlint_baseline.json. See docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.jaxlint.engine import Finding, ModuleInfo
from tools.jaxlint.rules import Rule

#: threading factory callables whose product is a mutual-exclusion
#: context manager (Condition wraps an RLock; ``with self._cond`` guards
#: exactly like ``with self._lock``).
_LOCKLIKE = ("Lock", "RLock", "Condition")
_EVENTLIKE = ("Event", "Condition")

#: container mutations that count as writes for lock-discipline purposes
_MUTATORS = frozenset(
    (
        "append",
        "appendleft",
        "extend",
        "insert",
        "pop",
        "popleft",
        "popitem",
        "remove",
        "discard",
        "clear",
        "add",
        "update",
        "setdefault",
        "sort",
    )
)

# Construction contexts: the object is not yet published to other
# threads, so unguarded writes are happens-before-safe (__setstate__ runs
# on a freshly unpickled instance, same argument).
_INIT_METHODS = frozenset(
    ("__init__", "__new__", "__post_init__", "__setstate__")
)


def _is_threading_factory(
    info: ModuleInfo, node: ast.AST, kinds: Tuple[str, ...]
) -> bool:
    """``threading.Lock()`` / bare ``Lock()`` (from-import) for ``kinds``."""
    if not isinstance(node, ast.Call):
        return False
    name = info.dotted_name(node.func)
    return name in kinds or any(name == f"threading.{k}" for k in kinds)


def _assign_value_targets(node: ast.AST):
    """``(value, targets)`` for plain and annotated assignments —
    ``self._lock: threading.Lock = threading.Lock()`` must count exactly
    like the unannotated form, or a typing-hygiene edit silently turns a
    rule off."""
    if isinstance(node, ast.Assign):
        return node.value, node.targets
    if isinstance(node, ast.AnnAssign) and node.value is not None:
        return node.value, [node.target]
    return None, ()


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _enclosing_class(
    info: ModuleInfo, node: ast.AST
) -> Optional[ast.ClassDef]:
    for a in info.ancestors(node):
        if isinstance(a, ast.ClassDef):
            return a
    return None


def _enclosing_method(info: ModuleInfo, node: ast.AST):
    """The function whose body directly contains ``node`` (first function
    ancestor)."""
    return info.enclosing_function(node)


def _held_locks(
    info: ModuleInfo, node: ast.AST, lock_attrs: Set[str]
) -> Set[str]:
    """Lock attrs held via ``with self.<lock>:`` around ``node``, within
    the same function (a nested def's body does not run under an outer
    with)."""
    held: Set[str] = set()
    for a in info.ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        if isinstance(a, ast.With):
            for item in a.items:
                attr = _self_attr(item.context_expr)
                if attr in lock_attrs:
                    held.add(attr)
    return held


class UnguardedAttr(Rule):
    name = "unguarded-attr"
    summary = (
        "attribute written under `with self._lock` elsewhere in the class "
        "is read/written on an unguarded path"
    )
    hint = (
        "take the lock (or snapshot under it), move the access into "
        "__init__, or rename the method *_locked if every caller already "
        "holds the lock"
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        for cls in ast.walk(info.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(info, cls)

    def _check_class(
        self, info: ModuleInfo, cls: ast.ClassDef
    ) -> Iterator[Finding]:
        lock_attrs: Set[str] = set()
        for node in ast.walk(cls):
            value, targets = _assign_value_targets(node)
            if value is not None and _is_threading_factory(
                info, value, _LOCKLIKE
            ):
                for t in targets:
                    attr = _self_attr(t)
                    if attr:
                        lock_attrs.add(attr)
        if not lock_attrs:
            return

        # (attr, node, is_write, held, method) for every self.<attr> access
        accesses: List[Tuple[str, ast.AST, bool, Set[str], Optional[str]]] = []
        for node in ast.walk(cls):
            if _enclosing_class(info, node) is not cls:
                continue  # a nested class owns its own discipline
            attr = _self_attr(node)
            if attr is None or attr in lock_attrs:
                continue
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            # self.<attr>.append(...) and friends mutate the container
            parent = info.parents.get(node)
            if (
                isinstance(parent, ast.Attribute)
                and parent.attr in _MUTATORS
                and isinstance(info.parents.get(parent), ast.Call)
                and info.parents[parent].func is parent
            ):
                is_write = True
            # self.<attr>[k] = v / del self.<attr>[k]
            if isinstance(parent, ast.Subscript) and isinstance(
                parent.ctx, (ast.Store, ast.Del)
            ):
                is_write = True
            method = _enclosing_method(info, node)
            accesses.append(
                (
                    attr,
                    node,
                    is_write,
                    _held_locks(info, node, lock_attrs),
                    method.name if method is not None else None,
                )
            )

        guarded = {
            attr for attr, _, is_write, held, _ in accesses if is_write and held
        }
        for attr, node, is_write, held, method in accesses:
            if attr not in guarded:
                continue
            # Holding a lock only counts if it is one of the locks the
            # attribute is actually written under — a DIFFERENT lock is
            # still a race (the two-lock wrong-lock shape).
            if held & guarded_locks(accesses, attr):
                continue
            if method is None or method in _INIT_METHODS:
                continue  # construction happens-before publication
            if method.endswith("_locked"):
                continue  # caller-holds-the-lock convention
            verb = "written" if is_write else "read"
            yield self.finding(
                info,
                node,
                f"self.{attr} is {verb} without the lock here but written "
                f"under `with self.{sorted(guarded_locks(accesses, attr))[0]}`"
                f" elsewhere in {cls.name} — a torn read/lost update race",
            )


def guarded_locks(accesses, attr: str) -> Set[str]:
    locks: Set[str] = set()
    for a, _, is_write, held, _ in accesses:
        if a == attr and is_write and held:
            locks |= held
    return locks or {"_lock"}


class SignalHandlerUnsafe(Rule):
    name = "signal-handler-unsafe"
    summary = (
        "signal handler does more than flip a flag / funnel to a blessed "
        "exit"
    )
    hint = (
        "handlers run at arbitrary bytecode boundaries on the main "
        "thread: set an Event/flag and act at a poll point, or funnel to "
        "io_guard.hard_exit / os._exit; anything that allocates, logs, or "
        "takes a lock the main path also takes can self-deadlock"
    )

    #: call targets a handler may invoke directly
    _ALLOWED = frozenset(
        ("os._exit", "os.kill", "hard_exit", "signal.signal", "getattr")
    )
    _ALLOWED_SUFFIX = (".set", ".hard_exit")

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        # One handler can serve several signals (SIGTERM+SIGINT is the
        # repo idiom) — analyze each handler body exactly once.
        seen: Set[int] = set()
        for node in ast.walk(info.tree):
            if not (
                isinstance(node, ast.Call)
                and info.dotted_name(node.func) == "signal.signal"
                and len(node.args) == 2
            ):
                continue
            handler = node.args[1]
            bodies: List[ast.AST] = []
            if isinstance(handler, ast.Lambda):
                bodies.append(handler.body)
            elif isinstance(handler, ast.Name):
                bodies.extend(info.defs_by_name.get(handler.id, ()))
            for body in bodies:
                if id(body) in seen:
                    continue
                seen.add(id(body))
                yield from self._check_handler(info, body)

    def _check_handler(
        self, info: ModuleInfo, handler: ast.AST
    ) -> Iterator[Finding]:
        # No self-skip here: for a lambda handler, ``handler`` IS the
        # offending Call expression itself.
        for node in ast.walk(handler):
            if not isinstance(node, ast.Call):
                continue
            name = info.dotted_name(node.func)
            if name in self._ALLOWED:
                continue
            if any(name.endswith(s) for s in self._ALLOWED_SUFFIX):
                continue
            yield self.finding(
                info,
                node,
                f"signal handler calls {name or 'an expression'}() — "
                "not async-signal-safe (allocation / logging locks / "
                "locks shared with the interrupted main thread)",
            )


class ThreadNoJoin(Rule):
    name = "thread-no-join"
    summary = "non-daemon thread with no join() on any shutdown path"
    hint = (
        "pass daemon=True (if the thread may be abandoned at exit) or "
        "join it on every shutdown path — otherwise threading._shutdown "
        "blocks interpreter exit forever on a wedged thread"
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        joined = self._joined_names(info)
        for node in ast.walk(info.tree):
            if not (
                isinstance(node, ast.Call)
                and info.dotted_name(node.func)
                in ("threading.Thread", "Thread")
            ):
                continue
            daemon = next(
                (kw.value for kw in node.keywords if kw.arg == "daemon"),
                None,
            )
            if (
                isinstance(daemon, ast.Constant)
                and daemon.value is True
            ):
                continue
            bound = self._binding(info, node)
            if bound is not None and bound in joined:
                continue
            yield self.finding(
                info,
                node,
                "non-daemon Thread is never join()ed in this module — a "
                "wedged run loop makes clean interpreter exit impossible",
            )

    @staticmethod
    def _binding(info: ModuleInfo, call: ast.Call) -> Optional[str]:
        """'x' or 'self.y' when the Thread lands in a simple (plain or
        annotated) binding."""
        parent = info.parents.get(call)
        value, targets = _assign_value_targets(parent)
        if value is call and len(targets) == 1:
            t = targets[0]
            if isinstance(t, ast.Name):
                return t.id
            attr = _self_attr(t)
            if attr:
                return f"self.{attr}"
        return None

    @staticmethod
    def _joined_names(info: ModuleInfo) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(info.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
            ):
                v = node.func.value
                if isinstance(v, ast.Name):
                    out.add(v.id)
                else:
                    attr = _self_attr(v)
                    if attr:
                        out.add(f"self.{attr}")
        return out


class ThreadTargetRaises(Rule):
    name = "thread-target-raises"
    summary = (
        "Thread target can raise past its top frame (silent thread death)"
    )
    hint = (
        "wrap the target's whole body in try/except that records the "
        "death (log, fail-fast flag, poison result) — an uncaught "
        "exception only prints to stderr and the thread vanishes (the "
        "PR 2 batcher-flush bug class)"
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if not (
                isinstance(node, ast.Call)
                and info.dotted_name(node.func)
                in ("threading.Thread", "Thread")
            ):
                continue
            target = next(
                (kw.value for kw in node.keywords if kw.arg == "target"),
                None,
            )
            name: Optional[str] = None
            if isinstance(target, ast.Name):
                name = target.id
            else:
                attr = _self_attr(target) if target is not None else None
                if attr:
                    name = attr
            if name is None:
                continue  # unresolvable (bound method of another object)
            defs = info.defs_by_name.get(name, ())
            if not defs:
                continue
            if all(self._shielded(d) for d in defs):
                continue
            yield self.finding(
                info,
                node,
                f"thread target '{name}' has top-level statements outside "
                "any try/except — an exception there kills the thread "
                "silently",
            )

    @staticmethod
    def _shielded(fn: ast.FunctionDef) -> bool:
        body = list(fn.body)
        if (
            body
            and isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and isinstance(body[0].value.value, str)
        ):
            body = body[1:]  # docstring
        return bool(body) and all(
            isinstance(stmt, ast.Try) and stmt.handlers for stmt in body
        )


class WaitNoTimeout(Rule):
    name = "wait-no-timeout"
    summary = "Event/Condition wait() without a timeout"
    hint = (
        "wait with a timeout in a loop (re-checking the predicate): a "
        "lost set()/notify() — dead producer, shutdown race — otherwise "
        "parks the thread forever"
    )

    @staticmethod
    def _untimed(call: ast.Call) -> bool:
        """``wait()``, ``wait(None)`` and ``wait(timeout=None)`` are all
        the same forever-park."""
        if not call.args and not call.keywords:
            return True
        timeout: Optional[ast.AST] = None
        if call.args:
            timeout = call.args[0]
        for kw in call.keywords:
            if kw.arg == "timeout":
                timeout = kw.value
        return isinstance(timeout, ast.Constant) and timeout.value is None

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        event_attrs: Set[str] = set()
        event_names: Set[str] = set()
        for node in ast.walk(info.tree):
            value, targets = _assign_value_targets(node)
            if value is not None and _is_threading_factory(
                info, value, _EVENTLIKE
            ):
                for t in targets:
                    attr = _self_attr(t)
                    if attr:
                        event_attrs.add(attr)
                    elif isinstance(t, ast.Name):
                        event_names.add(t.id)
        if not (event_attrs or event_names):
            return
        for node in ast.walk(info.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "wait"
                and self._untimed(node)
            ):
                continue
            v = node.func.value
            attr = _self_attr(v)
            known = (attr in event_attrs) or (
                isinstance(v, ast.Name) and v.id in event_names
            )
            if known:
                yield self.finding(
                    info,
                    node,
                    "untimed wait(): a lost wakeup parks this thread "
                    "forever with no watchdog signal",
                )


class HttpServerBacklog(Rule):
    name = "http-server-backlog"
    summary = (
        "socketserver subclass without a pinned request_queue_size"
    )
    hint = (
        "set `request_queue_size = 1024` in the class body: socketserver "
        "defaults the listen backlog to 5, and under conn-per-request "
        "bursts dropped SYNs retransmit at 1/3/7/15/31 s — client p99 "
        "clusters while the service idles (the PR 7 root cause)"
    )

    _SERVER_BASES = frozenset(
        (
            "HTTPServer",
            "ThreadingHTTPServer",
            "TCPServer",
            "ThreadingTCPServer",
            "ForkingTCPServer",
            "UnixStreamServer",
        )
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            bases = {
                info.dotted_name(b).split(".")[-1] for b in node.bases
            }
            if not (bases & self._SERVER_BASES):
                continue
            pinned = any(
                isinstance(stmt, (ast.Assign, ast.AnnAssign))
                # a bare `request_queue_size: int` annotation assigns
                # nothing — the backlog silently stays 5
                and (isinstance(stmt, ast.Assign) or stmt.value is not None)
                and any(
                    isinstance(t, ast.Name) and t.id == "request_queue_size"
                    for t in (
                        stmt.targets
                        if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                )
                for stmt in node.body
            )
            if not pinned:
                yield self.finding(
                    info,
                    node,
                    f"{node.name} subclasses a socketserver server without "
                    "pinning request_queue_size (backlog defaults to 5: "
                    "SYN drops under accept bursts)",
                )


class ExitOutsideFunnel(Rule):
    name = "exit-outside-funnel"
    summary = (
        "sys.exit/os._exit outside the blessed funnels, or a "
        "non-contract exit code"
    )
    hint = (
        "route hard deaths through io_guard.hard_exit (flushes logs, "
        "dumps the flight recorder); exit codes are a supervisor "
        "contract — 0 ok, 1 failure, 2 usage, and the named "
        "PREEMPT_EXIT_CODE constant (75, never the bare literal) for a "
        "managed preempt (docs/FAULT_TOLERANCE.md)"
    )

    _CONTRACT_CODES = frozenset((0, 1, 2))
    _CONTRACT_NAMES = frozenset(("PREEMPT_EXIT_CODE",))

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            name = info.dotted_name(node.func)
            if name == "os._exit":
                fn = info.enclosing_function(node)
                if fn is not None and fn.name == "hard_exit":
                    continue  # THE funnel (data/io_guard.py)
                yield self.finding(
                    info,
                    node,
                    "os._exit outside the io_guard.hard_exit funnel skips "
                    "log flush + flight-recorder dump",
                )
            elif name == "sys.exit":
                if not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Call):
                    continue  # sys.exit(main()) trampoline
                # -1 parses as UnaryOp(USub, Constant(1)) — fold it so
                # the bug-shaped sys.exit(-1) (process rc 255) is judged
                # as the literal it reads as.
                if (
                    isinstance(arg, ast.UnaryOp)
                    and isinstance(arg.op, ast.USub)
                    and isinstance(arg.operand, ast.Constant)
                    and isinstance(arg.operand.value, (int, float))
                    and not isinstance(arg.operand.value, bool)
                ):
                    arg = ast.copy_location(
                        ast.Constant(value=-arg.operand.value), arg
                    )
                if isinstance(arg, ast.Constant):
                    if isinstance(arg.value, str):
                        # sys.exit("message") is the stdlib-blessed
                        # print-to-stderr-and-exit-1 idiom: contract code 1
                        continue
                    if (
                        not isinstance(arg.value, bool)
                        and arg.value in self._CONTRACT_CODES
                    ):
                        # bools are ints (True == 1) but sys.exit(True) is
                        # a bug-shaped exit code, not the contract
                        continue
                    yield self.finding(
                        info,
                        node,
                        f"exit code {arg.value!r} is not a documented "
                        "contract value (0/1/2/PREEMPT_EXIT_CODE) — "
                        "supervisors will misclassify this death",
                    )
                    continue
                terminal = info.dotted_name(arg).split(".")[-1]
                if terminal and terminal not in self._CONTRACT_NAMES:
                    # A bare variable is unprovable; only flag names that
                    # LOOK like a constant but aren't the contract one.
                    if terminal.isupper():
                        yield self.finding(
                            info,
                            node,
                            f"exit code constant {terminal} is not "
                            "PREEMPT_EXIT_CODE — document it in the exit "
                            "contract or reuse 0/1/2/PREEMPT_EXIT_CODE",
                        )


RULES: Tuple[Rule, ...] = (
    UnguardedAttr(),
    SignalHandlerUnsafe(),
    ThreadNoJoin(),
    ThreadTargetRaises(),
    WaitNoTimeout(),
    HttpServerBacklog(),
    ExitOutsideFunnel(),
)

RULES_BY_NAME: Dict[str, Rule] = {r.name: r for r in RULES}
