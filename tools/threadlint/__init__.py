"""threadlint — host-side concurrency & process-lifecycle static analysis.

The jaxlint sibling (same engine, same suppression/baseline machinery,
``# threadlint: disable=<rule> -- <rationale>`` comments) aimed at the
bug class every hard failure of PRs 4-7 belonged to: unguarded shared
state, async-unsafe signal handlers, silently-dying threads, socketserver
backlog drops, and undocumented exit codes. ``tools/threadlint/runtime.py``
adds the opt-in LockGraph lane (lock-acquisition-order cycles + locks held
across blocking calls) that rides the smoke/chaos test lanes via
``pytest --lock-graph``. See docs/STATIC_ANALYSIS.md "Concurrency
analysis".
"""

from tools.threadlint.engine import lint_paths, lint_source  # noqa: F401
