"""threadlint runtime audit lane: the lock-order graph.

Static rules see lock *discipline*; this module sees lock *ordering* —
the property whose violation is a deadlock, and which no single-file AST
pass can check (the two locks of a deadlock usually live in different
modules). :class:`LockGraph` instruments ``threading.Lock`` /
``threading.RLock`` (and, transitively, ``threading.Condition``, which
builds on them) while active:

* every lock is identified by its **creation site** (``file:line`` of the
  factory call), so the per-instance locks of N replicas/batchers
  collapse onto one graph node — the meaningful unit for ordering;
* acquiring lock B while holding lock A adds the edge ``A -> B`` (first
  observation keeps a sample thread + stack);
* a **cycle** in the resulting directed graph is a potential deadlock:
  two threads walking the cycle from different entry points can block
  each other forever even if the test run happened to interleave safely;
* holding any instrumented lock across a known **blocking call**
  (``http.client`` response reads; ``jax.device_get`` when jax is
  loaded) is recorded as a violation — the serve plane's rule that
  forwards/HTTP happen outside locks, enforced at runtime.

Mirrors jaxlint's CompileBudget in shape: a context manager plus a
conftest fixture, ridden over the whole smoke lane with
``pytest -m smoke --lock-graph`` (``make lockgraph``). Only locks
*created* while the graph is active are instrumented — module-level
singletons born at import time are invisible, which is fine for the test
lanes (every serve/obs object under test is constructed inside a test).

Overhead: one dict operation per acquire/release against an internal
(uninstrumented) lock — measured single-digit microseconds per pair
(tests/test_threadlint.py pins the bound), invisible next to a
millisecond-scale model forward.
"""

from __future__ import annotations

import sys
import threading
import traceback
from typing import Any, Dict, List, Optional, Set, Tuple

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

#: the single active graph (nesting is a usage error: two graphs would
#: fight over the threading factory patch)
_ACTIVE: Optional["LockGraph"] = None


def _site(depth: int = 2) -> Optional[str]:
    """``file:line`` of the nearest non-threading.py frame above the
    factory call — for a direct ``threading.Lock()`` that is the call
    itself; for the RLock inside ``threading.Condition()`` it is the
    Condition() call site (the name a human recognizes). None when the
    whole visible stack is threading internals (interpreter plumbing —
    not part of any user ordering discipline)."""
    threading_file = threading.__file__
    f: Any = sys._getframe(depth)
    for _ in range(12):
        if f is None:
            return None
        if f.f_code.co_filename != threading_file:
            break
        f = f.f_back
    else:
        return None
    if f is None:
        return None
    name = f.f_code.co_filename
    for marker in ("/seist_tpu/", "/tools/", "/tests/"):
        i = name.rfind(marker)
        if i >= 0:
            name = name[i + 1 :]
            break
    return f"{name}:{f.f_lineno}"


class _InstrumentedLock:
    """Wraps one real primitive Lock; reports acquisition order to a
    graph.

    The wrapper outlives its creation graph's window (objects created
    during a test keep their locks afterwards). While the creation graph
    is live (active or paused by a nested graph) it gets the reports;
    once it is done for good, the lock RE-ATTACHES to whatever graph is
    currently active — a process-wide singleton born in test 1's window
    stays auditable for the rest of a ``--lock-graph`` lane instead of
    reporting into a dead graph. With no graph live at all, acquire and
    release degrade to plain delegation.

    Deliberately does NOT expose the RLock-only private protocol
    (``_release_save``/...): ``threading.Condition`` probes for it with
    getattr and must fall back to its plain-lock paths here, exactly as
    with an uninstrumented Lock.
    """

    def __init__(self, real: Any, site: str, graph: "LockGraph"):
        self._real = real
        self._site = site
        self._graph = graph

    def _target(self) -> Optional["LockGraph"]:
        g = self._graph
        if g.active or g._paused:
            return g
        return _ACTIVE

    # -- the Lock protocol --------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._real.acquire(blocking, timeout)
        if got:
            g = self._target()
            if g is not None:
                g._acquired(self)
        return got

    def release(self) -> None:
        g = self._target()
        if g is not None:
            g._released(self)
        self._real.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._real.locked()

    def __repr__(self) -> str:
        return f"<threadlint lock {self._site} wrapping {self._real!r}>"


class _InstrumentedRLock(_InstrumentedLock):
    # The private protocol threading.Condition prefers on a reentrant
    # lock: wait() must FULLY release the RLock (all recursion levels)
    # and restore the count afterwards. The graph's held bookkeeping
    # mirrors the full release, or a waiting thread would look like it
    # holds the lock for the whole wait — and carries the recursion
    # depth through the opaque state, or a depth-2 holder would come
    # back as depth-1 and the entry would pop while the lock is still
    # really held (missing edges/violations in the outer with-block).
    def _release_save(self):
        state = self._real._release_save()
        g = self._target()
        depth = g._released(self, fully=True) if g is not None else None
        return (state, depth)

    def _acquire_restore(self, state) -> None:
        real_state, depth = state
        self._real._acquire_restore(real_state)
        g = self._target()
        if g is not None:
            g._acquired(self, depth=depth)

    def _is_owned(self) -> bool:
        return self._real._is_owned()


class LockGraph:
    """Cross-thread lock-acquisition-order recorder; see module docstring.

    >>> with LockGraph() as graph:
    ...     run_the_workload()
    >>> graph.assert_clean()   # no order cycles, no lock held across I/O
    """

    #: dotted names patched as known blocking calls while active
    BLOCKING_PATCHES = (
        ("http.client", "HTTPConnection", "getresponse"),
    )

    def __init__(self) -> None:
        self._mu = _REAL_LOCK()  # internal; never instrumented
        self.active = False
        # paused = a nested graph is active on top of this one: edge and
        # violation RECORDING stops, but held-stack bookkeeping must keep
        # running — this graph's locks are still acquired/released inside
        # the inner window, and a stale entry would produce phantom edges
        # and false HELD-ACROSS-BLOCKING violations after resume.
        self._paused = False
        # held lock stacks per thread: ident -> [(site, lock_id, depth)]
        self._held: Dict[int, List[List[Any]]] = {}
        # (from_site, to_site) -> {"count": n, "thread": ..., "stack": ...}
        self.edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.sites: Set[str] = set()
        #: locks held across a blocking call: list of dicts
        self.violations: List[Dict[str, Any]] = []
        self._saved: List[Tuple[Any, str, Any]] = []
        self._prev: Optional["LockGraph"] = None
        self._saved_factories: Optional[Tuple[Any, Any]] = None

    # ------------------------------------------------------------ patching
    def __enter__(self) -> "LockGraph":
        global _ACTIVE
        # Graphs nest LIFO (an explicit LockGraph test inside a
        # --lock-graph lane): the outer graph pauses — its locks stop
        # recording edges/violations, though held bookkeeping continues —
        # and resumes when the inner one exits.
        self._prev = _ACTIVE
        if self._prev is not None:
            self._prev.active = False
            self._prev._paused = True
        self._saved_factories = (threading.Lock, threading.RLock)
        _ACTIVE = self
        self.active = True
        graph = self

        def make_lock():
            site = _site()
            if site is None:  # pure threading-internal plumbing
                return _REAL_LOCK()
            return _InstrumentedLock(_REAL_LOCK(), site, graph)

        def make_rlock():
            site = _site()
            if site is None:
                return _REAL_RLOCK()
            return _InstrumentedRLock(_REAL_RLOCK(), site, graph)

        threading.Lock = make_lock  # type: ignore[assignment]
        threading.RLock = make_rlock  # type: ignore[assignment]
        for mod_name, cls_name, fn_name in self.BLOCKING_PATCHES:
            self._patch_blocking(mod_name, cls_name, fn_name)
        if "jax" in sys.modules:  # never IMPORT jax for the router's sake
            self._patch_blocking_fn(sys.modules["jax"], "device_get")
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        self.active = False
        self._paused = False  # done for good, not paused
        _ACTIVE = self._prev
        if self._prev is not None:
            self._prev._paused = False
            self._prev.active = True
        self._prev = None
        lock_f, rlock_f = self._saved_factories or (_REAL_LOCK, _REAL_RLOCK)
        threading.Lock = lock_f  # type: ignore[assignment]
        threading.RLock = rlock_f  # type: ignore[assignment]
        self._saved_factories = None
        for owner, name, orig in self._saved:
            setattr(owner, name, orig)
        self._saved.clear()

    def _patch_blocking(
        self, mod_name: str, cls_name: str, fn_name: str
    ) -> None:
        mod = sys.modules.get(mod_name)
        if mod is None:
            return
        self._patch_blocking_fn(getattr(mod, cls_name), fn_name)

    def _patch_blocking_fn(self, owner: Any, fn_name: str) -> None:
        orig = getattr(owner, fn_name, None)
        if orig is None:
            return
        graph = self
        label = f"{getattr(owner, '__name__', owner)}.{fn_name}"

        def wrapped(*args, **kw):
            graph.check_blocking(label)
            return orig(*args, **kw)

        self._saved.append((owner, fn_name, orig))
        setattr(owner, fn_name, wrapped)

    # ----------------------------------------------------------- recording
    def _acquired(
        self, lock: _InstrumentedLock, depth: Optional[int] = None
    ) -> None:
        """Record an acquisition. ``depth`` (from an RLock
        ``_acquire_restore``) seeds the entry's recursion count; a plain
        acquire counts 1. While paused, only the held bookkeeping runs —
        no new edges are recorded."""
        if not (self.active or self._paused):
            return
        ident = threading.get_ident()
        with self._mu:
            stack = self._held.setdefault(ident, [])
            for entry in stack:
                if entry[1] == id(lock):  # reentrant re-acquire
                    entry[2] += depth or 1
                    return
            new_edges = []
            if self.active:
                for held_site, _, _ in stack:
                    if held_site != lock._site:
                        key = (held_site, lock._site)
                        e = self.edges.get(key)
                        if e is None:
                            new_edges.append(key)
                        else:
                            e["count"] += 1
                self.sites.add(lock._site)
            stack.append([lock._site, id(lock), depth or 1])
        # Stack capture outside the mutex, first observation only (keeps
        # the steady-state cost to dict ops).
        for key in new_edges:
            sample = {
                "count": 1,
                "thread": threading.current_thread().name,
                "stack": "".join(traceback.format_stack(limit=6)[:-1]),
            }
            with self._mu:
                self.edges.setdefault(key, sample)

    def _released(
        self, lock: _InstrumentedLock, fully: bool = False
    ) -> Optional[int]:
        """Record a release; with ``fully`` pop the whole entry and
        return the recursion depth it held (so an RLock
        ``_release_save``/``_acquire_restore`` round-trip preserves it).
        Runs while paused too — see :meth:`_acquired`."""
        if not (self.active or self._paused):
            return None
        ident = threading.get_ident()
        with self._mu:
            # Fast path: the releaser is the holder (>99% of releases).
            # But a primitive Lock may legally be released by ANOTHER
            # thread (the one-shot handoff idiom) — on a miss, fall back
            # to scanning the other threads' stacks, or the entry would
            # sit stale and poison that thread's ordering edges /
            # blocking checks for the rest of the run.
            own = self._held.get(ident)
            hit = self._pop_entry(own, lock, fully) if own else None
            if hit is None:
                for i, stack in self._held.items():
                    if i == ident:
                        continue
                    hit = self._pop_entry(stack, lock, fully)
                    if hit is not None:
                        break
            if hit is not None:
                return hit if fully else None
        return None

    @staticmethod
    def _pop_entry(
        stack: List[List[Any]], lock: "_InstrumentedLock", fully: bool
    ) -> Optional[int]:
        """Decrement (or with ``fully`` remove) the stack's entry for
        ``lock``; return the pre-release depth, None when absent."""
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][1] == id(lock):
                depth = stack[i][2]
                stack[i][2] = 0 if fully else stack[i][2] - 1
                if stack[i][2] <= 0:
                    stack.pop(i)
                return depth
        return None

    def check_blocking(self, label: str) -> None:
        """Record a violation if the calling thread holds any instrumented
        lock right now. Public so subsystems can declare their own
        blocking boundaries (e.g. a batcher's model forward)."""
        if not self.active:
            return
        ident = threading.get_ident()
        with self._mu:
            held = [s for s, _, _ in self._held.get(ident, [])]
        if held:
            self.violations.append(
                {
                    "blocking": label,
                    "held": held,
                    "thread": threading.current_thread().name,
                    "stack": "".join(traceback.format_stack(limit=8)[:-2]),
                }
            )

    # ------------------------------------------------------------- queries
    def cycles(self) -> List[List[str]]:
        """Site cycles in the acquisition-order graph (each reported once,
        rotated to start at its smallest site)."""
        adj: Dict[str, Set[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, set()).add(b)
        out: List[List[str]] = []
        seen: Set[Tuple[str, ...]] = set()

        def dfs(start: str, node: str, path: List[str]) -> None:
            for nxt in adj.get(node, ()):
                if nxt == start:
                    i = path.index(min(path))
                    canon = tuple(path[i:] + path[:i])
                    if canon not in seen:
                        seen.add(canon)
                        out.append(list(canon))
                elif nxt not in path and nxt > start:
                    # only walk nodes > start: each cycle is found from
                    # its smallest member exactly once
                    dfs(start, nxt, path + [nxt])

        for site in sorted(adj):
            dfs(site, site, [site])
        return out

    def report(self) -> str:
        lines = [
            f"lock graph: {len(self.sites)} site(s), "
            f"{len(self.edges)} order edge(s)"
        ]
        for cyc in self.cycles():
            lines.append(
                "  CYCLE (potential deadlock): " + " -> ".join(cyc + cyc[:1])
            )
            for a, b in zip(cyc, cyc[1:] + cyc[:1]):
                e = self.edges.get((a, b))
                if e:
                    lines.append(
                        f"    {a} -> {b} x{e['count']} "
                        f"(thread {e['thread']})"
                    )
        for v in self.violations:
            lines.append(
                f"  HELD-ACROSS-BLOCKING: {v['held']} held during "
                f"{v['blocking']} (thread {v['thread']})"
            )
        return "\n".join(lines)

    def assert_clean(self) -> None:
        cycles = self.cycles()
        if cycles or self.violations:
            raise AssertionError(
                "lock-order audit failed:\n" + self.report()
                + "\n(fix the ordering, or release the lock before the "
                "blocking call — see docs/STATIC_ANALYSIS.md)"
            )


def active_graph() -> Optional[LockGraph]:
    """The currently active LockGraph (None outside a --lock-graph run) —
    the hook for subsystems declaring custom blocking boundaries."""
    return _ACTIVE
