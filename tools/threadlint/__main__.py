"""threadlint CLI — the jaxlint frontend bound to the concurrency catalog.

    python -m tools.threadlint seist_tpu tools           # gate vs baseline
    python -m tools.threadlint seist_tpu --no-baseline   # everything
    python -m tools.threadlint --list-rules

Exit codes: 0 clean (vs baseline), 1 new findings, 2 usage/parse error.
"""

from __future__ import annotations

import os
import sys

from tools.jaxlint.__main__ import run
from tools.threadlint.rules import RULES, RULES_BY_NAME

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_DEFAULT_BASELINE = os.path.join(
    _REPO_ROOT, "tools", "threadlint_baseline.json"
)


def main(argv=None) -> int:
    return run(
        argv,
        tag="threadlint",
        catalog=RULES,
        rules_by_name=RULES_BY_NAME,
        default_baseline=_DEFAULT_BASELINE,
        docs="docs/STATIC_ANALYSIS.md §Concurrency analysis",
        example_paths="seist_tpu tools",
    )


if __name__ == "__main__":
    sys.exit(main())
