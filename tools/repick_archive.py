"""Re-pick a packed waveform archive as a map-reduce batch job.

The ROADMAP's planetary-archive workload: when a model improves,
observatories re-process decades x thousands of stations — billions of
windows, purely throughput-bound. This tool drives the
seist_tpu/batch engine (docs/DATA.md "Batch re-picking"):

* **map** — the archive's packed shards become deterministic work units;
  each worker owns ``units[worker_index::num_workers]`` and runs a
  straight-line device feed (double-buffered ``PackedRawStore`` fills
  against ONE AOT multi-batch executable — trunk-once head fan-out for
  task groups), committing catalog segments atomically every
  ``--commit-every`` device calls;
* **resume** — a SIGKILL'd worker restarts at its exact segment offset
  (committed segments are the durable state; ``worker_<i>.json`` is the
  advisory progress record); SIGTERM drains the current segment and
  exits 75 (the PR 2 preemption contract);
* **reduce** — ``--merge-only`` (or the driver, after its workers join)
  concatenates segments in (unit, segment) order into ``catalog.jsonl``
  + ``catalog_meta.json`` (written LAST). The merged catalog is
  byte-identical across worker counts and kill/resume histories —
  ``make repick-smoke`` pins it.

    # serial (one process does everything)
    python -m tools.repick_archive --archive /data/packed \
        --model phasenet=CKPT --out /data/catalog --batch-size 64

    # 4-worker driver (spawns workers, then merges)
    python -m tools.repick_archive --archive /data/packed \
        --model-group seist_s=dpk:CKPT,emg:CKPT2 --out /data/catalog \
        --workers 4 --variant bf16

Prints ONE JSON verdict line per role (worker / driver / merge).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def get_args(argv=None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(
        prog="python -m tools.repick_archive", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--archive", required=True,
                    help="packed archive dir (tools/pack_dataset.py output)")
    ap.add_argument("--out", required=True, help="catalog output dir")
    ap.add_argument("--model", default="", metavar="NAME[=CKPT]",
                    help="single-task model (fresh-init weights without "
                    "=CKPT — smoke/testing)")
    ap.add_argument("--model-group", default="",
                    metavar="PREFIX=TASK[:CKPT],TASK[:CKPT],...",
                    help="multi-task SeisT group served on ONE shared "
                    "trunk (the PR 10 fan-out at full batch)")
    ap.add_argument("--tasks", default="",
                    help="comma-separated subset of a group's heads")
    ap.add_argument("--variant", default="fp32",
                    choices=("fp32", "bf16", "int8"),
                    help="serving weight variant (parity-gated against "
                    "fp32 at load; a failing gate refuses the run)")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--batches-per-call", type=int, default=4,
                    help="micro-batches per compiled device call "
                    "(lax.map'd in ONE executable — host Python is off "
                    "the critical path)")
    ap.add_argument("--commit-every", type=int, default=4,
                    help="segment commit granularity in device calls")
    ap.add_argument("--prefetch", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0,
                    help="fresh-init weight seed (checkpoint-free runs)")
    ap.add_argument("--workers", type=int, default=0,
                    help="driver mode: spawn N worker subprocesses, then "
                    "merge (0 = do everything in-process)")
    ap.add_argument("--worker-index", type=int, default=-1,
                    help="worker mode: this worker's index (driver sets it)")
    ap.add_argument("--num-workers", type=int, default=1,
                    help="worker mode: total workers (driver sets it)")
    ap.add_argument("--retries", type=int, default=2,
                    help="driver: crash-relaunch budget per worker "
                    "(preempt exits never consume it)")
    ap.add_argument("--fleet", action="store_true",
                    help="lease-based fleet worker (batch/fleet.py): "
                    "instead of a static units[i::N] slice, acquire "
                    "work-unit leases with heartbeat + fencing token, "
                    "reclaim peers' expired leases, and park through "
                    "lease-store partitions — any number of workers, "
                    "joining and dying at any time, converge on the "
                    "same catalog (docs/FAULT_TOLERANCE.md)")
    ap.add_argument("--lease-dir", default="",
                    help="shared-directory lease store root (fleet "
                    "mode; also lets --merge-only audit segment fences "
                    "against the done ledger)")
    ap.add_argument("--worker-id", default="",
                    help="fleet mode: this worker's lease owner id "
                    "(default: worker<index>@<pid>)")
    ap.add_argument("--lease-store", default="auto",
                    choices=("auto", "dir", "kv"),
                    help="fleet lease store: 'dir' = shared directory "
                    "(--lease-dir), 'kv' = the jax coordination-service "
                    "KV (multi-host slices), 'auto' = kv when a "
                    "coordination service is initialized, else dir")
    ap.add_argument("--no-merge", action="store_true",
                    help="skip the reduce step (driver/smoke runs merge "
                    "separately)")
    ap.add_argument("--merge-only", action="store_true",
                    help="reduce only: merge committed segments into "
                    "catalog.jsonl (no model, no jax)")
    ap.add_argument("--compile-gate", action="store_true",
                    help="run the post-warm-up loop under CompileBudget "
                    "and report compiles_after_warmup (must be 0)")
    ap.add_argument("--ppk-threshold", type=float, default=0.3)
    ap.add_argument("--spk-threshold", type=float, default=0.3)
    ap.add_argument("--det-threshold", type=float, default=0.5)
    ap.add_argument("--min-peak-dist", type=float, default=1.0)
    ap.add_argument("--max-events", type=int, default=8)
    ap.add_argument("--station-meta", default="", metavar="FILE",
                    help="JSON file mapping waveform key -> station "
                    "metadata {'id', 'network', 'lat', 'lon'}; matched "
                    "rows carry a 'station' field in the catalog "
                    "(the /predict //stream provenance block)")
    args = ap.parse_args(argv)
    if args.merge_only:
        # The reduce is model-free: identity comes from repick_plan.json.
        if args.model or args.model_group:
            ap.error("--merge-only takes no --model/--model-group (the "
                     "plan file records them)")
    elif bool(args.model) == bool(args.model_group):
        ap.error("exactly one of --model / --model-group is required")
    if args.fleet and args.lease_store != "kv" and not args.lease_dir:
        ap.error("--fleet needs --lease-dir (or --lease-store kv under "
                 "an initialized jax coordination service)")
    return args


def _archive_index(archive: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """(meta.json dict, index columns needed for planning) — no jax."""
    from seist_tpu.data import packed as packed_mod

    with open(os.path.join(archive, packed_mod._META)) as f:
        meta = json.load(f)
    with np.load(
        os.path.join(archive, packed_mod._INDEX), allow_pickle=False
    ) as z:
        # Only the planning columns: 'key' (the biggest index array at
        # archive scale) is read by the worker via the packed dataset's
        # frame, not here — the model-free merge role must not pay it.
        cols = {"shard": z["shard"], "n_samp": z["n_samp"]}
    return meta, cols


def _parse_group(spec: str) -> Tuple[str, List[Tuple[str, str]]]:
    """PREFIX=TASK[:CKPT],... (the serve CLI's --model-group grammar)."""
    prefix, sep, rest = spec.partition("=")
    if not sep or not prefix or not rest:
        raise SystemExit(
            f"bad --model-group '{spec}' "
            "(want PREFIX=TASK[:CKPT],TASK[:CKPT],...)"
        )
    tasks: List[Tuple[str, str]] = []
    for part in rest.split(","):
        task, _, ckpt = part.partition(":")
        if not task:
            raise SystemExit(f"empty task in --model-group '{spec}'")
        tasks.append((task, ckpt))
    return prefix, tasks


def _plan_dict(args, meta, n_rows: int, n_units: int) -> Dict[str, Any]:
    """Everything that determines segment boundaries and row content —
    the resume geometry guard (catalog.write_or_check_plan)."""
    return {
        "format_version": 1,
        "source": meta.get("source", ""),
        "dtype": meta.get("dtype", "float32"),
        "n_rows": n_rows,
        "n_units": n_units,
        "model": args.model or args.model_group,
        "tasks": args.tasks,
        "variant": args.variant,
        "batch_size": args.batch_size,
        "batches_per_call": args.batches_per_call,
        "commit_every": args.commit_every,
        "sampling_rate": int(meta["sampling_rate"]),
        "decode": {
            "ppk_threshold": args.ppk_threshold,
            "spk_threshold": args.spk_threshold,
            "det_threshold": args.det_threshold,
            "min_peak_dist": args.min_peak_dist,
            "max_events": args.max_events,
        },
    }


def _merge(args, meta, units, print_verdict: bool = True) -> Dict[str, Any]:
    from seist_tpu.batch import catalog

    # Segment geometry and model identity come from the RECORDED plan,
    # never from this invocation's flags: a --merge-only run with
    # different defaults must not under-count segments (merge_catalog's
    # completeness guard would pass on a prefix and silently drop rows)
    # or misattribute the producing model in catalog_meta.json.
    plan = catalog.read_plan(args.out)
    rows_per_call = int(plan["batch_size"]) * int(plan["batches_per_call"])
    # Fleet merges audit every segment's fence sidecar against the lease
    # store's done ledger (merge_catalog refuses zombie-written
    # segments); catalog.jsonl bytes are identical either way.
    fences = None
    if args.lease_dir and os.path.isdir(args.lease_dir):
        from seist_tpu.batch import fleet

        fences = fleet.DirLeaseStore(args.lease_dir).done_fences(
            [u.unit_id for u in units]
        )
    out_meta = catalog.merge_catalog(
        args.out, units, rows_per_call, int(plan["commit_every"]),
        meta={
            "archive_source": meta.get("source", ""),
            "sampling_rate": int(meta["sampling_rate"]),
            "model": plan["model"],
            "variant": plan["variant"],
            "plan": plan,
        },
        fences=fences,
    )
    verdict = {
        "ok": True,
        "role": "merge",
        "out": args.out,
        "rows": out_meta["n_rows"],
        "units": out_meta["n_units"],
    }
    if fences is not None:
        verdict["fence_audit"] = out_meta["fleet"]
    if print_verdict:
        print(json.dumps(verdict))
    return verdict


def _load_entry(args, window: int):
    from seist_tpu.serve.pool import load_group_entry, load_model_entry

    variants = (args.variant,)
    if args.model_group:
        prefix, task_entries = _parse_group(args.model_group)
        return load_group_entry(
            prefix, task_entries, window=window, seed=args.seed,
            variants=variants,
        )
    name, _, ckpt = args.model.partition("=")
    return load_model_entry(
        name, ckpt, window=window, seed=args.seed, variants=variants
    )


def run_worker(args, worker_index: int, num_workers: int) -> int:
    """One map worker: build store + entry + engine, re-pick this
    worker's units, honor SIGTERM with a drain-and-exit-75."""
    from seist_tpu.batch import catalog
    from seist_tpu.batch.engine import RepickEngine
    from seist_tpu.data import pipeline
    from seist_tpu.data.ingest import PackedRawStore, packed_dataset_of
    from seist_tpu.train.checkpoint import PREEMPT_EXIT_CODE, ProgressFile

    meta, cols = _archive_index(args.archive)
    units = _units_from_cols(cols)
    if not units:
        raise SystemExit(f"archive {args.archive} has no rows")
    raw_len = int(cols["n_samp"][0])
    rows_per_call = args.batch_size * args.batches_per_call
    os.makedirs(args.out, exist_ok=True)
    catalog.write_or_check_plan(
        args.out, _plan_dict(args, meta, len(cols["shard"]), len(units))
    )

    # The store covers the WHOLE archive in pack order: no shuffle, no
    # split, no labels (inference needs waveforms only — a NaN label
    # column must not refuse the build).
    sds = pipeline.SeismicDataset(
        "packed", "train", seed=0, data_dir=args.archive,
        input_names=[], label_names=[], task_names=[],
        in_samples=raw_len, augmentation=False, shuffle=False,
        data_split=False,
    )
    # int8 v3 archives feed the device-dequant path: rows stay int8
    # through staging and the host->device copy, the program widens
    # (batch/engine.dequant_rows is fused ahead of the z-score).
    pds = packed_dataset_of(sds)
    store = PackedRawStore.build(
        sds, batch_size=rows_per_call, prefetch=args.prefetch,
        stage_raw=(pds.storage_dtype == np.int8),
    )
    keys = pds._meta_data["key"].to_numpy()
    entry = _load_entry(args, raw_len)
    engine = RepickEngine(
        entry, store,
        sampling_rate=int(meta["sampling_rate"]),
        batch_size=args.batch_size,
        batches_per_call=args.batches_per_call,
        variant=args.variant,
        decode_opts={
            "ppk_threshold": args.ppk_threshold,
            "spk_threshold": args.spk_threshold,
            "det_threshold": args.det_threshold,
            "min_peak_dist": args.min_peak_dist,
            "max_events": args.max_events,
        },
        keys=keys,
        stations=_load_station_meta(args.station_meta),
        prefetch=args.prefetch,
        tasks=[t for t in args.tasks.split(",") if t] or None,
    )

    stop = threading.Event()
    # threadlint: handlers do flag stores only (the drain happens on the
    # main thread, at the next segment boundary).
    signal.signal(signal.SIGTERM, lambda s, f: stop.set())

    if args.fleet:
        return _run_fleet_worker(args, worker_index, units, engine, stop)

    mine = list(units)[worker_index::num_workers]
    progress = ProgressFile(
        os.path.join(args.out, f"worker_{worker_index}.json")
    )
    engine.warmup()
    stats = engine.run_units(
        mine, args.out,
        commit_every=args.commit_every,
        stop_event=stop,
        compile_gate=args.compile_gate,
        progress=progress,
    )
    verdict = {
        "ok": not stats["preempted"],
        "role": "worker",
        "worker": worker_index,
        "num_workers": num_workers,
        "units_assigned": len(mine),
        **stats,
        **{f"warmup_{k}": v for k, v in engine.warmup_report.items()},
    }
    print(json.dumps(verdict), flush=True)
    if stats["preempted"]:
        return PREEMPT_EXIT_CODE
    return 0


def _lease_store(args):
    """Build the configured lease store. 'auto' prefers the jax
    coordination-service KV (real multi-host slices) and falls back to
    the shared directory when no service is initialized."""
    from seist_tpu.batch import fleet

    if args.lease_store in ("auto", "kv"):
        try:
            return fleet.KVLeaseStore.from_runtime()
        except fleet.LeaseStoreError:
            if args.lease_store == "kv":
                raise
    return fleet.DirLeaseStore(args.lease_dir)


def _run_fleet_worker(args, worker_index, units, engine, stop) -> int:
    """One FLEET worker: every unit is a candidate (work-stealing over
    leases, scan rotated by the worker index); the engine runs each
    leased unit with the fence guard on every segment commit. Exits 75
    on preemption — the supervisor relaunches and the worker re-joins
    whatever work is still unleased."""
    from seist_tpu.batch import fleet
    from seist_tpu.train.checkpoint import PREEMPT_EXIT_CODE, ProgressFile

    owner = args.worker_id or f"worker{max(worker_index, 0)}@{os.getpid()}"
    store = _lease_store(args)
    progress = ProgressFile(
        os.path.join(args.out, f"fleet_{max(worker_index, 0)}.json")
    )
    engine.warmup()  # burn compile time BEFORE any lease TTL is ticking
    totals = {"rows": 0, "calls": 0, "segments": 0}

    def run_one(unit, held):
        u = engine.run_unit(
            unit, args.out, commit_every=args.commit_every,
            stop_event=stop, lease=held,
        )
        for k in totals:
            totals[k] += u[k]
        progress.save({
            "owner": owner, "unit": unit.unit_id, "fence": held.fence,
            "preempted": u["preempted"], **totals,
        })
        return u

    worker = fleet.FleetWorker(
        store, units, owner, run_one,
        stop_event=stop, scan_offset=max(worker_index, 0),
    )
    budget = None
    if args.compile_gate:
        from tools.jaxlint.runtime import CompileBudget

        budget = CompileBudget()
        budget.__enter__()
    try:
        stats = worker.run()
    finally:
        if budget is not None:
            budget.__exit__(None, None, None)
    verdict = {
        "ok": stats["all_done"] or stats["preempted"],
        "role": "fleet-worker",
        "worker": worker_index,
        "owner": owner,
        "store": type(store).__name__,
        **{k: stats[k] for k in (
            "units_done", "units_lost", "parks", "preempted", "all_done",
        )},
        **totals,
        "lease": stats["lease"],
        **{f"warmup_{k}": v for k, v in engine.warmup_report.items()},
    }
    if budget is not None:
        verdict["compiles_after_warmup"] = budget.total("")
        verdict["xla_compiles_after_warmup"] = budget.backend_compiles
    print(json.dumps(verdict), flush=True)
    if stats["preempted"] and not stats["all_done"]:
        return PREEMPT_EXIT_CODE
    return 0 if verdict["ok"] else 1


def _load_station_meta(path: str):
    """--station-meta FILE -> {key: normalized station dict} or None.
    Validated through the same parse_station the serve plane uses, so a
    catalog's 'station' blocks and a /stream request's are one schema."""
    if not path:
        return None
    from seist_tpu.serve.protocol import BadRequest, parse_station

    with open(path) as f:
        raw = json.load(f)
    if not isinstance(raw, dict):
        raise SystemExit(f"--station-meta {path}: want a JSON object "
                         "mapping waveform key -> station metadata")
    out = {}
    for key, st in raw.items():
        try:
            out[str(key)] = parse_station(st, required=True)
        except BadRequest as e:
            raise SystemExit(f"--station-meta {path}: key {key!r}: {e}")
    return out


def _units_from_cols(cols):
    from seist_tpu.batch import catalog

    return catalog.plan_units(cols["shard"])


def _worker_cmd(args, worker_index: int) -> List[str]:
    cmd = [
        sys.executable, "-m", "tools.repick_archive",
        "--archive", args.archive, "--out", args.out,
        "--variant", args.variant,
        "--batch-size", str(args.batch_size),
        "--batches-per-call", str(args.batches_per_call),
        "--commit-every", str(args.commit_every),
        "--prefetch", str(args.prefetch),
        "--seed", str(args.seed),
        "--worker-index", str(worker_index),
        "--num-workers", str(args.workers),
        "--no-merge",
        "--ppk-threshold", str(args.ppk_threshold),
        "--spk-threshold", str(args.spk_threshold),
        "--det-threshold", str(args.det_threshold),
        "--min-peak-dist", str(args.min_peak_dist),
        "--max-events", str(args.max_events),
    ]
    if args.model:
        cmd += ["--model", args.model]
    if args.model_group:
        cmd += ["--model-group", args.model_group]
    if args.tasks:
        cmd += ["--tasks", args.tasks]
    if args.compile_gate:
        cmd += ["--compile-gate"]
    if args.station_meta:
        cmd += ["--station-meta", args.station_meta]
    return cmd


def run_driver(args) -> int:
    """Map-reduce driver: spawn the workers, relaunch preempted/crashed
    ones (preempt exits never consume the crash budget — the supervise
    contract), then run the reduce."""
    from seist_tpu.obs.bus import monotonic
    from seist_tpu.train.checkpoint import PREEMPT_EXIT_CODE

    t0 = monotonic()
    meta, cols = _archive_index(args.archive)
    units = _units_from_cols(cols)
    budget = {i: args.retries for i in range(args.workers)}
    pending = list(range(args.workers))
    failed: List[int] = []
    while pending:
        procs = {
            i: subprocess.Popen(_worker_cmd(args, i)) for i in pending
        }
        pending = []
        for i, p in procs.items():
            rc = p.wait()
            if rc == 0:
                continue
            if rc == PREEMPT_EXIT_CODE:
                pending.append(i)  # resume, budget untouched
            elif budget[i] > 0:
                budget[i] -= 1
                pending.append(i)
            else:
                failed.append(i)
    if failed:
        print(json.dumps({
            "ok": False, "role": "driver",
            "error": f"worker(s) {failed} exhausted the relaunch budget",
        }))
        return 1
    verdict: Dict[str, Any] = {
        "ok": True, "role": "driver", "workers": args.workers,
        "units": len(units), "wall_s": round(monotonic() - t0, 2),
    }
    if not args.no_merge:
        merged = _merge(args, meta, units, print_verdict=False)
        verdict["rows"] = merged["rows"]
        verdict["out"] = args.out
    print(json.dumps(verdict))
    return 0


def main(argv=None) -> int:
    args = get_args(argv)
    from seist_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()
    import seist_tpu
    from seist_tpu.utils.misc import enable_compile_cache

    seist_tpu.load_all()
    if args.merge_only:
        meta, cols = _archive_index(args.archive)
        _merge(args, meta, _units_from_cols(cols))
        return 0
    enable_compile_cache()
    if args.worker_index >= 0:
        return run_worker(args, args.worker_index, args.num_workers)
    if args.workers > 0:
        return run_driver(args)
    # Inline: one process maps every unit, then reduces.
    rc = run_worker(args, 0, 1)
    if rc == 0 and not args.no_merge:
        meta, cols = _archive_index(args.archive)
        _merge(args, meta, _units_from_cols(cols))
    return rc


if __name__ == "__main__":
    sys.exit(main())
