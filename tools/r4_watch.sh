#!/bin/bash
# Round-4 arm-and-fire watcher: block until the TPU tunnel answers, then
# immediately run the full round-4 evidence sequence (tools/r4_silicon.sh,
# which fronts the headline HEAD bench + fused-kernel assert so a short
# tunnel window still yields the round's #1 deliverable).
#
#   nohup bash tools/r4_watch.sh > tools/r4_watch.log 2>&1 &
#
# Safe to leave running all round; it exits after one full r4 sequence.
cd /root/repo
bash tools/tpu_probe_loop.sh
echo "tunnel up -> launching r4_silicon $(date -u +%FT%TZ)"
bash tools/r4_silicon.sh
echo "r4_watch done $(date -u +%FT%TZ)"
