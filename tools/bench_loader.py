"""Input-pipeline-only benchmark: waveforms/sec through the full host path.

Measures the loader end to end — dataset read, the nine augmentations,
window cut, normalize, soft-label generation, batch assembly — with no
device in the loop (SURVEY.md hard-part #1: at reference training shape,
batch 500 x 8192, the host must outrun the TPU step or the chip starves).

Prints ONE JSON line:
  {"metric": "input_pipeline_throughput", "value", "unit", "vs_baseline"}
``vs_baseline`` is loader wf/s divided by the most recent *device* step
rate (from BENCH env DEVICE_WFS or the default below) — the ratio that
matters; >= 2.0 means the pipeline can feed the chip with headroom.

Env knobs: BENCH_BATCH (500), BENCH_SAMPLES (8192), BENCH_BATCHES (8),
BENCH_WORKERS (os.cpu_count), DEVICE_WFS, BENCH_DATASET
(synthetic | diting_light | packed — diting_light writes a
DiTing-light-format CSV+HDF5 fixture once under logs/ and measures the
real h5py/pandas reader path end to end; packed measures the
packed-shard repack of that same fixture, tools/pack_dataset.py).

--compare (``python -m tools.bench_loader --compare [--out f.json]``)
runs the packed-ingest ladder on ONE shared fixture instead: hdf5
per-sample reads vs packed per-sample reads vs packed+direct-ingest
batch fills (data/ingest.py), with a per-stage budget that shows the
per-sample Event decode and ``_stack`` assembly eliminated on the fast
path — plus the storage-dtype ladder (fp32/bf16/int8 sibling packs of
the same fixture: per-dtype fill ms/wf and measured on-disk bytes/wf;
int8 also measures the stage_raw device-dequant lane). Pass gates:
direct >= 2x the hdf5 per-sample read throughput (ISSUE 14) and int8
on-disk bytes <= 0.55x fp32 (ISSUE 18); the committed verdict lives in
BENCH_loader_r02.json. Env: BENCH_EVENTS (512), BENCH_SAMPLES (8192),
BENCH_READS (400), BENCH_BATCH (64).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def run() -> None:
    import numpy as np  # noqa: F401 (keeps import cost out of the timing)

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.data import pipeline

    seist_tpu.load_all()

    batch = int(os.environ.get("BENCH_BATCH", 500))
    in_samples = int(os.environ.get("BENCH_SAMPLES", 8192))
    n_batches = int(os.environ.get("BENCH_BATCHES", 8))
    workers = int(os.environ.get("BENCH_WORKERS", os.cpu_count() or 1))
    # BENCH_PROCESSES > 0 routes the per-sample work through the process
    # pool (`--loader-processes` in the CLI) — the measured scaling knob
    # for feeding a chip from a multi-core host (VERDICT r3 #7).
    processes = int(os.environ.get("BENCH_PROCESSES", 0))
    device_wfs = float(os.environ.get("DEVICE_WFS", 4236.0))

    dataset_name = os.environ.get("BENCH_DATASET", "synthetic")
    spec = taskspec.get_task_spec("seist_l_dpk")
    ds_kw: dict = {}
    data_dir = ""
    if dataset_name == "synthetic":
        ds_kw = {"num_events": batch * 4}
    elif dataset_name == "packed":
        # Packed-shard repack of the diting_light fixture (VERDICT r4 #8).
        from tools.fixtures import ensure_packed_fixture

        data_dir = ensure_packed_fixture(max(batch * 2, 512), in_samples)
    elif dataset_name == "diting_light":
        # Real-format reader path: write the fixture once (keyed by shape)
        # and reuse it across runs.
        from tools.fixtures import write_diting_light_fixture

        n_events = max(batch * 2, 512)
        data_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            os.pardir,
            "logs",
            f"loader_fixture_{n_events}x{in_samples}",
        )
        # Sentinel written only after the full fixture lands — the CSV is
        # the FIRST artifact the writer produces, so its existence alone
        # would turn an interrupted write into a permanently broken cache.
        marker = os.path.join(data_dir, ".complete")
        if not os.path.exists(marker):
            t0 = time.perf_counter()
            write_diting_light_fixture(
                data_dir, n_events=n_events, trace_samples=in_samples
            )
            with open(marker, "w") as f:
                f.write("ok\n")
            print(
                f"fixture written in {time.perf_counter() - t0:.1f}s: "
                f"{data_dir}",
                file=sys.stderr,
            )
    else:
        raise SystemExit(f"unknown BENCH_DATASET {dataset_name!r}")
    dataset = pipeline.from_task_spec(
        spec,
        dataset_name,
        "train",
        seed=0,
        in_samples=in_samples,
        augmentation=True,
        data_dir=data_dir,
        dataset_kwargs=ds_kw,
    )
    loader = pipeline.Loader(
        dataset,
        batch,
        shuffle=True,
        drop_last=True,
        num_workers=workers,
        worker_processes=processes,
        seed=0,
    )

    # Warm one batch (imports, native-kernel dlopen, thread spin-up).
    it = iter(loader)
    next(it)

    t0 = time.perf_counter()
    done = 0
    for _ in range(n_batches):
        try:
            next(it)
        except StopIteration:
            loader.set_epoch(loader.epoch + 1)
            it = iter(loader)
            next(it)
        done += 1
    dt = time.perf_counter() - t0
    wfs = batch * done / dt

    print(
        json.dumps(
            {
                "metric": "input_pipeline_throughput",
                "value": round(wfs, 2),
                "unit": "waveforms/sec/host",
                "vs_baseline": round(wfs / device_wfs, 3),
                "device_wfs_ref": device_wfs,
                "batch": batch,
                "workers": workers,
                "worker_processes": processes,
                "augmentation": True,
                "dataset": dataset_name,
            }
        )
    )


def compare(out_path: str = "") -> int:
    """hdf5 vs packed vs packed+direct-ingest on one shared fixture."""
    import numpy as np

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.data import pipeline
    from seist_tpu.data.ingest import PackedRawStore
    from seist_tpu.registry import DATASETS
    from tools.fixtures import ensure_loader_fixture, ensure_packed_fixture

    seist_tpu.load_all()
    n_events = int(os.environ.get("BENCH_EVENTS", 512))
    in_samples = int(os.environ.get("BENCH_SAMPLES", 8192))
    n_reads = int(os.environ.get("BENCH_READS", 400))
    batch = int(os.environ.get("BENCH_BATCH", 64))

    src_dir = ensure_loader_fixture(n_events, in_samples)
    packed_dir = ensure_packed_fixture(n_events, in_samples)
    hdf5 = DATASETS.create(
        "diting_light", seed=0, mode="train", data_dir=src_dir
    )
    packed = DATASETS.create(
        "packed", seed=0, mode="train", data_dir=packed_dir
    )
    idxs = [i % len(hdf5) for i in range(n_reads)]
    for i in idxs[:16]:  # warm h5 handles / memmaps / page cache
        hdf5[i]
        packed[i]

    def rate(fn, items):
        t0 = time.perf_counter()
        for i in items:
            fn(i)
        dt = time.perf_counter() - t0
        return len(items) / dt, dt * 1e3 / len(items)

    hdf5_wfs, hdf5_ms = rate(lambda i: hdf5[i], idxs)
    packed_wfs, packed_ms = rate(lambda i: packed[i], idxs)

    # The batch-assembly (_stack) tax both per-sample paths pay per wf.
    rows = [packed[i][0]["data"] for i in idxs[:batch]]
    reps = max(1, n_reads // batch)
    t0 = time.perf_counter()
    for _ in range(reps):
        pipeline._stack(rows)
    stack_ms = (time.perf_counter() - t0) * 1e3 / (reps * batch)

    # Direct ingest: memmap -> staging slab batch fills, no Event decode.
    spec = taskspec.get_task_spec("seist_l_dpk")
    sds = pipeline.from_task_spec(
        spec, "packed", "train", seed=0, in_samples=in_samples,
        augmentation=False, data_dir=packed_dir,
    )
    store = PackedRawStore.build(sds, batch_size=batch)
    order = np.arange(store.n_raw)
    chunks = [
        order[b * batch : (b + 1) * batch]
        for b in range(max(1, min(len(order) // batch, n_reads // batch)))
    ]
    store.row_batch(chunks[0])  # warm
    t0 = time.perf_counter()
    for c in chunks:
        store.row_batch(c)
    dt = time.perf_counter() - t0
    direct_n = sum(len(c) for c in chunks)
    direct_wfs = direct_n / dt
    fill_ms = dt * 1e3 / direct_n

    # ------------------------------------------------------ dtype ladder
    # fp32/bf16/int8 direct-ingest fills off sibling packs of the SAME
    # fixture: per-dtype fill ms/wf plus on-disk bytes/wf measured from
    # the shards (ISSUE 18 — the bandwidth claim is measured, not
    # asserted). int8 additionally measures the stage_raw lane (rows
    # staged AS int8 + resident scales, the repick engine's
    # device-dequant feed) — that is the lane whose host->device bytes
    # shrink 4x.
    def shard_bytes(d):
        return sum(
            os.path.getsize(os.path.join(d, f))
            for f in sorted(os.listdir(d))
            if f.startswith("shard_") and f.endswith(".bin")
        )

    ladder = {}
    fp32_bytes_wf = shard_bytes(packed_dir) / n_events
    for dname in ("float32", "bfloat16", "int8"):
        pdir = (
            packed_dir
            if dname == "float32"
            else ensure_packed_fixture(n_events, in_samples, dtype=dname)
        )
        dsds = pipeline.from_task_spec(
            spec, "packed", "train", seed=0, in_samples=in_samples,
            augmentation=False, data_dir=pdir,
        )
        entry = {"bytes_per_wf": round(shard_bytes(pdir) / n_events, 1)}
        entry["bytes_vs_fp32"] = round(
            entry["bytes_per_wf"] / fp32_bytes_wf, 4
        )
        lanes = [("fill_f32", False)]
        if dname == "int8":
            lanes.append(("fill_raw_int8", True))
        for lane, raw in lanes:
            dstore = PackedRawStore.build(
                dsds, batch_size=batch, stage_raw=raw
            )
            dstore.row_batch(chunks[0])  # warm memmaps/page cache
            t0 = time.perf_counter()
            for c in chunks:
                dstore.row_batch(c)
            ddt = time.perf_counter() - t0
            entry[lane + "_wfs"] = round(direct_n / ddt, 1)
            entry[lane + "_ms_per_wf"] = round(ddt * 1e3 / direct_n, 4)
        ladder[dname] = entry

    verdict = {
        "metric": "packed_ingest_throughput",
        "unit": "waveforms/sec/host (single-thread read lane)",
        "hdf5_read_wfs": round(hdf5_wfs, 1),
        "packed_read_wfs": round(packed_wfs, 1),
        "packed_direct_wfs": round(direct_wfs, 1),
        "speedup_packed_vs_hdf5": round(packed_wfs / hdf5_wfs, 2),
        "speedup_direct_vs_hdf5": round(direct_wfs / hdf5_wfs, 2),
        "stage_budget_ms_per_wf": {
            "hdf5": {
                "per_sample_event_decode": round(hdf5_ms, 4),
                "_stack": round(stack_ms, 4),
            },
            "packed": {
                "per_sample_event_decode": round(packed_ms, 4),
                "_stack": round(stack_ms, 4),
            },
            "packed_direct": {
                "batch_fill": round(fill_ms, 4),
                "eliminated": ["per_sample_event_decode", "_stack"],
            },
        },
        "dtype_ladder": ladder,
        "config": {
            "n_events": n_events,
            "in_samples": in_samples,
            "n_reads": n_reads,
            "batch": batch,
        },
        # Two gates: the ISSUE 14 direct>=2x hdf5 throughput floor and
        # the ISSUE 18 int8 on-disk bytes<=0.55x fp32 ceiling.
        "pass": (
            direct_wfs >= 2.0 * hdf5_wfs
            and ladder["int8"]["bytes_vs_fp32"] <= 0.55
        ),
    }
    line = json.dumps(verdict)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 0 if verdict["pass"] else 1


def main() -> int:
    argv = sys.argv[1:]
    if "--compare" in argv:
        out = ""
        if "--out" in argv:
            out = argv[argv.index("--out") + 1]
        return compare(out)
    run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
