"""Input-pipeline-only benchmark: waveforms/sec through the full host path.

Measures the loader end to end — dataset read, the nine augmentations,
window cut, normalize, soft-label generation, batch assembly — with no
device in the loop (SURVEY.md hard-part #1: at reference training shape,
batch 500 x 8192, the host must outrun the TPU step or the chip starves).

Prints ONE JSON line:
  {"metric": "input_pipeline_throughput", "value", "unit", "vs_baseline"}
``vs_baseline`` is loader wf/s divided by the most recent *device* step
rate (from BENCH env DEVICE_WFS or the default below) — the ratio that
matters; >= 2.0 means the pipeline can feed the chip with headroom.

Env knobs: BENCH_BATCH (500), BENCH_SAMPLES (8192), BENCH_BATCHES (8),
BENCH_WORKERS (os.cpu_count), DEVICE_WFS.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def run() -> None:
    import numpy as np  # noqa: F401 (keeps import cost out of the timing)

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.data import pipeline

    seist_tpu.load_all()

    batch = int(os.environ.get("BENCH_BATCH", 500))
    in_samples = int(os.environ.get("BENCH_SAMPLES", 8192))
    n_batches = int(os.environ.get("BENCH_BATCHES", 8))
    workers = int(os.environ.get("BENCH_WORKERS", os.cpu_count() or 1))
    device_wfs = float(os.environ.get("DEVICE_WFS", 4236.0))

    spec = taskspec.get_task_spec("seist_l_dpk")
    dataset = pipeline.from_task_spec(
        spec,
        "synthetic",
        "train",
        seed=0,
        in_samples=in_samples,
        augmentation=True,
        dataset_kwargs={"num_events": batch * 4},
    )
    loader = pipeline.Loader(
        dataset,
        batch,
        shuffle=True,
        drop_last=True,
        num_workers=workers,
        seed=0,
    )

    # Warm one batch (imports, native-kernel dlopen, thread spin-up).
    it = iter(loader)
    next(it)

    t0 = time.perf_counter()
    done = 0
    for _ in range(n_batches):
        try:
            next(it)
        except StopIteration:
            loader.set_epoch(loader.epoch + 1)
            it = iter(loader)
            next(it)
        done += 1
    dt = time.perf_counter() - t0
    wfs = batch * done / dt

    print(
        json.dumps(
            {
                "metric": "input_pipeline_throughput",
                "value": round(wfs, 2),
                "unit": "waveforms/sec/host",
                "vs_baseline": round(wfs / device_wfs, 3),
                "device_wfs_ref": device_wfs,
                "batch": batch,
                "workers": workers,
                "augmentation": True,
            }
        )
    )


if __name__ == "__main__":
    run()
