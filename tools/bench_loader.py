"""Input-pipeline-only benchmark: waveforms/sec through the full host path.

Measures the loader end to end — dataset read, the nine augmentations,
window cut, normalize, soft-label generation, batch assembly — with no
device in the loop (SURVEY.md hard-part #1: at reference training shape,
batch 500 x 8192, the host must outrun the TPU step or the chip starves).

Prints ONE JSON line:
  {"metric": "input_pipeline_throughput", "value", "unit", "vs_baseline"}
``vs_baseline`` is loader wf/s divided by the most recent *device* step
rate (from BENCH env DEVICE_WFS or the default below) — the ratio that
matters; >= 2.0 means the pipeline can feed the chip with headroom.

Env knobs: BENCH_BATCH (500), BENCH_SAMPLES (8192), BENCH_BATCHES (8),
BENCH_WORKERS (os.cpu_count), DEVICE_WFS, BENCH_DATASET
(synthetic | diting_light | packed — diting_light writes a
DiTing-light-format CSV+HDF5 fixture once under logs/ and measures the
real h5py/pandas reader path end to end; packed measures the
packed-shard repack of that same fixture, tools/pack_dataset.py).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def run() -> None:
    import numpy as np  # noqa: F401 (keeps import cost out of the timing)

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.data import pipeline

    seist_tpu.load_all()

    batch = int(os.environ.get("BENCH_BATCH", 500))
    in_samples = int(os.environ.get("BENCH_SAMPLES", 8192))
    n_batches = int(os.environ.get("BENCH_BATCHES", 8))
    workers = int(os.environ.get("BENCH_WORKERS", os.cpu_count() or 1))
    # BENCH_PROCESSES > 0 routes the per-sample work through the process
    # pool (`--loader-processes` in the CLI) — the measured scaling knob
    # for feeding a chip from a multi-core host (VERDICT r3 #7).
    processes = int(os.environ.get("BENCH_PROCESSES", 0))
    device_wfs = float(os.environ.get("DEVICE_WFS", 4236.0))

    dataset_name = os.environ.get("BENCH_DATASET", "synthetic")
    spec = taskspec.get_task_spec("seist_l_dpk")
    ds_kw: dict = {}
    data_dir = ""
    if dataset_name == "synthetic":
        ds_kw = {"num_events": batch * 4}
    elif dataset_name == "packed":
        # Packed-shard repack of the diting_light fixture (VERDICT r4 #8).
        from tools.fixtures import ensure_packed_fixture

        data_dir = ensure_packed_fixture(max(batch * 2, 512), in_samples)
    elif dataset_name == "diting_light":
        # Real-format reader path: write the fixture once (keyed by shape)
        # and reuse it across runs.
        from tools.fixtures import write_diting_light_fixture

        n_events = max(batch * 2, 512)
        data_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            os.pardir,
            "logs",
            f"loader_fixture_{n_events}x{in_samples}",
        )
        # Sentinel written only after the full fixture lands — the CSV is
        # the FIRST artifact the writer produces, so its existence alone
        # would turn an interrupted write into a permanently broken cache.
        marker = os.path.join(data_dir, ".complete")
        if not os.path.exists(marker):
            t0 = time.perf_counter()
            write_diting_light_fixture(
                data_dir, n_events=n_events, trace_samples=in_samples
            )
            with open(marker, "w") as f:
                f.write("ok\n")
            print(
                f"fixture written in {time.perf_counter() - t0:.1f}s: "
                f"{data_dir}",
                file=sys.stderr,
            )
    else:
        raise SystemExit(f"unknown BENCH_DATASET {dataset_name!r}")
    dataset = pipeline.from_task_spec(
        spec,
        dataset_name,
        "train",
        seed=0,
        in_samples=in_samples,
        augmentation=True,
        data_dir=data_dir,
        dataset_kwargs=ds_kw,
    )
    loader = pipeline.Loader(
        dataset,
        batch,
        shuffle=True,
        drop_last=True,
        num_workers=workers,
        worker_processes=processes,
        seed=0,
    )

    # Warm one batch (imports, native-kernel dlopen, thread spin-up).
    it = iter(loader)
    next(it)

    t0 = time.perf_counter()
    done = 0
    for _ in range(n_batches):
        try:
            next(it)
        except StopIteration:
            loader.set_epoch(loader.epoch + 1)
            it = iter(loader)
            next(it)
        done += 1
    dt = time.perf_counter() - t0
    wfs = batch * done / dt

    print(
        json.dumps(
            {
                "metric": "input_pipeline_throughput",
                "value": round(wfs, 2),
                "unit": "waveforms/sec/host",
                "vs_baseline": round(wfs / device_wfs, 3),
                "device_wfs_ref": device_wfs,
                "batch": batch,
                "workers": workers,
                "worker_processes": processes,
                "augmentation": True,
                "dataset": dataset_name,
            }
        )
    )


if __name__ == "__main__":
    run()
