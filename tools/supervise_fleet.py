"""Serving-fleet supervisor: spawn N replicas + the front-tier router,
restart what dies, roll the registry.

The serving analog of tools/supervise.py (which babysits one training
process): this babysits a *fleet* — N ``python main.py serve`` replica
processes on consecutive ports plus an in-process
:mod:`seist_tpu.serve.router` front tier that load-balances, retries and
circuit-breaks across them (docs/SERVING.md)::

    python tools/supervise_fleet.py --replicas 2 --router-port 8080 \\
        --base-port 18100 -- \\
        python main.py serve --model seist_s_dpk=CKPT --window 8192

Replica lifecycle (mirrors the train-plane exit-code contract,
docs/FAULT_TOLERANCE.md):

* exit ``75`` (EX_TEMPFAIL) — the replica caught SIGTERM, drained its
  in-flight requests and left cleanly (a managed preemption). Relaunched
  IMMEDIATELY; the failure budget is untouched.
* any other nonzero exit (SIGKILL shows as -9) — a crash. The replica is
  pulled from the router's rotation at once (faster than a probe
  interval), relaunched after ``--backoff`` seconds, up to ``--retries``
  consecutive crashes; staying up ``--healthy-reset-s`` refills the
  budget. A replica that exhausts its budget is deregistered for good.
* exit ``0`` — voluntary stop (operator SIGINT); the slot is retired.

The supervisor exits 0 on SIGTERM/SIGINT (after draining the replicas)
and 1 once every replica slot has been retired. Each replica gets
``SEIST_SERVE_REPLICA=<index>`` in its environment — the handle
``SEIST_FAULT_SERVE_REPLICA`` uses to aim a chaos fault at exactly one
member of the fleet (utils/faults.py), and the ordinal that suffixes
the replica's ``events_r<N>.jsonl`` / flight-dump artifacts under a
shared ``--logdir``.

The supervisor is also the fleet's metrics pane: a
:class:`seist_tpu.obs.fleet.FleetAggregator` periodically pulls every
replica's ``/metrics.json`` plus the in-process router's bus and serves
the merged view (counters summed, histograms merged bucket-wise,
per-replica breakdown retained) at ``GET /fleet/metrics[.json]`` on the
router port (docs/SERVING.md "Fleet metrics").
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))

# Keep in sync with seist_tpu.serve.server.PREEMPT_EXIT_CODE /
# seist_tpu.train.checkpoint.PREEMPT_EXIT_CODE
# (tests/test_serve_fleet.py pins all three together).
PREEMPT_EXIT_CODE = 75


def _log(msg: str) -> None:
    print(f"[fleet] {msg}", file=sys.stderr, flush=True)


class ReplicaSlot:
    """One fleet position: its port, process handle and failure budget."""

    def __init__(self, index: int, port: int, cmd: List[str]):
        self.index = index
        self.port = port
        self.url = f"127.0.0.1:{port}"
        self.cmd = list(cmd) + ["--host", "127.0.0.1", "--port", str(port)]
        self.proc: Optional[subprocess.Popen] = None
        self.failures = 0  # consecutive crashes since last healthy stretch
        self.started_at = 0.0
        self.restart_at: Optional[float] = None  # backoff schedule
        self.retired = False

    def spawn(self) -> None:
        env = dict(os.environ)
        env["SEIST_SERVE_REPLICA"] = str(self.index)
        self.proc = subprocess.Popen(self.cmd, env=env)
        self.started_at = time.monotonic()
        self.restart_at = None
        _log(
            f"replica {self.index} (port {self.port}) started "
            f"pid={self.proc.pid}"
        )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="serving-fleet supervisor: replicas + router",
        usage="supervise_fleet.py [opts] -- python main.py serve ...",
    )
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--base-port", type=int, default=18100,
                    help="replica i serves on base-port + i")
    ap.add_argument("--router-host", default="127.0.0.1")
    ap.add_argument("--router-port", type=int, default=8080,
                    help="front-tier port (0 = ephemeral, printed)")
    ap.add_argument("--retries", type=int, default=3,
                    help="consecutive crash relaunches per replica before "
                    "the slot is retired (exit-75 preempts are free)")
    ap.add_argument("--backoff", type=float, default=2.0,
                    help="seconds before a crash relaunch")
    ap.add_argument("--healthy-reset-s", type=float, default=60.0,
                    help="uptime that refills a replica's crash budget")
    ap.add_argument("--drain-timeout-s", type=float, default=30.0,
                    help="SIGTERM->SIGKILL grace on supervisor shutdown")
    # Router knobs (forwarded to seist_tpu.serve.router.RouterConfig).
    ap.add_argument("--router-retries", type=int, default=2)
    ap.add_argument("--request-timeout-s", type=float, default=10.0)
    ap.add_argument("--hedge-ms", type=float, default=0.0)
    ap.add_argument("--probe-interval-s", type=float, default=0.5)
    ap.add_argument("--breaker-failures", type=int, default=3)
    ap.add_argument("--breaker-cooldown-s", type=float, default=2.0)
    ap.add_argument("--fleet-scrape-interval-s", type=float, default=5.0,
                    help="how often the fleet aggregator pulls every "
                    "replica's /metrics.json (served merged on the "
                    "router port at GET /fleet/metrics[.json])")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="the replica command, after `--` (without "
                    "--host/--port, which the supervisor assigns)")
    args = ap.parse_args(argv)

    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no replica command (use: supervise_fleet.py [opts] -- "
                 "python main.py serve ...)")
    if args.replicas < 1:
        ap.error("--replicas must be >= 1")

    from seist_tpu.obs import trace as obs_trace
    from seist_tpu.obs.bus import BUS
    from seist_tpu.obs.fleet import FleetAggregator
    from seist_tpu.serve.router import (
        Router,
        RouterConfig,
        start_router_server,
    )

    router = Router(
        config=RouterConfig(
            retries=args.router_retries,
            request_timeout_s=args.request_timeout_s,
            hedge_ms=args.hedge_ms,
            probe_interval_s=args.probe_interval_s,
            breaker_failures=args.breaker_failures,
            breaker_cooldown_s=args.breaker_cooldown_s,
        )
    )
    slots = [
        ReplicaSlot(i, args.base_port + i, cmd)
        for i in range(args.replicas)
    ]
    # Fleet metrics pane: periodically pull every replica's /metrics.json
    # plus the (in-process) router's bus, merge counters/gauges and
    # bucket-wise histograms, serve the single aggregated view at
    # GET /fleet/metrics[.json] on the router port (docs/SERVING.md) —
    # the signal source the autoscaler and canary rollback will read.
    obs_trace.register_trace_collector()
    fleet = FleetAggregator(interval_s=args.fleet_scrape_interval_s)
    fleet.add_source("router", BUS.snapshot)
    for slot in slots:
        slot.spawn()
        router.registry.add(slot.url)
        fleet.add_source(f"replica-{slot.index}", slot.url)
    server = start_router_server(router, args.router_host, args.router_port)
    server.fleet = fleet
    fleet.start()
    host, port = server.server_address[:2]
    # Machine-greppable for harnesses driving an ephemeral-port fleet.
    print(f"[fleet] ROUTER=http://{host}:{port}", flush=True)
    _log(f"router on http://{host}:{port}, {len(slots)} replica(s)")

    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)

    try:
        _monitor(slots, router, args, stop)
    finally:
        fleet.stop()
        _drain(slots, args.drain_timeout_s)
        server.shutdown()
        router.stop()
    live_slots = [s for s in slots if not s.retired]
    if stop.is_set():
        _log("stopped (signal)")
        return 0
    _log("stopped (all replica slots retired)" if not live_slots
         else "stopped")
    return 0 if live_slots else 1


def _monitor(
    slots: List["ReplicaSlot"], router, args, stop: threading.Event
) -> None:
    """Poll replica processes; restart / retire per the exit contract."""
    while not stop.is_set():
        active = 0
        for slot in slots:
            if slot.retired:
                continue
            active += 1
            now = time.monotonic()
            if slot.proc is None:
                # In backoff: relaunch when its clock expires.
                if slot.restart_at is not None and now >= slot.restart_at:
                    slot.spawn()
                    router.registry.add(slot.url)
                continue
            if (
                slot.failures
                and now - slot.started_at >= args.healthy_reset_s
            ):
                _log(f"replica {slot.index} healthy "
                     f"{args.healthy_reset_s:.0f}s: crash budget reset")
                slot.failures = 0
            rc = slot.proc.poll()
            if rc is None:
                continue
            slot.proc = None
            # Pull it from rotation NOW — the router should stop routing
            # to a dead port before the next health probe finds out.
            router.registry.mark_down(slot.url, reason=f"rc={rc}")
            if rc == 0:
                _log(f"replica {slot.index} exited 0 (voluntary); "
                     "slot retired")
                slot.retired = True
                router.registry.remove(slot.url)
            elif rc == PREEMPT_EXIT_CODE:
                _log(f"replica {slot.index} clean preempt (rc={rc}): "
                     "immediate relaunch, budget untouched")
                slot.spawn()
                router.registry.add(slot.url)
            else:
                slot.failures += 1
                if slot.failures > args.retries:
                    _log(f"replica {slot.index} crashed rc={rc}; budget "
                         f"exhausted ({slot.failures - 1}/{args.retries}) "
                         "— slot retired")
                    slot.retired = True
                    router.registry.remove(slot.url)
                else:
                    _log(f"replica {slot.index} crashed rc={rc}; relaunch "
                         f"in {args.backoff:.1f}s "
                         f"(budget {slot.failures}/{args.retries})")
                    slot.restart_at = now + args.backoff
        if active == 0:
            return  # every slot retired: the fleet is gone
        stop.wait(0.2)


def _drain(slots: List["ReplicaSlot"], timeout_s: float) -> None:
    """SIGTERM every live replica (graceful drain, expect exit 75), then
    SIGKILL stragglers after the grace period."""
    live = [s for s in slots if s.proc is not None and s.proc.poll() is None]
    for slot in live:
        try:
            slot.proc.send_signal(signal.SIGTERM)
        except OSError:
            pass
    deadline = time.monotonic() + timeout_s
    for slot in live:
        remaining = max(0.1, deadline - time.monotonic())
        try:
            rc = slot.proc.wait(timeout=remaining)
            _log(f"replica {slot.index} drained (rc={rc})")
        except subprocess.TimeoutExpired:
            _log(f"replica {slot.index} did not drain in "
                 f"{timeout_s:.0f}s; SIGKILL")
            slot.proc.kill()
            slot.proc.wait()


if __name__ == "__main__":
    sys.exit(main())
