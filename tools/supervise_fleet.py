"""Serving-fleet supervisor: spawn N replicas + the front-tier router,
restart what dies, roll the registry.

The serving analog of tools/supervise.py (which babysits one training
process): this babysits a *fleet* — N ``python main.py serve`` replica
processes on consecutive ports plus an in-process
:mod:`seist_tpu.serve.router` front tier that load-balances, retries and
circuit-breaks across them (docs/SERVING.md)::

    python tools/supervise_fleet.py --replicas 2 --router-port 8080 \\
        --base-port 18100 -- \\
        python main.py serve --model seist_s_dpk=CKPT --window 8192

Replica lifecycle (mirrors the train-plane exit-code contract,
docs/FAULT_TOLERANCE.md):

* exit ``75`` (EX_TEMPFAIL) — the replica caught SIGTERM, drained its
  in-flight requests and left cleanly (a managed preemption). Relaunched
  IMMEDIATELY; the failure budget is untouched.
* any other nonzero exit (SIGKILL shows as -9) — a crash. The replica is
  pulled from the router's rotation at once (faster than a probe
  interval), relaunched after ``--backoff`` seconds, up to ``--retries``
  consecutive crashes; staying up ``--healthy-reset-s`` refills the
  budget. A replica that exhausts its budget is deregistered for good.
* exit ``0`` — voluntary stop (operator SIGINT); the slot is retired.

The supervisor exits 0 on SIGTERM/SIGINT (after draining the replicas)
and 1 once every replica slot has been retired. Each replica gets
``SEIST_SERVE_REPLICA=<index>`` in its environment — the handle
``SEIST_FAULT_SERVE_REPLICA`` uses to aim a chaos fault at exactly one
member of the fleet (utils/faults.py), and the ordinal that suffixes
the replica's ``events_r<N>.jsonl`` / flight-dump artifacts under a
shared ``--logdir``.

The supervisor is also the fleet's metrics pane: a
:class:`seist_tpu.obs.fleet.FleetAggregator` periodically pulls every
replica's ``/metrics.json`` plus the in-process router's bus and serves
the merged view (counters summed, histograms merged bucket-wise,
per-replica breakdown retained) at ``GET /fleet/metrics[.json]`` on the
router port (docs/SERVING.md "Fleet metrics").

**Rolling restart** (the live-model flywheel, docs/SERVING.md "Live
rollout"): ``SIGHUP`` makes the supervisor read ``--rollout-file`` (JSON:
``{"version": N, "checkpoint"?: path, "cmd"?: [...], "replicas"?: [i]}``)
and roll the fleet to the new model version ONE replica at a time —
SIGTERM-drain (exit 75, in-flight requests finish, the router routes
away), relaunch on the rewritten command (``--model-version N`` +
checkpoint substitution), then wait until the replica answers
``/healthz/ready`` with the target version AND is probe-ready in the
router's registry before touching the next. Capacity never dips below
N-1, and a replica that never converges aborts the roll loudly instead
of draining the next one. ``"replicas": [0]`` rolls a subset — the
canary-staging primitive (roll one, canary it via ``POST
/router/canary``, then roll the rest).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))

# Keep in sync with seist_tpu.serve.server.PREEMPT_EXIT_CODE /
# seist_tpu.train.checkpoint.PREEMPT_EXIT_CODE
# (tests/test_serve_fleet.py pins all three together).
PREEMPT_EXIT_CODE = 75


def _log(msg: str) -> None:
    print(f"[fleet] {msg}", file=sys.stderr, flush=True)


def rollout_cmd(
    cmd: List[str], version: int, checkpoint: Optional[str] = None
) -> List[str]:
    """Rewrite a replica command for a new model version: strip any
    existing ``--model-version``, substitute checkpoints when one is
    given (``--model NAME=CKPT`` values, ``--checkpoint`` values, and
    every task of ``--model-group PREFIX=task:CKPT,...``), then append
    ``--model-version N``. Pure function (unit-tested); anything fancier
    ships a full ``"cmd"`` in the rollout file instead."""
    out: List[str] = []
    i = 0
    while i < len(cmd):
        arg = cmd[i]
        if arg == "--model-version":
            i += 2  # drop flag + value
            continue
        if arg.startswith("--model-version="):
            i += 1
            continue
        if checkpoint is not None:
            if arg == "--model" and i + 1 < len(cmd):
                name = cmd[i + 1].partition("=")[0]
                out += [arg, f"{name}={checkpoint}"]
                i += 2
                continue
            if arg == "--checkpoint" and i + 1 < len(cmd):
                out += [arg, checkpoint]
                i += 2
                continue
            if arg == "--model-group" and i + 1 < len(cmd):
                prefix, _, rest = cmd[i + 1].partition("=")
                tasks = [
                    part.partition(":")[0] for part in rest.split(",")
                ]
                out += [
                    arg,
                    prefix + "=" + ",".join(
                        f"{t}:{checkpoint}" for t in tasks if t
                    ),
                ]
                i += 2
                continue
        out.append(arg)
        i += 1
    return out + ["--model-version", str(version)]


class FleetRollout:
    """One in-flight rolling restart, advanced by the monitor loop (a
    state machine, not a blocking call — crash relaunches and budget
    accounting keep running for the rest of the fleet mid-roll).

    Per replica: ``drain`` (SIGTERM; the replica exits 75 after serving
    its in-flight work and the monitor relaunches it IMMEDIATELY on the
    already-rewritten command) -> ``wait_ready`` (poll the replica's
    ``/healthz/ready`` until it reports the target version, plus the
    router's probe_ready so it is actually back in rotation) -> next
    slot. Aborts loudly on a per-replica ready timeout."""

    def __init__(
        self,
        slots: List["ReplicaSlot"],
        version: int,
        checkpoint: Optional[str] = None,
        cmd: Optional[List[str]] = None,
        subset: Optional[List[int]] = None,
        ready_timeout_s: float = 300.0,
    ):
        self.version = int(version)
        self.checkpoint = checkpoint
        self.cmd = list(cmd) if cmd else None
        self.ready_timeout_s = float(ready_timeout_s)
        self.queue = [
            s for s in slots
            if not s.retired and (subset is None or s.index in subset)
        ]
        self.phase = "start"  # start -> wait_relaunch -> wait_ready
        self.current: Optional[ReplicaSlot] = None
        self._old_pid: Optional[int] = None
        self._ready_deadline = 0.0
        self.done = False
        self.aborted = ""
        self.rolled: List[int] = []

    def _finish(self) -> None:
        self.done = True
        _log(
            f"rollout complete: version {self.version} on "
            f"replica(s) {self.rolled}"
        )

    def _next_slot(self) -> None:
        # A queued slot may have burned its crash budget since SIGHUP:
        # skip retired slots instead of draining a corpse (the monitor
        # never relaunches them, so waiting on one would hang the roll).
        while self.queue and self.queue[0].retired:
            skipped = self.queue.pop(0)
            _log(
                f"rollout: replica {skipped.index} retired since the "
                "roll started; skipping"
            )
        if not self.queue:
            self._finish()
            return
        self.current = self.queue.pop(0)
        slot = self.current
        # One deadline covers the slot's WHOLE drain -> relaunch -> ready
        # journey: a replica that ignores SIGTERM (wedged flush thread)
        # must abort the roll just as loudly as one that never converges.
        self._ready_deadline = time.monotonic() + self.ready_timeout_s
        base = self.cmd if self.cmd is not None else slot.cmd
        # Keep the supervisor-assigned --host/--port intact: rollout_cmd
        # only touches model flags; a full "cmd" replacement gets the
        # slot's host/port re-appended (argparse: last value wins).
        new_cmd = rollout_cmd(base, self.version, self.checkpoint)
        if self.cmd is not None:
            new_cmd += ["--host", "127.0.0.1", "--port", str(slot.port)]
        slot.cmd = new_cmd
        if slot.proc is not None and slot.proc.poll() is None:
            self._old_pid = slot.proc.pid
            _log(
                f"rollout: draining replica {slot.index} "
                f"(pid {self._old_pid}) for version {self.version}"
            )
            try:
                slot.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
            self.phase = "wait_relaunch"
        else:
            # Slot already down (crash backoff): the monitor's next
            # relaunch uses the rewritten command.
            self._old_pid = None
            _log(
                f"rollout: replica {slot.index} already down; relaunch "
                f"will carry version {self.version}"
            )
            self.phase = "wait_relaunch"

    def _abort(self, reason: str) -> None:
        self.aborted = reason
        self.done = True
        _log(f"rollout ABORTED: {reason}")

    def advance(self, registry, probe_ready_fn) -> None:
        """One monitor tick. ``probe_ready_fn(slot) -> (ready, versions)``
        polls the replica's own /healthz/ready (injectable for tests)."""
        if self.done:
            return
        if self.phase == "start":
            self._next_slot()
            if self.done:
                return
        slot = self.current
        now = time.monotonic()
        if self.phase == "wait_relaunch":
            if slot.retired:
                self._abort(
                    f"replica {slot.index} retired mid-roll (crash budget)"
                )
                return
            if now >= self._ready_deadline:
                self._abort(
                    f"replica {slot.index} never relaunched within "
                    f"{self.ready_timeout_s:.0f}s (drain wedged?)"
                )
                return
            proc = slot.proc
            if proc is None or (
                self._old_pid is not None and proc.pid == self._old_pid
            ):
                return  # still draining / in the monitor's relaunch gap
            _log(
                f"rollout: replica {slot.index} relaunched "
                f"(pid {proc.pid}, version {self.version}); waiting ready"
            )
            self.phase = "wait_ready"
            return
        if self.phase == "wait_ready":
            if slot.retired:
                self._abort(
                    f"replica {slot.index} retired mid-roll (crash budget)"
                )
                return
            if now >= self._ready_deadline:
                self._abort(
                    f"replica {slot.index} not ready on version "
                    f"{self.version} within {self.ready_timeout_s:.0f}s"
                )
                return
            ready, versions = probe_ready_fn(slot)
            if not ready or not versions:
                return
            if any(int(v) != self.version for v in versions.values()):
                return  # relaunched but still reporting the old version
            in_rotation = any(
                r.probe_ready and r.url.endswith(f":{slot.port}")
                for r in registry.replicas()
            )
            if not in_rotation:
                return  # ready, but the router's prober hasn't readmitted
            _log(
                f"rollout: replica {slot.index} ready + re-registered "
                f"(version {self.version})"
            )
            self.rolled.append(slot.index)
            self.phase = "start"
            if not self.queue:
                self._finish()  # the last replica converged this tick


class ReplicaSlot:
    """One fleet position: its port, process handle and failure budget."""

    def __init__(self, index: int, port: int, cmd: List[str]):
        self.index = index
        self.port = port
        self.url = f"127.0.0.1:{port}"
        self.cmd = list(cmd) + ["--host", "127.0.0.1", "--port", str(port)]
        self.proc: Optional[subprocess.Popen] = None
        self.failures = 0  # consecutive crashes since last healthy stretch
        self.started_at = 0.0
        self.restart_at: Optional[float] = None  # backoff schedule
        self.retired = False

    def spawn(self) -> None:
        env = dict(os.environ)
        env["SEIST_SERVE_REPLICA"] = str(self.index)
        self.proc = subprocess.Popen(self.cmd, env=env)
        self.started_at = time.monotonic()
        self.restart_at = None
        _log(
            f"replica {self.index} (port {self.port}) started "
            f"pid={self.proc.pid}"
        )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="serving-fleet supervisor: replicas + router",
        usage="supervise_fleet.py [opts] -- python main.py serve ...",
    )
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--base-port", type=int, default=18100,
                    help="replica i serves on base-port + i")
    ap.add_argument("--router-host", default="127.0.0.1")
    ap.add_argument("--router-port", type=int, default=8080,
                    help="front-tier port (0 = ephemeral, printed)")
    ap.add_argument("--retries", type=int, default=3,
                    help="consecutive crash relaunches per replica before "
                    "the slot is retired (exit-75 preempts are free)")
    ap.add_argument("--backoff", type=float, default=2.0,
                    help="seconds before a crash relaunch")
    ap.add_argument("--healthy-reset-s", type=float, default=60.0,
                    help="uptime that refills a replica's crash budget")
    ap.add_argument("--drain-timeout-s", type=float, default=30.0,
                    help="SIGTERM->SIGKILL grace on supervisor shutdown")
    # Router knobs (forwarded to seist_tpu.serve.router.RouterConfig).
    ap.add_argument("--router-retries", type=int, default=2)
    ap.add_argument("--request-timeout-s", type=float, default=10.0)
    ap.add_argument("--hedge-ms", type=float, default=0.0)
    ap.add_argument("--probe-interval-s", type=float, default=0.5)
    ap.add_argument("--breaker-failures", type=int, default=3)
    ap.add_argument("--breaker-cooldown-s", type=float, default=2.0)
    ap.add_argument("--fleet-scrape-interval-s", type=float, default=5.0,
                    help="how often the fleet aggregator pulls every "
                    "replica's /metrics.json (served merged on the "
                    "router port at GET /fleet/metrics[.json])")
    ap.add_argument("--rollout-file", default="",
                    help="JSON rollout spec ({'version': N, "
                    "'checkpoint'?: path, 'cmd'?: [...], 'replicas'?: "
                    "[i, ...]}) read when SIGHUP arrives: rolls the "
                    "fleet to the new model version one replica at a "
                    "time (docs/SERVING.md 'Live rollout')")
    ap.add_argument("--rollout-ready-timeout-s", type=float, default=300.0,
                    help="per-replica ready deadline during a roll; "
                    "exceeding it ABORTS the roll (capacity stays N-1, "
                    "never N-2)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="the replica command, after `--` (without "
                    "--host/--port, which the supervisor assigns)")
    args = ap.parse_args(argv)

    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no replica command (use: supervise_fleet.py [opts] -- "
                 "python main.py serve ...)")
    if args.replicas < 1:
        ap.error("--replicas must be >= 1")

    from seist_tpu.obs import trace as obs_trace
    from seist_tpu.obs.bus import BUS
    from seist_tpu.obs.fleet import FleetAggregator
    from seist_tpu.serve.router import (
        Router,
        RouterConfig,
        start_router_server,
    )

    router = Router(
        config=RouterConfig(
            retries=args.router_retries,
            request_timeout_s=args.request_timeout_s,
            hedge_ms=args.hedge_ms,
            probe_interval_s=args.probe_interval_s,
            breaker_failures=args.breaker_failures,
            breaker_cooldown_s=args.breaker_cooldown_s,
        )
    )
    slots = [
        ReplicaSlot(i, args.base_port + i, cmd)
        for i in range(args.replicas)
    ]
    # Fleet metrics pane: periodically pull every replica's /metrics.json
    # plus the (in-process) router's bus, merge counters/gauges and
    # bucket-wise histograms, serve the single aggregated view at
    # GET /fleet/metrics[.json] on the router port (docs/SERVING.md) —
    # the signal source the autoscaler and canary rollback will read.
    obs_trace.register_trace_collector()
    fleet = FleetAggregator(interval_s=args.fleet_scrape_interval_s)
    fleet.add_source("router", BUS.snapshot)
    for slot in slots:
        slot.spawn()
        router.registry.add(slot.url)
        fleet.add_source(f"replica-{slot.index}", slot.url)
    router_port = args.router_port
    if router_port == 0:
        # An ephemeral (port-0) router bind can land ON a replica's
        # pre-assigned port: the replica process may not have bound it
        # yet, so the kernel hands it out, and that replica then
        # crash-loops on EADDRINUSE until its relaunch budget retires
        # the slot. Pick the ephemeral port ourselves, excluding every
        # slot's port.
        import socket

        replica_ports = {slot.port for slot in slots}
        while True:
            probe = socket.socket()
            probe.bind((args.router_host, 0))
            router_port = probe.getsockname()[1]
            probe.close()
            if router_port not in replica_ports:
                break
    server = start_router_server(router, args.router_host, router_port)
    server.fleet = fleet
    fleet.start()
    host, port = server.server_address[:2]
    # Machine-greppable for harnesses driving an ephemeral-port fleet.
    print(f"[fleet] ROUTER=http://{host}:{port}", flush=True)
    _log(f"router on http://{host}:{port}, {len(slots)} replica(s)")

    stop = threading.Event()
    #: SIGHUP arrivals (handler does a GIL-atomic increment only —
    #: threadlint signal-handler-unsafe); the monitor loop compares
    #: against its consumed count and starts the roll itself.
    hup = {"count": 0, "seen": 0}

    def _term(signum, frame):
        stop.set()

    def _hup(signum, frame):
        hup["count"] += 1

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    signal.signal(signal.SIGHUP, _hup)

    try:
        _monitor(slots, router, args, stop, hup)
    finally:
        fleet.stop()
        _drain(slots, args.drain_timeout_s)
        server.shutdown()
        router.stop()
    live_slots = [s for s in slots if not s.retired]
    if stop.is_set():
        _log("stopped (signal)")
        return 0
    _log("stopped (all replica slots retired)" if not live_slots
         else "stopped")
    return 0 if live_slots else 1


def _probe_replica(slot: "ReplicaSlot") -> Tuple[bool, Dict[str, int]]:
    """Poll one replica's /healthz/ready directly: (ready, versions).
    The rollout's convergence check — the router's registry alone is not
    enough (its prober can lag a probe interval)."""
    from seist_tpu.serve.router import _http_request

    try:
        status, _, body = _http_request(
            slot.url, "GET", "/healthz/ready", timeout_s=2.0
        )
    except Exception:  # noqa: BLE001 — a dead/warming replica is "not yet"
        return False, {}
    try:
        payload = json.loads(body.decode())
    except (ValueError, UnicodeDecodeError):
        payload = {}
    versions = (
        payload.get("versions") if isinstance(payload, dict) else None
    )
    return status == 200, versions if isinstance(versions, dict) else {}


def _read_rollout_spec(path: str) -> Optional[dict]:
    if not path:
        _log("SIGHUP but no --rollout-file configured; ignoring")
        return None
    try:
        with open(path) as f:
            spec = json.load(f)
    except (OSError, ValueError) as e:
        _log(f"rollout file {path!r} unreadable: {e!r}; ignoring SIGHUP")
        return None
    if not isinstance(spec, dict) or "version" not in spec:
        _log(f"rollout file {path!r} needs {{'version': N}}; ignoring")
        return None
    return spec


def _monitor(
    slots: List["ReplicaSlot"], router, args, stop: threading.Event,
    hup: Optional[Dict[str, int]] = None,
) -> None:
    """Poll replica processes; restart / retire per the exit contract.
    Also advances an in-flight rolling restart (SIGHUP + --rollout-file)
    one state-machine tick per loop — crash handling for the REST of the
    fleet keeps running mid-roll."""
    rollout: Optional[FleetRollout] = None
    while not stop.is_set():
        if hup is not None and hup["count"] > hup["seen"]:
            hup["seen"] = hup["count"]
            if rollout is not None and not rollout.done:
                _log("SIGHUP during an active rollout; ignoring")
            else:
                spec = _read_rollout_spec(args.rollout_file)
                if spec is not None:
                    rollout = FleetRollout(
                        slots,
                        version=spec["version"],
                        checkpoint=spec.get("checkpoint"),
                        cmd=spec.get("cmd"),
                        subset=spec.get("replicas"),
                        ready_timeout_s=args.rollout_ready_timeout_s,
                    )
                    _log(
                        f"rollout started: version {rollout.version} over "
                        f"{len(rollout.queue)} replica(s), one at a time"
                    )
        if rollout is not None and not rollout.done:
            rollout.advance(router.registry, _probe_replica)
        active = 0
        for slot in slots:
            if slot.retired:
                continue
            active += 1
            now = time.monotonic()
            if slot.proc is None:
                # In backoff: relaunch when its clock expires.
                if slot.restart_at is not None and now >= slot.restart_at:
                    slot.spawn()
                    router.registry.add(slot.url)
                continue
            if (
                slot.failures
                and now - slot.started_at >= args.healthy_reset_s
            ):
                _log(f"replica {slot.index} healthy "
                     f"{args.healthy_reset_s:.0f}s: crash budget reset")
                slot.failures = 0
            rc = slot.proc.poll()
            if rc is None:
                continue
            slot.proc = None
            # Pull it from rotation NOW — the router should stop routing
            # to a dead port before the next health probe finds out.
            router.registry.mark_down(slot.url, reason=f"rc={rc}")
            # Streaming failover visibility: how many stations the dead
            # replica was home to. They re-home to survivors on their
            # next packet (journal restore / gap-stitch re-warm); the
            # chaos lane greps this line to time the re-home.
            homed = router.affinity.snapshot()["by_replica"].get(
                slot.url, 0
            )
            if homed:
                _log(
                    f"replica {slot.index} was stream home to {homed} "
                    "stations; re-homing to survivors"
                )
            if rc == 0:
                _log(f"replica {slot.index} exited 0 (voluntary); "
                     "slot retired")
                slot.retired = True
                router.registry.remove(slot.url)
            elif rc == PREEMPT_EXIT_CODE:
                _log(f"replica {slot.index} clean preempt (rc={rc}): "
                     "immediate relaunch, budget untouched")
                slot.spawn()
                router.registry.add(slot.url)
            else:
                slot.failures += 1
                if slot.failures > args.retries:
                    _log(f"replica {slot.index} crashed rc={rc}; budget "
                         f"exhausted ({slot.failures - 1}/{args.retries}) "
                         "— slot retired")
                    slot.retired = True
                    router.registry.remove(slot.url)
                else:
                    _log(f"replica {slot.index} crashed rc={rc}; relaunch "
                         f"in {args.backoff:.1f}s "
                         f"(budget {slot.failures}/{args.retries})")
                    slot.restart_at = now + args.backoff
        if active == 0:
            return  # every slot retired: the fleet is gone
        stop.wait(0.2)


def _drain(slots: List["ReplicaSlot"], timeout_s: float) -> None:
    """SIGTERM every live replica (graceful drain, expect exit 75), then
    SIGKILL stragglers after the grace period."""
    live = [s for s in slots if s.proc is not None and s.proc.poll() is None]
    for slot in live:
        try:
            slot.proc.send_signal(signal.SIGTERM)
        except OSError:
            pass
    deadline = time.monotonic() + timeout_s
    for slot in live:
        remaining = max(0.1, deadline - time.monotonic())
        try:
            rc = slot.proc.wait(timeout=remaining)
            _log(f"replica {slot.index} drained (rc={rc})")
        except subprocess.TimeoutExpired:
            _log(f"replica {slot.index} did not drain in "
                 f"{timeout_s:.0f}s; SIGKILL")
            slot.proc.kill()
            slot.proc.wait()


if __name__ == "__main__":
    sys.exit(main())
