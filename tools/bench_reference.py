"""Measure the torch reference's training throughput (host CPU).

The reference repo publishes no benchmark numbers (BASELINE.md) and this
environment has no GPU, so the comparison baseline for bench.py is the
reference's own training step (forward + BCE loss + backward + Adam) timed on
this host's CPU. The reference code is *imported* from /root/reference at
runtime (never copied); its `timm` dependency is satisfied with a minimal
stub since only `timm.models.layers.DropPath` is used (reference
models/seist.py:7).

Writes tools/reference_baseline.json consumed by bench.py.

Usage: python tools/bench_reference.py [--batch 32] [--steps 5]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import types

REFERENCE = "/root/reference"


def _install_timm_stub() -> None:
    import torch.nn as nn

    class DropPath(nn.Module):
        """Stochastic depth (per-sample residual drop), the standard
        implementation every library ships."""

        def __init__(self, drop_prob: float = 0.0):
            super().__init__()
            self.drop_prob = float(drop_prob)

        def forward(self, x):
            if self.drop_prob == 0.0 or not self.training:
                return x
            keep = 1.0 - self.drop_prob
            shape = (x.shape[0],) + (1,) * (x.ndim - 1)
            mask = x.new_empty(shape).bernoulli_(keep)
            return x * mask / keep

    timm = types.ModuleType("timm")
    models = types.ModuleType("timm.models")
    layers = types.ModuleType("timm.models.layers")
    layers.DropPath = DropPath
    models.layers = layers
    timm.models = models
    sys.modules["timm"] = timm
    sys.modules["timm.models"] = models
    sys.modules["timm.models.layers"] = layers


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="seist_l_dpk")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--in-samples", type=int, default=8192)
    args = ap.parse_args()

    import torch

    _install_timm_stub()
    sys.path.insert(0, REFERENCE)
    from models import create_model  # reference models/_factory.py

    model = create_model(args.model, in_channels=3, in_samples=args.in_samples)
    model.train()
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)

    x = torch.randn(args.batch, 3, args.in_samples)
    y = torch.zeros(args.batch, 3, args.in_samples)
    y[:, 0, :] = 1.0  # det on
    y[:, 1, args.in_samples // 4] = 1.0
    y[:, 2, args.in_samples // 2] = 1.0
    weights = torch.tensor([[0.5], [1.0], [1.0]])

    def step():
        opt.zero_grad()
        out = model(x)
        eps = 1e-6
        loss = -(
            y * torch.log(out + eps) + (1 - y) * torch.log(1 - out + eps)
        )
        loss = (loss * weights).mean()
        loss.backward()
        opt.step()
        return loss

    step()  # warmup
    t0 = time.perf_counter()
    for _ in range(args.steps):
        step()
    dt = time.perf_counter() - t0
    wfs = args.batch * args.steps / dt

    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "reference_baseline.json")
    payload = {
        "model": args.model,
        "waveforms_per_sec": round(wfs, 2),
        "hardware": f"host CPU ({os.cpu_count()} cores), torch {torch.__version__}",
        "batch": args.batch,
        "steps": args.steps,
        "in_samples": args.in_samples,
        "note": "torch reference train step timed on host CPU (no GPU in env; "
        "reference publishes no numbers)",
    }
    with open(out_path, "w") as f:
        json.dump(payload, f, indent=2)
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
