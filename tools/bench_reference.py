"""Measure the torch reference's training throughput (host CPU).

The reference repo publishes no benchmark numbers (BASELINE.md) and this
environment has no GPU, so the comparison baseline for bench.py is the
reference's own training step (forward + BCE loss + backward + Adam) timed on
this host's CPU. The reference code is *imported* from /root/reference at
runtime (never copied); its `timm` dependency is satisfied with a minimal
stub since only `timm.models.layers.DropPath` is used (reference
models/seist.py:7).

Writes tools/reference_baseline.json consumed by bench.py (per_model
entries keyed by model name; each stamped with its session's host/torch).

Usage: python tools/bench_reference.py \
    [--models seist_l_dpk,phasenet,...] [--batch 16] [--steps 4]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import types

REFERENCE = "/root/reference"


def _install_timm_stub() -> None:
    import torch.nn as nn

    class DropPath(nn.Module):
        """Stochastic depth (per-sample residual drop), the standard
        implementation every library ships.

        ``DropPath.inject`` (class attribute) mirrors seist_tpu's
        droppath_mask_injection (models/common.py): when set to
        ``{"uniforms": (max_calls, batch) tensor, "i": 0}``, each
        train-mode call consumes the next row as its uniform draws —
        identical rows in identical call order on both frameworks make
        the dropped residual paths identical (tools/train_dynamics.py
        dropout-on lane)."""

        inject = None  # class-level: one shared stream per forward

        def __init__(self, drop_prob: float = 0.0):
            super().__init__()
            self.drop_prob = float(drop_prob)

        def forward(self, x):
            if self.drop_prob == 0.0 or not self.training:
                return x
            keep = 1.0 - self.drop_prob
            shape = (x.shape[0],) + (1,) * (x.ndim - 1)
            if DropPath.inject is not None:
                inj = DropPath.inject
                u = inj["uniforms"][inj["i"]]
                inj["i"] += 1
                mask = (u < keep).to(x.dtype).view(shape)
            else:
                mask = x.new_empty(shape).bernoulli_(keep)
            return x * mask / keep

    timm = types.ModuleType("timm")
    models = types.ModuleType("timm.models")
    layers = types.ModuleType("timm.models.layers")
    layers.DropPath = DropPath
    models.layers = layers
    timm.models = models
    sys.modules["timm"] = timm
    sys.modules["timm.models"] = models
    sys.modules["timm.models.layers"] = layers


def _dpk_loss(torch, batch, in_samples):
    """BCE on probability outputs, dpk weights (ref config.py:138)."""
    y = torch.zeros(batch, 3, in_samples)
    y[:, 0, :] = 1.0
    y[:, 1, in_samples // 4] = 1.0
    y[:, 2, in_samples // 2] = 1.0
    w = torch.tensor([[0.5], [1.0], [1.0]])
    eps = 1e-6

    def loss_fn(out):
        loss = -(y * torch.log(out + eps) + (1 - y) * torch.log(1 - out + eps))
        return (loss * w).mean()

    return loss_fn


def _ce_loss(torch, batch, in_samples):
    """CE on softmax outputs (phasenet, ref config.py:68-71)."""
    y = torch.zeros(batch, 3, in_samples)
    y[:, 0, :] = 1.0
    eps = 1e-6
    return lambda out: -(y * torch.log(out + eps)).mean()


def _tuple_bce_loss(torch, out, batch, in_samples):
    """Per-output BCE mean (eqtransformer's (det, p, s) triple — surrogate
    with the same tensor structure/shapes as ref CombinationLoss)."""
    ys = [torch.zeros_like(o) for o in out]
    eps = 1e-6

    def loss_fn(out):
        total = 0.0
        for o, y in zip(out, ys):
            total = total + (
                -(y * torch.log(o + eps) + (1 - y) * torch.log(1 - o + eps))
            ).mean()
        return total / len(out)

    return loss_fn


def _measure(model_name: str, batch: int, steps: int, in_samples: int) -> dict:
    import torch

    from models import create_model  # reference models/_factory.py

    model = create_model(model_name, in_channels=3, in_samples=in_samples)
    model.train()
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    x = torch.randn(batch, 3, in_samples)

    with torch.no_grad():  # structure probe only — keep no autograd graph
        out0 = model(x)
    if isinstance(out0, (tuple, list)):
        loss_fn = _tuple_bce_loss(torch, out0, batch, in_samples)
    elif model_name == "phasenet":
        loss_fn = _ce_loss(torch, batch, in_samples)
    else:
        loss_fn = _dpk_loss(torch, batch, in_samples)
    del out0

    def step():
        opt.zero_grad()
        out = model(x)
        loss = loss_fn(out)
        loss.backward()
        opt.step()

    step()  # warmup
    t0 = time.perf_counter()
    for _ in range(steps):
        step()
    dt = time.perf_counter() - t0
    return {
        "waveforms_per_sec": round(batch * steps / dt, 2),
        "batch": batch,
        "steps": steps,
        "in_samples": in_samples,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", default="seist_l_dpk",
                    help="comma-separated reference model names")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--in-samples", type=int, default=8192)
    args = ap.parse_args()

    import torch

    _install_timm_stub()
    sys.path.insert(0, REFERENCE)

    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "reference_baseline.json")
    payload = {}
    if os.path.exists(out_path):
        with open(out_path) as f:
            payload = json.load(f)
    # Overwritten each run; per-entry stamps below are the durable record
    # (a later session on different hardware must not masquerade as the
    # one that measured the other entries).
    hardware = f"host CPU ({os.cpu_count()} cores), torch {torch.__version__}"
    session = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    payload["hardware"] = hardware
    payload["note"] = (
        "torch reference train step timed on host CPU (no GPU in env; "
        "reference publishes no numbers); compare per_model entries only "
        "within one hardware/session stamp"
    )
    per_model = payload.setdefault("per_model", {})
    for name in args.models.split(","):
        entry = _measure(name, args.batch, args.steps, args.in_samples)
        entry["hardware"] = hardware
        entry["session"] = session
        per_model[name] = entry
        print(name, json.dumps(entry), flush=True)
        with open(out_path, "w") as f:  # persist incrementally
            json.dump(payload, f, indent=2)


if __name__ == "__main__":
    main()
