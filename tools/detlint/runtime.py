"""detlint runtime lane: replay-divergence helpers (the LockGraph
analogue — docs/STATIC_ANALYSIS.md "Determinism analysis").

Static rules prove the ABSENCE of known hazard patterns; this module
provides the primitives ``tools/replay_smoke.py`` composes to prove the
PRESENCE of the actual contract: run the pack -> resume -> repick ->
journal-restore pipeline twice under perturbation (different
``PYTHONHASHSEED``, different worker counts, shuffled directory inode
order) and pin every digest byte-identical.

* :func:`digest_tree` — sha256 per file under a root, keyed by posix
  relpath, enumerated in SORTED order (the harness must not itself have
  the bug it hunts).
* :func:`relink_tree` — re-materialize a directory tree with directory-
  entry CREATION order reversed (hard links when possible, copies as
  fallback). On the filesystems this repo meets in practice, readdir
  order follows entry creation order closely enough that an unsorted
  ``os.listdir`` consumer sees a DIFFERENT sequence over the relinked
  tree — the cheapest portable approximation of "same bytes, different
  inode order" there is. A consumer that sorts is invariant either way,
  which is exactly the property under test; on filesystems where
  readdir order is name-hash-ordered the shim degrades to a no-op
  (same-bytes copy), never to a false failure.
* :func:`combine` — one hex digest over a digest map, for one-line
  verdicts.

Everything here is stdlib-only and import-light: the replay children
pay for jax exactly once each, in the repick phase, not at helper
import time.
"""

from __future__ import annotations

import hashlib
import os
import shutil
from typing import Dict, Iterable, Optional, Sequence

__all__ = ["combine", "digest_file", "digest_tree", "relink_tree"]


def digest_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def digest_tree(
    root: str, suffixes: Optional[Sequence[str]] = None
) -> Dict[str, str]:
    """{posix relpath: sha256} for every file under ``root`` (optionally
    filtered to ``suffixes``), walked in sorted order. Dotfiles are
    skipped: in-flight atomic-write temporaries (``.foo.tmp``) are not
    part of any contract."""
    out: Dict[str, str] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if not d.startswith("."))
        for fn in sorted(filenames):
            if fn.startswith("."):
                continue
            if suffixes and not any(fn.endswith(s) for s in suffixes):
                continue
            ap = os.path.join(dirpath, fn)
            rel = os.path.relpath(ap, root).replace(os.sep, "/")
            out[rel] = digest_file(ap)
    return out


def combine(digests: Dict[str, str]) -> str:
    """One canonical digest over a digest map (sorted key order — the
    map's own iteration order must never matter)."""
    h = hashlib.sha256()
    for k in sorted(digests):
        h.update(f"{k}={digests[k]}\n".encode())
    return h.hexdigest()


def relink_tree(src: str, dst: str) -> int:
    """Rebuild ``src`` under ``dst`` with per-directory entry creation
    order REVERSED relative to sorted-name order; returns the file
    count. Hard links preserve bytes for free; cross-device falls back
    to copy."""
    n = 0
    for dirpath, dirnames, filenames in os.walk(src):
        dirnames[:] = sorted(d for d in dirnames if not d.startswith("."))
        rel = os.path.relpath(dirpath, src)
        ddir = dst if rel == "." else os.path.join(dst, rel)
        os.makedirs(ddir, exist_ok=True)
        for fn in sorted(
            (f for f in filenames if not f.startswith(".")), reverse=True
        ):
            sp = os.path.join(dirpath, fn)
            dp = os.path.join(ddir, fn)
            try:
                os.link(sp, dp)
            except OSError:
                shutil.copy2(sp, dp)
            n += 1
    return n
