"""detlint rule catalog — determinism & reproducibility hazards, repo-tuned.

Every rule is a pure function of one
:class:`~tools.jaxlint.engine.ModuleInfo`. Nearly every load-bearing
contract in this repo is a determinism contract — plan-first N-worker
bit-identical packing, byte-identical repick catalogs across kill/resume
histories, journal restore parity, deterministic alert IDs — and this
catalog encodes the bug classes that silently break those contracts on a
*different machine* while passing every test on this one:

* **Filesystem order is not an order** (`unsorted-dir-enumeration`):
  ``os.listdir``/``glob.glob``/``Path.iterdir`` return inode order, which
  differs across filesystems, mounts, and rsync histories. Any consumer
  that is not provably order-insensitive (``sorted``/``set``/``len``/
  membership/emptiness tests) is flagged. Simple local dataflow follows a
  result assigned to a name: the name is exempt only if EVERY use in its
  scope is order-insensitive (``names = os.listdir(p)`` later consumed
  inside ``sorted(...)`` passes; ``dumps[0]`` on an unsorted glob fails).
* **Global RNG state is a hidden input** (`unseeded-rng`): module-level
  ``np.random.*`` / stdlib ``random.*`` draws depend on whoever seeded
  (or forgot to seed) the process; zero-arg ``default_rng()`` /
  ``RandomState()`` are OS-entropy seeded; ``jax.random.PRNGKey(time...)``
  launders wall-clock into the key tree. Registered seed plumbing
  (``*.seed(...)``, constructing seeded generators) is exempt.
* **Wall-clock reaches data** (`wallclock-in-deterministic-path`): in
  modules declared determinism-critical (:data:`DET_PATH_GLOBS`),
  ``time.time()``/``datetime.now()`` taint anything they touch — shard
  metadata, catalog rows, alert IDs. Telemetry-only functions opt out via
  the ``@telemetry_only`` decorator (seist_tpu/utils/determinism.py);
  ``time.monotonic``/``perf_counter`` are exempt BY DESIGN — interval
  measurement never serializes an absolute timestamp.
* **Set iteration order is hash order** (`set-or-dict-order-dependence`):
  iterating a set (or materializing one via ``list(set(...))`` — the
  classic dedup-order bug) feeds PYTHONHASHSEED-dependent order into
  whatever consumes it; ``dict.keys()`` piped straight into a digest or
  ``join`` serializes insertion order. Both flagged unless sorted first.
* **Float addition is not associative** (`float-reduction-order`): a
  Python ``sum()`` over floats in a det-critical module changes in the
  last ulp when pairing order changes — exactly what varies with worker
  count. ``math.fsum`` (exact) or a stacked ``np.sum`` are the fixes.
* **Environment is configuration, not entropy** (`env-dependent-default`):
  an ``os.environ`` read in a det-critical module is a machine-dependent
  default unless the variable is REGISTERED (:data:`REGISTERED_ENV`) —
  registration means docs/DATA.md or docs/FAULT_TOLERANCE.md names it as
  part of the run's recorded configuration.

Known soundness limits (documented, accepted): aliasing ``env =
os.environ`` hides reads from `env-dependent-default`; an enumeration
passed across a function boundary before sorting is invisible to the
local dataflow; ``time.monotonic`` persisted to disk would be a real bug
the wallclock rule cannot see. The replay lane (tools/replay_smoke.py)
exists to catch dynamically what these static limits miss.

False positives are expected to be rare and cheap: suppress inline with
``# detlint: disable=<rule> -- <rationale>``. The baseline
(tools/detlint_baseline.json) is EMPTY BY CONSTRUCTION — the frontend
refuses --update-baseline while it is empty. See docs/STATIC_ANALYSIS.md
"Determinism analysis".
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.jaxlint.engine import Finding, ModuleInfo
from tools.jaxlint.rules import Rule

#: Modules whose outputs are pinned byte-identical by a repo contract
#: (docs/DATA.md pack/resume/repick, docs/FAULT_TOLERANCE.md journal
#: restore + alert dedup). Rules marked "det-path only" fire nowhere
#: else: wall-clock in a bench harness is telemetry, wall-clock in the
#: catalog merge is a broken contract.
DET_PATH_GLOBS = (
    "seist_tpu/data/*.py",
    "seist_tpu/batch/*.py",
    "seist_tpu/stream/journal.py",
    "seist_tpu/stream/session.py",
    "seist_tpu/stream/assoc.py",
    "tools/pack_dataset.py",
    "tools/repick_archive.py",
)

#: Environment variables a det-path module MAY read: each is recorded
#: run configuration (docs name it, smoke lanes pin it) rather than
#: ambient machine state. Extend this registry — with a docs cross-ref —
#: instead of suppressing inline when a variable becomes part of the
#: recorded contract.
REGISTERED_ENV_EXACT = frozenset(
    (
        "SEIST_IO_GUARD",  # docs/FAULT_TOLERANCE.md — guard on/off switch
        "SEIST_BATCH_WORKER",  # docs/FAULT_TOLERANCE.md — fleet worker index
        "SEIST_INGEST_REUSE_STAGING",  # docs/DATA.md — staging reuse mode
        "PYTHONHASHSEED",  # the replay lane's own perturbation axis
        "JAX_PLATFORMS",  # backend pin, recorded by every smoke lane
        "TMPDIR",  # staging root; never reaches bytes on disk
        "HOME",  # cache roots only
    )
)
REGISTERED_ENV_PREFIXES = (
    "SEIST_FAULT_",  # fault injection — docs/FAULT_TOLERANCE.md registry
    "SEIST_IO_",  # io_guard retry/backoff knobs — docs/FAULT_TOLERANCE.md
    "SEIST_LEASE_",  # batch-fleet lease plane — docs/FAULT_TOLERANCE.md
)

#: Builtins whose value is independent of input ordering — an enumeration
#: consumed ONLY through these is safe unsorted. ``sum`` is deliberately
#: absent: integer sums are order-independent but float sums are not, and
#: statically we cannot tell which we have.
_ORDER_INSENSITIVE_FUNCS = frozenset(
    ("sorted", "set", "frozenset", "len", "any", "all", "max", "min", "bool")
)

_ENUM_EXACT = frozenset(
    ("os.listdir", "os.scandir", "glob.glob", "glob.iglob")
)
_ENUM_PATH_ATTRS = frozenset(("iterdir", "glob", "rglob"))

_TELEMETRY_DECORATOR = "telemetry_only"


def _is_det_path(path: str) -> bool:
    return any(fnmatch.fnmatch(path, g) for g in DET_PATH_GLOBS)


def _subtree_contains(root: ast.AST, node: ast.AST) -> bool:
    return any(n is node for n in ast.walk(root))


def _consumed_order_insensitively(info: ModuleInfo, node: ast.AST) -> bool:
    """True when ``node``'s value provably cannot leak ordering: wrapped
    (at any ancestor depth) in an order-insensitive builtin, used as a
    membership-test operand, or used only as a truthiness test."""
    for a in info.ancestors(node):
        if isinstance(a, ast.Call):
            fname = info.dotted_name(a.func)
            if fname in _ORDER_INSENSITIVE_FUNCS:
                return True
        elif isinstance(a, ast.Compare):
            if any(isinstance(op, (ast.In, ast.NotIn)) for op in a.ops):
                return True
        elif isinstance(a, (ast.If, ast.While)):
            if _subtree_contains(a.test, node):
                return True
        elif isinstance(a, ast.IfExp):
            if _subtree_contains(a.test, node):
                return True
        elif isinstance(a, ast.Assert):
            return True
        elif isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Scope boundary: stop the walk — an enclosing call in an
            # OUTER scope never receives this value.
            return False
    return False


def _scope_of(info: ModuleInfo, node: ast.AST) -> ast.AST:
    fn = info.enclosing_function(node)
    return fn if fn is not None else info.tree


def _name_loads(scope: ast.AST, name: str) -> List[ast.Name]:
    return [
        n
        for n in ast.walk(scope)
        if isinstance(n, ast.Name)
        and n.id == name
        and isinstance(n.ctx, ast.Load)
    ]


def _in_telemetry_fn(info: ModuleInfo, node: ast.AST) -> bool:
    for a in info.ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in a.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                dotted = info.dotted_name(target)
                if dotted.split(".")[-1] == _TELEMETRY_DECORATOR:
                    return True
    return False


class UnsortedDirEnumeration(Rule):
    name = "unsorted-dir-enumeration"
    summary = (
        "os.listdir/glob/iterdir result consumed order-sensitively "
        "without sorted() — filesystem inode order differs across machines"
    )
    hint = (
        "wrap the enumeration in sorted(...); if the consumer is provably "
        "order-insensitive, suppress with a rationale"
    )

    def _is_enum_call(self, info: ModuleInfo, node: ast.Call) -> bool:
        dotted = info.dotted_name(node.func)
        if dotted in _ENUM_EXACT:
            return True
        return (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _ENUM_PATH_ATTRS
            and not dotted.startswith("glob.")
        )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            if not self._is_enum_call(info, node):
                continue
            if _consumed_order_insensitively(info, node):
                continue
            # Local dataflow: `names = os.listdir(p)` is exempt iff EVERY
            # later use of `names` in this scope is order-insensitive.
            parent = info.parents.get(node)
            if (
                isinstance(parent, ast.Assign)
                and parent.value is node
                and len(parent.targets) == 1
                and isinstance(parent.targets[0], ast.Name)
            ):
                name = parent.targets[0].id
                scope = _scope_of(info, parent)
                loads = _name_loads(scope, name)
                if loads and all(
                    _consumed_order_insensitively(info, n) for n in loads
                ):
                    continue
            call_name = info.dotted_name(node.func) or (
                f".{node.func.attr}(...)"
                if isinstance(node.func, ast.Attribute)
                else "enumeration"
            )
            yield self.finding(
                info,
                node,
                f"{call_name} returns filesystem order — consumers see a "
                "different sequence on a different machine",
            )


_NP_RANDOM_PREFIXES = ("np.random.", "numpy.random.")
#: np.random attrs that are seed plumbing or seeded-generator
#: construction, not global-state draws.
_NP_RANDOM_ALLOWED = frozenset(
    (
        "default_rng",
        "Generator",
        "RandomState",
        "SeedSequence",
        "PCG64",
        "Philox",
        "MT19937",
        "SFC64",
        "seed",
        "get_state",
        "set_state",
    )
)
#: zero-arg constructors fall back to OS entropy — unseeded by definition
_NP_NEED_SEED = frozenset(("default_rng", "RandomState", "SeedSequence"))
_STD_RANDOM_ALLOWED = frozenset(
    ("seed", "Random", "SystemRandom", "getstate", "setstate")
)
_NONDET_KEY_SOURCES = frozenset(
    (
        "time.time",
        "time.time_ns",
        "os.urandom",
        "os.getpid",
        "uuid.uuid4",
        "uuid.uuid1",
    )
)


class UnseededRng(Rule):
    name = "unseeded-rng"
    summary = (
        "global-state or OS-entropy RNG (np.random.* / random.* draws, "
        "zero-arg default_rng(), PRNGKey from wall-clock)"
    )
    hint = (
        "thread a seeded np.random.Generator (default_rng(seed)) or a "
        "jax PRNG key from the run's root seed; global seeding belongs "
        "in utils.misc.seed_everything only"
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = info.dotted_name(node.func)
            if not dotted:
                continue
            head, _, _ = dotted.partition(".")
            tail = dotted.rsplit(".", 1)[-1]

            if dotted.startswith(_NP_RANDOM_PREFIXES):
                if tail in _NP_NEED_SEED and not node.args:
                    yield self.finding(
                        info,
                        node,
                        f"{dotted}() with no seed draws OS entropy — "
                        "results differ on every run",
                    )
                elif tail not in _NP_RANDOM_ALLOWED:
                    yield self.finding(
                        info,
                        node,
                        f"{dotted} draws from numpy's GLOBAL rng state — "
                        "a hidden input seeded (or not) by whoever ran "
                        "first",
                    )
                continue

            if head == "random" and "random" not in info.jax_random_aliases:
                if tail not in _STD_RANDOM_ALLOWED:
                    yield self.finding(
                        info,
                        node,
                        f"{dotted} draws from the stdlib GLOBAL rng state",
                    )
                continue

            if tail in ("PRNGKey", "key") and (
                head in info.jax_random_aliases
                or dotted.startswith("jax.random.")
            ):
                for arg in node.args:
                    for sub in ast.walk(arg):
                        if (
                            isinstance(sub, ast.Call)
                            and info.dotted_name(sub.func)
                            in _NONDET_KEY_SOURCES
                        ):
                            yield self.finding(
                                info,
                                node,
                                f"{dotted} seeded from "
                                f"{info.dotted_name(sub.func)}() — the "
                                "key tree is not reproducible",
                            )
                            break


_WALLCLOCK_CALLS = frozenset(
    (
        "time.time",
        "time.time_ns",
        "time.ctime",
        "time.localtime",
        "time.gmtime",
        "time.strftime",
        "datetime.datetime.now",
        "datetime.datetime.utcnow",
        "datetime.datetime.today",
        "datetime.now",
        "datetime.utcnow",
        "datetime.today",
        "datetime.date.today",
        "date.today",
    )
)


class WallclockInDeterministicPath(Rule):
    name = "wallclock-in-deterministic-path"
    summary = (
        "time.time()/datetime.now() in a determinism-critical module "
        "(DET_PATH_GLOBS) outside a @telemetry_only function"
    )
    hint = (
        "pass timestamps in from the caller, or mark the enclosing "
        "function @telemetry_only (seist_tpu.utils.determinism) if the "
        "value never reaches shard bytes, catalog rows, or IDs; "
        "time.monotonic/perf_counter are already exempt for intervals"
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        if not _is_det_path(info.path):
            return
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = info.dotted_name(node.func)
            if dotted not in _WALLCLOCK_CALLS:
                continue
            if _in_telemetry_fn(info, node):
                continue
            yield self.finding(
                info,
                node,
                f"{dotted}() in det-critical module {info.path} — "
                "wall-clock taints anything it touches (shard meta, "
                "catalog rows, alert IDs)",
            )


def _is_setish(info: ModuleInfo, node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    return isinstance(node, ast.Call) and info.dotted_name(node.func) in (
        "set",
        "frozenset",
    )


def _is_dict_view(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in ("keys", "values", "items")
        and not node.args
    )


_ORDERING_SINKS = frozenset(("list", "tuple", "enumerate", "iter", "reversed"))


class SetOrDictOrderDependence(Rule):
    name = "set-or-dict-order-dependence"
    summary = (
        "set iteration order (hash order) or dict-view bytes reaching "
        "an ordered consumer — list(set(...)), for-over-set, "
        "''.join(keys()), digests"
    )
    hint = (
        "sorted(set(...)) fixes both dedup and order; serialize dicts "
        "with sort_keys=True or json-canonical helpers"
    )

    def _sink_of_setish(
        self, info: ModuleInfo, node: ast.AST
    ) -> Optional[str]:
        parent = info.parents.get(node)
        # direct iteration
        if isinstance(parent, (ast.For, ast.AsyncFor)) and parent.iter is node:
            return "for-loop iteration"
        if isinstance(parent, ast.comprehension) and parent.iter is node:
            return "comprehension iteration"
        if isinstance(parent, ast.Call) and node in parent.args:
            fname = info.dotted_name(parent.func)
            if fname in _ORDERING_SINKS:
                return f"{fname}(...)"
            if (
                isinstance(parent.func, ast.Attribute)
                and parent.func.attr == "join"
            ):
                return "str.join"
            if fname.startswith("hashlib."):
                return fname
        return None

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if _is_setish(info, node):
                if _consumed_order_insensitively(info, node):
                    continue
                sink = self._sink_of_setish(info, node)
                if sink is not None:
                    yield self.finding(
                        info,
                        node,
                        f"set iteration order feeds {sink} — hash order "
                        "varies with PYTHONHASHSEED and across processes",
                    )
            elif _is_dict_view(node):
                parent = info.parents.get(node)
                if not (
                    isinstance(parent, ast.Call) and node in parent.args
                ):
                    continue
                fname = info.dotted_name(parent.func)
                is_join = (
                    isinstance(parent.func, ast.Attribute)
                    and parent.func.attr == "join"
                )
                if is_join or fname.startswith("hashlib."):
                    yield self.finding(
                        info,
                        node,
                        f".{node.func.attr}() serialized via "
                        f"{'str.join' if is_join else fname} — insertion "
                        "order becomes output bytes; sort first",
                    )


class FloatReductionOrder(Rule):
    name = "float-reduction-order"
    summary = (
        "builtin sum() over float terms in a det-critical module — "
        "pairing order (worker count, chunking) changes the last ulp"
    )
    hint = (
        "math.fsum(...) is exactly rounded regardless of order; or stack "
        "into one array and np.sum with a fixed reduction shape"
    )

    @staticmethod
    def _float_evidence(arg: ast.AST) -> Optional[str]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Div):
                return "division in the summand"
            if isinstance(sub, ast.Constant) and isinstance(sub.value, float):
                return f"float literal {sub.value!r}"
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == "float"
            ):
                return "float(...) in the summand"
        return None

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        if not _is_det_path(info.path):
            return
        for node in ast.walk(info.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "sum"
                and node.args
            ):
                continue
            evidence = self._float_evidence(node.args[0])
            if evidence is None:
                continue
            yield self.finding(
                info,
                node,
                f"sum() over floats ({evidence}) — float addition is not "
                "associative, so the result depends on pairing order",
            )


class EnvDependentDefault(Rule):
    name = "env-dependent-default"
    summary = (
        "os.environ read in a det-critical module for a variable not in "
        "the REGISTERED_ENV registry"
    )
    hint = (
        "register the variable in tools/detlint/rules.py REGISTERED_ENV_* "
        "with a docs cross-ref (it becomes recorded run configuration), "
        "or thread the value through explicit config"
    )

    @staticmethod
    def _registered(name: str) -> bool:
        return name in REGISTERED_ENV_EXACT or any(
            name.startswith(p) for p in REGISTERED_ENV_PREFIXES
        )

    def _env_read_name(
        self, info: ModuleInfo, node: ast.AST
    ) -> Optional[Tuple[ast.AST, Optional[str]]]:
        """(node-to-report, var-name-or-None) for environ reads; None
        var-name means the name is not a literal."""
        if isinstance(node, ast.Call):
            dotted = info.dotted_name(node.func)
            if dotted in ("os.getenv", "os.environ.get") and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    return node, arg.value
                return node, None
        elif isinstance(node, ast.Subscript):
            if info.dotted_name(node.value) == "os.environ":
                sl = node.slice
                if isinstance(sl, ast.Constant) and isinstance(
                    sl.value, str
                ):
                    return node, sl.value
                return node, None
        return None

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        if not _is_det_path(info.path):
            return
        for node in ast.walk(info.tree):
            hit = self._env_read_name(info, node)
            if hit is None:
                continue
            report, name = hit
            if name is None:
                yield self.finding(
                    info,
                    report,
                    "environ read with a non-literal variable name — "
                    "cannot be checked against the registry",
                )
            elif not self._registered(name):
                yield self.finding(
                    info,
                    report,
                    f"environ read of unregistered {name!r} in a "
                    "det-critical module — behavior now depends on "
                    "ambient machine state",
                )


RULES: Tuple[Rule, ...] = (
    UnsortedDirEnumeration(),
    UnseededRng(),
    WallclockInDeterministicPath(),
    SetOrDictOrderDependence(),
    FloatReductionOrder(),
    EnvDependentDefault(),
)

RULES_BY_NAME: Dict[str, Rule] = {r.name: r for r in RULES}
