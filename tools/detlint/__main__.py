"""detlint CLI — the jaxlint frontend bound to the determinism catalog.

    python -m tools.detlint                       # gate the default surface
    python -m tools.detlint seist_tpu/data        # subset
    python -m tools.detlint --no-baseline         # everything
    python -m tools.detlint --list-rules

Exit codes: 0 clean (vs baseline), 1 new findings, 2 usage/parse error.
The baseline (tools/detlint_baseline.json) is EMPTY BY CONSTRUCTION:
--update-baseline REFUSES to write while it is empty — fix the code or
add a rationale'd ``# detlint: disable`` instead of grandfathering.
"""

from __future__ import annotations

import os
import sys

from tools.detlint.rules import RULES, RULES_BY_NAME
from tools.jaxlint.__main__ import run

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_DEFAULT_BASELINE = os.path.join(
    _REPO_ROOT, "tools", "detlint_baseline.json"
)

#: The gated surface when no paths are given: the whole library plus the
#: tools the contracts run through (pack/repick/bench drivers). Matches
#: what `make lint` feeds the combined runner.
DEFAULT_PATHS = ("seist_tpu", "tools")


def main(argv=None) -> int:
    return run(
        argv,
        tag="detlint",
        catalog=RULES,
        rules_by_name=RULES_BY_NAME,
        default_baseline=_DEFAULT_BASELINE,
        docs="docs/STATIC_ANALYSIS.md §Determinism analysis",
        example_paths="seist_tpu tools",
        refuse_empty_baseline_update=True,
        default_paths=DEFAULT_PATHS,
    )


if __name__ == "__main__":
    sys.exit(main())
