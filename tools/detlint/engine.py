"""detlint engine: the shared lint framework (tools/jaxlint/engine.py)
bound to the ``detlint`` suppression tag and rule catalog.

Everything structural — :class:`ModuleInfo`, rationale-required
suppressions, the line-shift-proof :class:`Baseline`, file iteration —
IS jaxlint's engine; the analyzers differ only in tag and rules, so a
``# jaxlint: disable`` / ``# threadlint: disable`` comment can never
silence a detlint finding (and vice versa) while the grammar and
workflow stay identical.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from tools.jaxlint import engine as _engine
from tools.jaxlint.engine import (  # noqa: F401  (re-exported surface)
    META_RULES,
    Baseline,
    Finding,
    ModuleInfo,
    Suppression,
    iter_python_files,
)

TAG = "detlint"


def parse_suppressions(info: ModuleInfo):
    return _engine.parse_suppressions(info, TAG)


def lint_source(
    source: str,
    path: str = "<string>",
    rules: Optional[Sequence] = None,
) -> List[Finding]:
    from tools.detlint.rules import RULES

    return _engine.lint_source(source, path, rules, tag=TAG, catalog=RULES)


def lint_paths(
    paths: Sequence[str],
    root: Optional[str] = None,
    rules: Optional[Sequence] = None,
) -> List[Finding]:
    from tools.detlint.rules import RULES

    return _engine.lint_paths(paths, root, rules, tag=TAG, catalog=RULES)
