"""detlint — determinism & reproducibility static analysis.

The fourth analyzer of the jaxlint/threadlint/irlint family (same
engine, same rationale-required suppression grammar via
``# detlint: disable=<rule> -- <rationale>``, same line-shift-proof
baseline — empty by construction, like irlint's and threadlint's),
aimed at the bug class every byte-identity contract in this repo is
exposed to: unsorted directory enumeration, global/unseeded RNG state,
wall-clock leaking into det-critical modules, set/dict iteration order,
float reduction order, and unregistered environment reads.

``tools/replay_smoke.py`` adds the runtime replay lane (the lockgraph
analogue): pack -> resume -> repick -> journal-restore run twice under
perturbation (PYTHONHASHSEED, worker count, shuffled directory inode
order) with every digest pinned byte-identical. See
docs/STATIC_ANALYSIS.md "Determinism analysis".
"""

from tools.detlint.engine import lint_paths, lint_source  # noqa: F401
