"""jaxlint runtime audit lane: compile budgets and tracer-leak checks.

Static rules catch hazards that are visible in source; this module catches
the ones that only materialize at run time:

* :class:`CompileBudget` — counts every jit trace/compile while active,
  attributed to the jitted function's name and argument-shape signature
  (via the ``jax_log_compiles`` log stream; ``jax.monitoring`` backend
  compile events are tallied as a cross-check). The train-step invariant
  "compiles once per shape bucket" becomes an assertion instead of a
  mysterious slowdown.
* :func:`tracer_leak_check` — scoped ``jax.check_tracer_leaks`` for the
  smoke lane (`pytest -m smoke --tracer-leaks`).

Counting traces (not just backend compiles) is deliberate: with the
persistent XLA compile cache enabled (tests/conftest.py), a retrace can hit
the disk cache and skip the expensive backend compile while still burning
seconds of lowering per step — the budget must catch that too.
"""

from __future__ import annotations

import logging
import re
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Tuple

import jax

# "Compiling <fn> with global shapes and types [...]. Argument mapping: ..."
# (jax._src.interpreters.pxla, emitted at WARNING when jax_log_compiles is
# on — one record per trace+lower, including persistent-cache hits).
_COMPILING_RE = re.compile(
    r"Compiling ([^\s]+) with global shapes and types (.*?)\.\s*Argument"
)

_active_budgets: List["CompileBudget"] = []
_monitoring_installed = False


def _install_monitoring() -> None:
    """One process-wide listener (jax.monitoring has no unregister API);
    it fans out to whichever budgets are currently active."""
    global _monitoring_installed
    if _monitoring_installed:
        return
    _monitoring_installed = True
    import jax.monitoring

    def _on_duration(name: str, duration: float, **kw) -> None:
        if name == "/jax/core/compile/backend_compile_duration":
            for budget in _active_budgets:
                budget.backend_compiles += 1

    jax.monitoring.register_event_duration_secs_listener(_on_duration)


class _CompileLogHandler(logging.Handler):
    def __init__(self, budget: "CompileBudget"):
        super().__init__(level=logging.DEBUG)
        self._budget = budget

    def emit(self, record: logging.LogRecord) -> None:
        try:
            m = _COMPILING_RE.search(record.getMessage())
        except Exception:  # defensive: never let logging break the run
            return
        if m:
            self._budget._record(m.group(1), m.group(2))


class CompileBudget:
    """Track (function name, shape signature) of every jit compile.

    >>> with CompileBudget() as budget:
    ...     for _ in range(5):
    ...         state, loss = train_step(state, batch)   # jitted
    >>> budget.assert_compiles_once("train_step")

    ``assert_compiles_once`` fails when any shape signature of a matching
    function compiled more than once (a retrace on identical shapes —
    e.g. a non-hashable static arg rebuilt per call, or a fresh jax.jit
    wrap per step) or when it never compiled at all (the budget saw
    nothing — miswired test). ``max_signatures`` bounds how many shape
    buckets are allowed (padding/bucketing regressions).
    """

    def __init__(self) -> None:
        # (name, signature) -> count
        self.compiles: Dict[Tuple[str, str], int] = {}
        self.backend_compiles = 0
        self._handler: Optional[_CompileLogHandler] = None
        self._saved_log_compiles: Optional[bool] = None

    # -- recording ---------------------------------------------------------
    def _record(self, name: str, signature: str) -> None:
        key = (name, signature)
        self.compiles[key] = self.compiles.get(key, 0) + 1

    def __enter__(self) -> "CompileBudget":
        _install_monitoring()
        self._saved_log_compiles = bool(jax.config.jax_log_compiles)
        jax.config.update("jax_log_compiles", True)
        self._handler = _CompileLogHandler(self)
        logging.getLogger("jax._src.interpreters.pxla").addHandler(
            self._handler
        )
        _active_budgets.append(self)
        return self

    def __exit__(self, *exc) -> None:
        _active_budgets.remove(self)
        logging.getLogger("jax._src.interpreters.pxla").removeHandler(
            self._handler
        )
        jax.config.update("jax_log_compiles", self._saved_log_compiles)

    # -- queries -----------------------------------------------------------
    def names(self) -> List[str]:
        return sorted({name for name, _ in self.compiles})

    def total(self, name_substr: str = "") -> int:
        return sum(
            n
            for (name, _), n in self.compiles.items()
            if name_substr in name
        )

    def signatures(self, name_substr: str = "") -> List[str]:
        return sorted(
            {sig for (name, sig) in self.compiles if name_substr in name}
        )

    def retraces(self, name_substr: str = "") -> List[Tuple[str, str, int]]:
        """(name, signature, count) entries that compiled more than once —
        i.e. retraces on IDENTICAL shapes."""
        return sorted(
            (name, sig, n)
            for (name, sig), n in self.compiles.items()
            if name_substr in name and n > 1
        )

    # -- assertions --------------------------------------------------------
    def assert_compiles_once(
        self, name_substr: str, max_signatures: Optional[int] = None
    ) -> None:
        if self.total(name_substr) == 0:
            raise AssertionError(
                f"compile budget saw no compiles matching {name_substr!r} "
                f"(observed: {self.names()}) — is the budget active around "
                "the first call?"
            )
        retraced = self.retraces(name_substr)
        if retraced:
            detail = "; ".join(
                f"{name} x{n} for shapes {sig}" for name, sig, n in retraced
            )
            raise AssertionError(
                f"retrace on identical shapes: {detail}. The step function "
                "must compile once per shape bucket — look for non-hashable "
                "statics, fresh jax.jit wraps per call, or weak_type churn."
            )
        if max_signatures is not None:
            sigs = self.signatures(name_substr)
            if len(sigs) > max_signatures:
                raise AssertionError(
                    f"{name_substr!r} compiled {len(sigs)} shape buckets "
                    f"(budget {max_signatures}): {sigs}"
                )


@contextmanager
def tracer_leak_check(enabled: bool = True) -> Iterator[None]:
    """Scoped ``jax.check_tracer_leaks``: raises if a traced value escapes
    its trace (closure capture of a tracer, storing tracers on self, ...).
    No-op when ``enabled`` is false so callers can wire it to a CLI flag."""
    if not enabled:
        yield
        return
    with jax.checking_leaks():
        yield
