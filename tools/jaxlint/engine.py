"""Lint-framework core: module analysis, suppression handling, baseline
gate. Shared by jaxlint (JAX hot-path rules) and threadlint (concurrency
and process-lifecycle rules, tools/threadlint/) — the two analyzers differ
only in their rule catalog and suppression ``tag``.

The engine is rule-agnostic: it parses each file once into a
:class:`ModuleInfo` (AST + parent links + comment map + jit registry) and
hands it to every rule in the catalog. Findings are identified for
baseline purposes by ``(file, rule, stripped-source-line)`` — NOT by line
number — so unrelated edits that shift code don't invalidate the
grandfather list.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

# Comment grammar:  # <tag>: disable=rule-a,rule-b -- rationale text
# (tag = "jaxlint" or "threadlint"; each analyzer only honors its own tag,
# so a jaxlint suppression can never silence a threadlint finding.)
_SUPPRESS_RES: Dict[str, "re.Pattern[str]"] = {}


def _suppress_re(tag: str) -> "re.Pattern[str]":
    pat = _SUPPRESS_RES.get(tag)
    if pat is None:
        pat = re.compile(
            r"#\s*" + re.escape(tag)
            + r":\s*disable=([A-Za-z0-9_,-]+)\s*(?:--\s*(.*))?$"
        )
        _SUPPRESS_RES[tag] = pat
    return pat

# Findings about the lint annotations themselves — never eligible for the
# baseline: grandfathering a rationale-less or stale suppression would
# permanently disable the suppression-hygiene checks.
META_RULES = frozenset(
    ("suppression-missing-rationale", "unused-suppression", "parse-error")
)


@dataclass(frozen=True)
class Finding:
    file: str  # posix relpath from the lint root
    line: int
    col: int
    rule: str
    message: str
    hint: str = ""
    text: str = ""  # stripped source line: the baseline identity

    @property
    def key(self) -> str:
        return f"{self.file}::{self.rule}::{self.text}"

    def render(self) -> str:
        out = f"{self.file}:{self.line}:{self.col}: {self.rule}: {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


@dataclass
class Suppression:
    line: int
    rules: Tuple[str, ...]  # ("all",) is a wildcard
    rationale: str
    used: bool = False

    def covers(self, rule: str) -> bool:
        return "all" in self.rules or rule in self.rules


class ModuleInfo:
    """One parsed file plus the cross-rule analysis every rule needs."""

    def __init__(self, path: str, source: str):
        self.path = path  # posix relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.comments = self._collect_comments(source)
        self.jax_random_aliases = self._collect_jax_random_aliases()
        self.functions = [
            n
            for n in ast.walk(self.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        self.defs_by_name: Dict[str, List[ast.FunctionDef]] = {}
        for fn in self.functions:
            self.defs_by_name.setdefault(fn.name, []).append(fn)
        self.jitted_defs = self._collect_jitted_defs()

    # -- generic helpers ---------------------------------------------------
    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(
        self, node: ast.AST
    ) -> Optional[ast.FunctionDef]:
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return None

    def enclosing_loop(self, node: ast.AST) -> Optional[ast.AST]:
        """Nearest For/While ancestor WITHIN the same function scope."""
        for a in self.ancestors(node):
            if isinstance(a, (ast.For, ast.AsyncFor, ast.While)):
                return a
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return None
        return None

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def dotted_name(self, node: ast.AST) -> str:
        """'jax.random.normal' for nested Attributes, '' if not a plain
        dotted chain."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return ""

    # -- analysis passes ---------------------------------------------------
    @staticmethod
    def _collect_comments(source: str) -> Dict[int, str]:
        comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass
        return comments

    def _collect_jax_random_aliases(self) -> set:
        """Names that refer to the jax.random module in this file."""
        aliases = {"jax.random"}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax.random" and a.asname:
                        aliases.add(a.asname)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax" and node.level == 0:
                    for a in node.names:
                        if a.name == "random":
                            aliases.add(a.asname or "random")
        return aliases

    def is_jit_call(self, node: ast.AST) -> bool:
        """Call node that wraps a function in jax.jit/pjit (including
        functools.partial(jax.jit, ...))."""
        if not isinstance(node, ast.Call):
            return False
        name = self.dotted_name(node.func)
        if name in ("jax.jit", "jit", "jax.pjit", "pjit"):
            return True
        if name in ("partial", "functools.partial") and node.args:
            return self.dotted_name(node.args[0]) in (
                "jax.jit",
                "jit",
                "jax.pjit",
                "pjit",
            )
        return False

    def _collect_jitted_defs(self) -> List[ast.FunctionDef]:
        """Defs whose body runs under trace: decorated with jax.jit (or
        partial(jax.jit, ...)), or passed by name to a jax.jit(...) call
        anywhere in the module (the factory idiom: ``def step_fn(...): ...;
        return jax.jit(step_fn, ...)``)."""
        jitted: List[ast.FunctionDef] = []
        jitted_names: set = set()
        for node in ast.walk(self.tree):
            if self.is_jit_call(node):
                args = node.args
                # partial(jax.jit, fn) puts the wrapped fn at args[1]
                wrapped = None
                if self.dotted_name(node.func) in ("partial", "functools.partial"):
                    if len(args) > 1:
                        wrapped = args[1]
                elif args:
                    wrapped = args[0]
                if isinstance(wrapped, ast.Name):
                    jitted_names.add(wrapped.id)
        for fn in self.functions:
            if fn.name in jitted_names:
                jitted.append(fn)
                continue
            for dec in fn.decorator_list:
                if self.is_jit_call(dec) or self.dotted_name(dec) in (
                    "jax.jit",
                    "jit",
                    "jax.pjit",
                    "pjit",
                ):
                    jitted.append(fn)
                    break
        return jitted

    def in_jitted_body(self, node: ast.AST) -> Optional[ast.FunctionDef]:
        """The jitted def whose body contains ``node`` (nested defs count:
        a closure inside a jitted fn still traces)."""
        for a in self.ancestors(node):
            if a in self.jitted_defs:
                return a
        return None


# --------------------------------------------------------------- suppressions
def parse_suppressions(
    info: ModuleInfo, tag: str = "jaxlint"
) -> Tuple[Dict[int, Suppression], List[Finding]]:
    """Line -> suppression. A suppression covers its own line; a comment
    alone on its line also covers the next source line (comment-above
    idiom). A missing ``-- rationale`` voids the suppression and is itself
    a finding."""
    by_line: Dict[int, Suppression] = {}
    problems: List[Finding] = []
    for lineno, comment in info.comments.items():
        m = _suppress_re(tag).search(comment)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        rationale = (m.group(2) or "").strip()
        if not rationale:
            problems.append(
                Finding(
                    file=info.path,
                    line=lineno,
                    col=0,
                    rule="suppression-missing-rationale",
                    message=(
                        f"{tag} suppression without a rationale is ignored"
                    ),
                    hint=(
                        f"write `# {tag}: disable=<rule> -- <why this is "
                        "safe here>`"
                    ),
                    text=info.line_text(lineno),
                )
            )
            continue
        sup = Suppression(line=lineno, rules=rules, rationale=rationale)
        by_line[lineno] = sup
        line_body = info.lines[lineno - 1][: info.lines[lineno - 1].find("#")]
        if not line_body.strip():
            # Standalone comment: covers the next CODE line — skipping any
            # further comment lines (a wrapped rationale) and blank lines,
            # so neither silently voids the suppression.
            nxt = lineno + 1
            while nxt <= len(info.lines) and (
                not info.lines[nxt - 1].strip()
                or info.lines[nxt - 1].lstrip().startswith("#")
            ):
                nxt += 1
            by_line.setdefault(nxt, sup)
    return by_line, problems


# -------------------------------------------------------------------- baseline
class Baseline:
    """Grandfather list. Findings are counted per ``(file, rule, line-text)``
    key; the gate fails only when an observed count exceeds the accepted
    count for that key (i.e. a NEW violation, even of an old kind)."""

    def __init__(self, counts: Optional[Dict[str, int]] = None):
        self.counts: Dict[str, int] = dict(counts or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path) as f:
            data = json.load(f)
        return cls(data.get("accepted", {}))

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        counts: Dict[str, int] = {}
        for f in findings:
            counts[f.key] = counts.get(f.key, 0) + 1
        return cls(counts)

    def save(self, path: str, tool: str = "jaxlint") -> None:
        with open(path, "w") as f:
            json.dump(
                {
                    "comment": (
                        f"{tool} grandfather list — regenerate with "
                        f"`python -m tools.{tool} <paths> "
                        "--update-baseline`. "
                        "Keys are file::rule::source-line; the gate fails "
                        "only on findings beyond these counts."
                    ),
                    "accepted": dict(sorted(self.counts.items())),
                },
                f,
                indent=1,
                sort_keys=False,
            )
            f.write("\n")

    def new_findings(self, findings: Sequence[Finding]) -> List[Finding]:
        """Findings beyond the accepted count for their key. When N are
        accepted and N+k observed, the LAST k (by position) are reported."""
        seen: Dict[str, int] = {}
        out: List[Finding] = []
        for f in sorted(findings, key=lambda f: (f.file, f.line, f.col)):
            seen[f.key] = seen.get(f.key, 0) + 1
            if seen[f.key] > self.counts.get(f.key, 0):
                out.append(f)
        return out

    def stale_keys(self, findings: Sequence[Finding]) -> List[str]:
        """Accepted keys no longer observed at their accepted count —
        candidates for tightening the baseline."""
        seen: Dict[str, int] = {}
        for f in findings:
            seen[f.key] = seen.get(f.key, 0) + 1
        return sorted(
            k for k, n in self.counts.items() if seen.get(k, 0) < n
        )


# ------------------------------------------------------------------- frontend
def lint_source(
    source: str,
    path: str = "<string>",
    rules: Optional[Sequence] = None,
    tag: str = "jaxlint",
    catalog: Optional[Sequence] = None,
) -> List[Finding]:
    """Lint one source blob. ``path`` should be the posix relpath used in
    baseline keys. ``tag`` selects the suppression grammar; ``catalog``
    is the analyzer's full rule set (default: jaxlint's), used when
    ``rules`` is None — passing ``rules`` explicitly means a --select
    subset run, which disables unused-suppression reporting."""
    if catalog is None:
        from tools.jaxlint.rules import RULES as catalog

    try:
        info = ModuleInfo(path, source)
    except SyntaxError as e:
        return [
            Finding(
                file=path,
                line=e.lineno or 0,
                col=e.offset or 0,
                rule="parse-error",
                message=f"file does not parse: {e.msg}",
                text="",
            )
        ]
    suppressions, problems = parse_suppressions(info, tag)
    findings: List[Finding] = list(problems)
    for rule in rules if rules is not None else catalog:
        for f in rule.check(info):
            sup = suppressions.get(f.line)
            if sup is not None and sup.covers(f.rule):
                sup.used = True
                continue
            findings.append(f)
    if rules is None:
        # A suppression that no longer silences anything is stale noise —
        # report it like a stale baseline key. Only meaningful with the
        # full catalog: under --select, un-run rules would look "unused".
        reported = set()
        for sup in suppressions.values():
            if id(sup) in reported or sup.used:
                continue
            reported.add(id(sup))
            findings.append(
                Finding(
                    file=path,
                    line=sup.line,
                    col=0,
                    rule="unused-suppression",
                    message=(
                        "suppression matches no finding "
                        f"(rules: {', '.join(sup.rules)}) — the code it "
                        "excused is gone or the rule name is wrong"
                    ),
                    hint=f"delete the stale `# {tag}: disable` comment",
                    text=info.line_text(sup.line),
                )
            )
    findings.sort(key=lambda f: (f.file, f.line, f.col))
    return findings


def iter_python_files(paths: Sequence[str], root: str) -> Iterator[str]:
    # Dedup across overlapping path args (`seist_tpu seist_tpu/serve`):
    # linting a file twice would double its counts against the baseline.
    seen: set = set()

    def emit(path: str) -> Iterator[str]:
        key = os.path.abspath(path)
        if key not in seen:
            seen.add(key)
            yield path

    for p in paths:
        ap = os.path.join(root, p) if not os.path.isabs(p) else p
        if not os.path.exists(ap):
            # A typo'd/renamed path must fail the gate loudly — os.walk on
            # a missing dir is silently empty, which would turn the lint
            # gate into a no-op that exits 0 forever.
            raise FileNotFoundError(f"lint path does not exist: {ap}")
        if os.path.isfile(ap):
            yield from emit(ap)
        else:
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [
                    d
                    for d in sorted(dirnames)
                    if d != "__pycache__" and not d.startswith(".")
                ]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield from emit(os.path.join(dirpath, fn))


def lint_paths(
    paths: Sequence[str],
    root: Optional[str] = None,
    rules: Optional[Sequence] = None,
    tag: str = "jaxlint",
    catalog: Optional[Sequence] = None,
    source_cache: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    """``source_cache`` ({abspath: source}) short-circuits the file read —
    the combined ``tools/lint.py`` runner walks and reads every file ONCE
    and feeds both AST analyzers from the same cache."""
    root = os.path.abspath(root or os.getcwd())
    findings: List[Finding] = []
    for fpath in iter_python_files(paths, root):
        ap = os.path.abspath(fpath)
        rel = os.path.relpath(ap, root).replace(os.sep, "/")
        source = None if source_cache is None else source_cache.get(ap)
        if source is None:
            with open(fpath, encoding="utf-8") as f:
                source = f.read()
            if source_cache is not None:
                source_cache[ap] = source
        findings.extend(lint_source(source, rel, rules, tag, catalog))
    return findings
