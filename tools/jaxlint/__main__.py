"""jaxlint CLI (the generic frontend both analyzers share — threadlint's
``python -m tools.threadlint`` calls :func:`run` with its own catalog).

    python -m tools.jaxlint seist_tpu                    # gate vs baseline
    python -m tools.jaxlint seist_tpu --no-baseline      # everything
    python -m tools.jaxlint seist_tpu --update-baseline  # re-grandfather
    python -m tools.jaxlint --list-rules

Exit codes: 0 clean (vs baseline), 1 new findings, 2 usage/parse error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Callable, Dict, Optional, Sequence

from tools.jaxlint.engine import (
    META_RULES,
    Baseline,
    iter_python_files,
    lint_paths,
)

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def run(
    argv: Optional[Sequence[str]],
    *,
    tag: str,
    catalog: Sequence,
    rules_by_name: Dict[str, object],
    default_baseline: str,
    docs: str,
    example_paths: str = "seist_tpu",
    collect: Optional[Callable] = None,
    add_args: Optional[Callable] = None,
    refuse_empty_baseline_update: bool = False,
    source_cache: Optional[Dict[str, str]] = None,
    default_paths: Optional[Sequence[str]] = None,
) -> int:
    """The shared gate frontend. ``tag`` is both the suppression-comment
    tag and the ``python -m tools.<tag>`` program name.

    The AST analyzers (jaxlint, threadlint) use the default file walk;
    irlint swaps in ``collect(args, rules) -> (findings, linted_keys)``,
    which lowers its program manifest instead of walking files —
    baseline/suppression/staleness semantics are identical either way.
    ``add_args`` extends the argparse surface (irlint's --report/--window
    ...); ``refuse_empty_baseline_update`` hard-errors --update-baseline
    against an existing EMPTY baseline (empty-by-construction invariant);
    ``source_cache`` ({abspath: source}) lets a combined runner
    (tools/lint.py) walk + read every file exactly once for all
    analyzers; ``default_paths`` makes a bare ``python -m tools.<tag>``
    lint that surface instead of erroring (detlint's whole-repo gate)."""
    ap = argparse.ArgumentParser(
        prog=f"python -m tools.{tag}",
        description=f"{tag} static analysis (see {docs})",
    )
    paths_help = (
        "program-key globs to lint (default: the full manifest)"
        if collect is not None
        else "files/dirs to lint"
    )
    ap.add_argument("paths", nargs="*", default=[], help=paths_help)
    ap.add_argument(
        "--baseline",
        default=default_baseline,
        help=(
            "grandfather list (default "
            f"{os.path.relpath(default_baseline, _REPO_ROOT)})"
        ),
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring the baseline",
    )
    ap.add_argument(
        "--update-baseline",
        action="store_true",
        help="accept all current findings into the baseline and exit 0",
    )
    ap.add_argument(
        "--select",
        default="",
        help="comma-separated rule names to run (default: all)",
    )
    ap.add_argument(
        "--format", choices=("text", "json"), default="text", dest="fmt"
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "--root",
        default=_REPO_ROOT,
        help="path findings are reported relative to (baseline keys)",
    )
    if add_args is not None:
        add_args(ap)
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in catalog:
            print(f"{rule.name}\n    {rule.summary}\n    fix: {rule.hint}")
        return 0

    if not args.paths and collect is None:
        if default_paths:
            args.paths = list(default_paths)
        else:
            ap.error(
                f"no paths given (try: python -m tools.{tag} "
                f"{example_paths})"
            )

    rules = None
    if args.select:
        if args.update_baseline:
            ap.error(
                "--update-baseline with --select would record only the "
                "selected rules' findings and drop every other accepted "
                "entry for the linted files; update with the full catalog"
            )
        names = [n.strip() for n in args.select.split(",") if n.strip()]
        unknown = [n for n in names if n not in rules_by_name]
        if unknown:
            ap.error(
                f"unknown rule(s) {unknown}; see --list-rules"
            )
        rules = [rules_by_name[n] for n in names]

    if args.update_baseline and refuse_empty_baseline_update:
        existing = Baseline.load(args.baseline)
        if os.path.exists(args.baseline) and not existing.counts:
            print(
                f"{tag}: refusing --update-baseline: "
                f"{os.path.relpath(args.baseline, args.root)} is EMPTY BY "
                "CONSTRUCTION — fix the finding or add a rationale'd "
                f"`# {tag}: disable` at the program's registration site "
                "instead of grandfathering",
                file=sys.stderr,
            )
            return 2

    try:
        if collect is not None:
            findings, linted = collect(args, rules)
        else:
            findings = lint_paths(
                args.paths, root=args.root, rules=rules, tag=tag,
                catalog=catalog, source_cache=source_cache,
            )
            linted = {
                os.path.relpath(
                    os.path.abspath(p), os.path.abspath(args.root)
                ).replace(os.sep, "/")
                for p in iter_python_files(
                    args.paths, os.path.abspath(args.root)
                )
            }
    except FileNotFoundError as e:
        print(f"{tag}: {e}", file=sys.stderr)
        return 2
    if any(f.rule == "parse-error" for f in findings):
        for f in findings:
            if f.rule == "parse-error":
                print(f.render(), file=sys.stderr)
        return 2

    if args.update_baseline:
        # Merge, don't overwrite: accepted entries for files OUTSIDE this
        # invocation's paths are preserved, so a subset run (e.g.
        # `tools.jaxlint seist_tpu/train --update-baseline`) can't
        # silently drop the rest of the grandfather list.
        old = Baseline.load(args.baseline)
        kept = {
            k: v
            for k, v in old.counts.items()
            if k.split("::", 1)[0] not in linted
        }
        # Meta-findings (void/stale suppressions) are about the lint
        # annotations themselves — accepting them would disable the
        # suppression-hygiene checks forever, so they stay gating.
        acceptable = [f for f in findings if f.rule not in META_RULES]
        merged = Baseline(kept)
        merged.counts.update(Baseline.from_findings(acceptable).counts)
        merged.save(args.baseline, tool=tag)
        print(
            f"baseline updated: {len(acceptable)} accepted finding(s) from "
            f"{len(linted)} linted file(s), {len(kept)} entr(ies) for "
            "unlinted files preserved -> "
            f"{os.path.relpath(args.baseline, args.root)}"
        )
        skipped = len(findings) - len(acceptable)
        if skipped:
            print(
                f"{tag}: {skipped} suppression-hygiene finding(s) NOT "
                "accepted (fix the annotations instead)"
            )
        return 0

    baseline = (
        Baseline() if args.no_baseline else Baseline.load(args.baseline)
    )
    new = baseline.new_findings(findings)
    # Staleness is only decidable for keys this run actually checked: an
    # entry for an unlinted file or an un-run rule was not observed
    # because it was not looked for, not because the code changed.
    selected = {r.name for r in rules} if rules is not None else None
    stale = (
        []
        if args.no_baseline
        else [
            k
            for k in baseline.stale_keys(findings)
            if k.split("::", 2)[0] in linted
            and (selected is None or k.split("::", 2)[1] in selected)
        ]
    )

    if args.fmt == "json":
        print(
            json.dumps(
                {
                    "total": len(findings),
                    "new": [f.__dict__ for f in new],
                    "stale_baseline_keys": stale,
                },
                indent=1,
            )
        )
    else:
        for f in new:
            print(f.render())
        grandfathered = len(findings) - len(new)
        print(
            f"{tag}: {len(new)} new finding(s), "
            f"{grandfathered} grandfathered (baseline: "
            f"{os.path.relpath(args.baseline, args.root)})"
        )
        if stale:
            print(
                f"{tag}: note — {len(stale)} baseline entr(ies) no longer "
                "observed; tighten with --update-baseline:"
            )
            for k in stale:
                print(f"    {k}")
    return 1 if new else 0


def main(argv=None) -> int:
    from tools.jaxlint.rules import RULES, RULES_BY_NAME

    return run(
        argv,
        tag="jaxlint",
        catalog=RULES,
        rules_by_name=RULES_BY_NAME,
        default_baseline=os.path.join(
            _REPO_ROOT, "tools", "jaxlint_baseline.json"
        ),
        docs="docs/STATIC_ANALYSIS.md",
    )


if __name__ == "__main__":
    sys.exit(main())
