"""jaxlint rule catalog — JAX hot-path hazards, repo-tuned.

Every rule is a pure function of one :class:`~tools.jaxlint.engine.ModuleInfo`.
Static analysis cannot prove a value lives on device, so the catalog trades
soundness for signal with two repo-tuned knobs:

* ``HOT_PATH_GLOBS`` — modules on the step/serve/stream hot path, where ANY
  host materialization (``.item()``, ``float()``/``int()``, ``np.asarray``)
  is presumed guilty until suppressed with a rationale.
* ``TRACED_NAME_RE`` — the factory idiom (``make_train_step`` returning a
  local ``train_step`` that a *different* module jits) hides the jit wrap
  from a single-file pass, so defs named like step functions are treated
  as traced bodies too.

False positives are expected to be rare and cheap: suppress inline with a
rationale or accept into tools/jaxlint_baseline.json. See
docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from typing import Dict, Iterator, List, Optional, Tuple

from tools.jaxlint.engine import Finding, ModuleInfo

# Modules where a host sync stalls the accelerator pipeline (train step
# dispatch, serving forward, stream annotate).
HOT_PATH_GLOBS = (
    "seist_tpu/train/step.py",
    "seist_tpu/ops/stream.py",
    "seist_tpu/ops/postprocess.py",
    "seist_tpu/serve/pool.py",
)

# Local defs with these names are traced even when the jax.jit call lives
# in another module (factory idiom).
TRACED_NAME_RE = re.compile(
    r"(_step|_fn)$|^(train|eval|multi|device_aug|cached)_step$|^step_fn$"
)

# jax.random callees that CONSUME a key (single-use). Deriving functions
# (split/fold_in/...) are exempt: they mint fresh keys.
_KEY_DERIVING = {
    "split",
    "fold_in",
    "PRNGKey",
    "key",
    "key_data",
    "wrap_key_data",
    "clone",
}

_STATE_PARAM_NAMES = {"state", "train_state", "opt_state"}
_EVALISH_RE = re.compile(r"eval|infer|predict|forward|apply|val")

_IMPURE_EXACT = {
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "time.process_time",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.now",
    "os.urandom",
    "uuid.uuid4",
}
_IMPURE_PREFIXES = ("np.random.", "numpy.random.", "random.")


class Rule:
    """Base: subclasses set ``name``/``summary``/``hint`` and implement
    ``check``."""

    name: str = ""
    summary: str = ""
    hint: str = ""

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(
        self,
        info: ModuleInfo,
        node: ast.AST,
        message: str,
        hint: Optional[str] = None,
    ) -> Finding:
        return Finding(
            file=info.path,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            rule=self.name,
            message=message,
            hint=self.hint if hint is None else hint,
            text=info.line_text(getattr(node, "lineno", 0)),
        )


def _is_hot(path: str) -> bool:
    return any(fnmatch.fnmatch(path, g) for g in HOT_PATH_GLOBS)


def _call_name(info: ModuleInfo, node: ast.Call) -> str:
    return info.dotted_name(node.func)


def _is_item_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in ("item", "tolist")
        and not node.args
    )


class HostSyncHotPath(Rule):
    name = "host-sync-hot-path"
    summary = (
        "host materialization (.item()/float()/int()/np.asarray) in a "
        "hot-path module"
    )
    hint = (
        "keep device values on device; if a host copy is required, batch it "
        "into ONE jax.device_get outside the per-step/per-request path, or "
        "suppress with a rationale"
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        if not _is_hot(info.path):
            return
        traced = set(info.jitted_defs) | {
            fn for fn in info.functions if TRACED_NAME_RE.search(fn.name)
        }
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_item_call(node):
                yield self.finding(
                    info,
                    node,
                    f".{node.func.attr}() forces a device->host sync",
                )
                continue
            # float()/int()/np.asarray are only presumed-guilty where they
            # repeat (a loop: one sync per iteration) or where they cannot
            # work at all (a traced body: concretization error / baked
            # constant). One-shot coercions of host config stay legal.
            repeated = info.enclosing_loop(node) is not None
            in_traced = any(a in traced for a in info.ancestors(node))
            if not repeated and not in_traced:
                continue
            where = "a traced body" if in_traced else "a loop"
            name = _call_name(info, node)
            if name in ("float", "int", "bool") and (
                len(node.args) == 1
                and not isinstance(node.args[0], ast.Constant)
            ):
                yield self.finding(
                    info,
                    node,
                    f"{name}() in {where} on the hot path blocks on the "
                    "accelerator",
                )
            elif name in ("np.asarray", "np.array", "numpy.asarray", "numpy.array"):
                yield self.finding(
                    info,
                    node,
                    f"{name}() in {where} on the hot path materializes its "
                    "argument on host",
                )


class HostSyncItemLoop(Rule):
    name = "host-sync-item-loop"
    summary = ".item()/jax.device_get inside a loop — one sync per entry"
    hint = (
        "hoist to a single batched jax.device_get of the whole "
        "container before the loop"
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            is_item = _is_item_call(node) and node.func.attr == "item"
            is_get = _call_name(info, node) == "jax.device_get"
            if not (is_item or is_get):
                continue
            loop = info.enclosing_loop(node)
            if loop is None:
                continue
            if is_get and not self._arg_uses_loop_var(info, node, loop):
                # A batched device_get that merely SITS inside an outer
                # (e.g. per-epoch) loop is the recommended pattern — only
                # per-entry gets (argument indexed by the loop variable)
                # are the hazard.
                continue
            what = ".item()" if is_item else "jax.device_get"
            yield self.finding(
                info,
                node,
                f"{what} inside a loop: one device->host round trip "
                "per iteration",
            )

    @staticmethod
    def _arg_uses_loop_var(
        info: ModuleInfo, call: ast.Call, loop: ast.AST
    ) -> bool:
        targets: set = set()
        cur: Optional[ast.AST] = loop
        while cur is not None:
            if isinstance(cur, (ast.For, ast.AsyncFor)):
                targets |= {
                    n.id
                    for n in ast.walk(cur.target)
                    if isinstance(n, ast.Name)
                }
            cur = next(
                (
                    a
                    for a in info.ancestors(cur)
                    if isinstance(
                        a,
                        (ast.For, ast.AsyncFor, ast.While, ast.FunctionDef),
                    )
                ),
                None,
            )
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        arg_names = {
            n.id
            for a in call.args
            for n in ast.walk(a)
            if isinstance(n, ast.Name)
        }
        return bool(targets & arg_names)


class PrngKeyReuse(Rule):
    name = "prng-key-reuse"
    summary = "the same PRNG key consumed by more than one jax.random call"
    hint = (
        "keys are single-use: jax.random.split the key (or fold_in a "
        "counter) so each draw gets a fresh key"
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        for fn in info.functions:
            yield from self._check_scope(info, fn)

    def _key_use(
        self, info: ModuleInfo, node: ast.AST
    ) -> Optional[Tuple[str, str]]:
        """(key_var, callee) when node consumes a key held in a bare Name."""
        if not isinstance(node, ast.Call):
            return None
        name = _call_name(info, node)
        for alias in info.jax_random_aliases:
            if name.startswith(alias + "."):
                callee = name[len(alias) + 1 :]
                if callee in _KEY_DERIVING or "." in callee:
                    return None
                if node.args and isinstance(node.args[0], ast.Name):
                    return node.args[0].id, callee
        return None

    def _check_scope(
        self, info: ModuleInfo, fn: ast.FunctionDef
    ) -> Iterator[Finding]:
        uses: List[Tuple[int, int, str, str, ast.AST]] = []
        assigns: List[Tuple[int, int, str, ast.AST]] = []

        def record_target(t: ast.AST, node: ast.AST) -> None:
            # Record the Name node itself (not the statement): its ancestor
            # chain includes the For/comprehension, so a loop's own target
            # counts as assigned INSIDE that loop.
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    assigns.append(
                        (sub.lineno, sub.col_offset, sub.id, sub)
                    )

        for node in ast.walk(fn):
            if node is not fn and info.enclosing_function(node) is not fn:
                continue  # nested function scopes get their own pass
            use = self._key_use(info, node)
            if use is not None:
                uses.append(
                    (node.lineno, node.col_offset, use[0], use[1], node)
                )
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    record_target(t, node)
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                record_target(node.target, node)
            elif isinstance(node, ast.NamedExpr):
                record_target(node.target, node)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                record_target(node.target, node)
            elif isinstance(node, ast.comprehension):
                record_target(node.target, node)
            elif isinstance(node, ast.withitem) and node.optional_vars:
                record_target(node.optional_vars, node)

        # Linear dual-use: a second consumption of the same name with no
        # reassignment in between. At most one finding per use site (the
        # loop check below skips already-flagged sites).
        flagged: Dict[Tuple[int, int], Finding] = {}
        events = sorted(
            [(u[0], u[1], "use", u) for u in uses]
            + [(a[0], a[1], "assign", a) for a in assigns],
            key=lambda e: (e[0], e[1]),
        )
        consumed: Dict[str, List[Tuple[str, ast.AST]]] = {}
        for _, _, kind, payload in events:
            if kind == "assign":
                consumed.pop(payload[2], None)
            else:
                _, _, key_var, callee, node = payload
                prior = consumed.setdefault(key_var, [])
                live = [
                    c
                    for c, n in prior
                    if not _exclusive_branches(info, n, node)
                ]
                if live:
                    # Draws on mutually exclusive if/else branches are NOT
                    # reuse — exactly one executes per call.
                    flagged[(node.lineno, node.col_offset)] = self.finding(
                        info,
                        node,
                        f"key `{key_var}` was already consumed by "
                        f"jax.random.{live[0]}; reusing it makes "
                        "correlated random draws",
                    )
                prior.append((callee, node))

        # Cross-iteration reuse: a key consumed inside a loop with no
        # refresh of that name anywhere inside the same loop body.
        for lineno, col, key_var, callee, node in uses:
            if (lineno, col) in flagged:
                continue
            loop = info.enclosing_loop(node)
            if loop is None:
                continue
            refreshed = any(
                a_name == key_var and loop in set(info.ancestors(a_node))
                for _, _, a_name, a_node in assigns
            )
            if not refreshed:
                flagged[(lineno, col)] = self.finding(
                    info,
                    node,
                    f"key `{key_var}` consumed by jax.random.{callee} "
                    "inside a loop without per-iteration split/fold_in: "
                    "every iteration draws the same randomness",
                )
        yield from flagged.values()


def _in_field(node: ast.AST, owner: ast.AST, field: str) -> bool:
    """Is ``node`` within ``owner.<field>`` (a stmt list or single expr)?"""
    val = getattr(owner, field, None)
    parts = val if isinstance(val, list) else [val] if val is not None else []
    for part in parts:
        if part is node or any(d is node for d in ast.walk(part)):
            return True
    return False


def _exclusive_branches(info: ModuleInfo, a: ast.AST, b: ast.AST) -> bool:
    """True when a and b sit on opposite arms of a common if/else (or
    ternary) — at most one of them executes per call."""
    a_ancestors = set(info.ancestors(a))
    for anc in info.ancestors(b):
        if anc in a_ancestors and isinstance(anc, (ast.If, ast.IfExp)):
            if (
                _in_field(a, anc, "body")
                and _in_field(b, anc, "orelse")
            ) or (
                _in_field(a, anc, "orelse")
                and _in_field(b, anc, "body")
            ):
                return True
    return False


def _jit_wrapped(info: ModuleInfo, call: ast.Call) -> Optional[ast.AST]:
    if info.dotted_name(call.func) in ("partial", "functools.partial"):
        return call.args[1] if len(call.args) > 1 else None
    return call.args[0] if call.args else None


def _resolve_def(
    info: ModuleInfo, node: Optional[ast.AST]
) -> Optional[ast.FunctionDef]:
    if isinstance(node, ast.Name):
        defs = info.defs_by_name.get(node.id)
        if defs:
            return defs[0]
    return None


def _has_kwarg(call: ast.Call, *names: str) -> bool:
    return any(kw.arg in names for kw in call.keywords)


def _first_param(fn: ast.FunctionDef) -> Optional[str]:
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    return params[0] if params else None


def _carries_state(fn: ast.FunctionDef) -> bool:
    return (
        _first_param(fn) in _STATE_PARAM_NAMES
        and not _EVALISH_RE.search(fn.name)
    )


class JitNoDonate(Rule):
    name = "jit-no-donate"
    summary = (
        "jax.jit of a state-carrying step function without donate_argnums"
    )
    hint = (
        "donate the state argument (donate_argnums=(0,)) so XLA reuses its "
        "buffers — without it every step holds two copies of params + "
        "optimizer state in HBM"
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if info.is_jit_call(node) and not any(
                node is dec
                for fn in info.functions
                for dec in fn.decorator_list
            ):
                fn = _resolve_def(info, _jit_wrapped(info, node))
                if (
                    fn is not None
                    and _carries_state(fn)
                    and not _has_kwarg(
                        node, "donate_argnums", "donate_argnames"
                    )
                ):
                    yield self.finding(
                        info,
                        node,
                        f"jax.jit({fn.name}) updates `{_first_param(fn)}` "
                        "but does not donate it",
                    )
        for fn in info.functions:
            if not _carries_state(fn):
                continue
            for dec in fn.decorator_list:
                bare = info.dotted_name(dec) in ("jax.jit", "jit")
                call_no_donate = (
                    isinstance(dec, ast.Call)
                    and info.is_jit_call(dec)
                    and not _has_kwarg(dec, "donate_argnums", "donate_argnames")
                )
                if bare or call_no_donate:
                    yield self.finding(
                        info,
                        dec,
                        f"@jax.jit on `{fn.name}` updates "
                        f"`{_first_param(fn)}` but does not donate it",
                    )


class ImpureCallInJit(Rule):
    name = "impure-call-in-jit"
    summary = (
        "wall-clock / host-RNG call inside a traced function body"
    )
    hint = (
        "the call runs ONCE at trace time and its result is baked into the "
        "compiled program as a constant — move it outside the jitted "
        "function or pass the value in as an argument"
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        traced = set(info.jitted_defs) | {
            fn for fn in info.functions if TRACED_NAME_RE.search(fn.name)
        }
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(info, node)
            if not name:
                continue
            impure = name in _IMPURE_EXACT or any(
                name.startswith(p) for p in _IMPURE_PREFIXES
            )
            if not impure:
                continue
            owner = None
            for a in info.ancestors(node):
                if a in traced:
                    owner = a
                    break
            if owner is not None:
                yield self.finding(
                    info,
                    node,
                    f"{name}() inside traced function `{owner.name}` is "
                    "evaluated once at trace time, not per step",
                )


class JitInLoop(Rule):
    name = "jit-in-loop"
    summary = "fresh jax.jit wrap inside a loop — recompiles every iteration"
    hint = (
        "hoist the jax.jit call out of the loop (or cache the jitted "
        "callable) so the XLA program compiles once"
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if (
                isinstance(node, ast.Call)
                and info.is_jit_call(node)
                and info.enclosing_loop(node) is not None
            ):
                yield self.finding(
                    info,
                    node,
                    "jax.jit(...) constructed inside a loop: each iteration "
                    "builds a fresh cache entry and recompiles",
                )


_NONHASHABLE_DEFAULTS = (ast.List, ast.Dict, ast.Set)


class NonHashableStatic(Rule):
    name = "nonhashable-static"
    summary = "static jit argument whose default is a list/dict/set"
    hint = (
        "static args are hashed to key the compile cache; pass a tuple / "
        "frozen structure instead (an unhashable static arg raises, and a "
        "mutable one silently retraces on every new object)"
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if not (isinstance(node, ast.Call) and info.is_jit_call(node)):
                continue
            static_nums: List[int] = []
            static_names: List[str] = []
            for kw in node.keywords:
                if kw.arg == "static_argnums":
                    static_nums = _const_ints(kw.value)
                elif kw.arg == "static_argnames":
                    static_names = _const_strs(kw.value)
            if not static_nums and not static_names:
                continue
            fn = _resolve_def(info, _jit_wrapped(info, node))
            if fn is None:
                # decorator form: the def this call decorates
                for f in info.functions:
                    if node in f.decorator_list:
                        fn = f
                        break
            if fn is None:
                continue
            for pname, default in _param_defaults(fn, static_nums, static_names):
                if isinstance(default, _NONHASHABLE_DEFAULTS):
                    yield self.finding(
                        info,
                        node,
                        f"static arg `{pname}` of `{fn.name}` defaults to a "
                        f"{type(default).__name__.lower()} — not hashable",
                    )


def _const_ints(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        ]
    return []


def _const_strs(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
    return []


def _param_defaults(
    fn: ast.FunctionDef, nums: List[int], names: List[str]
) -> Iterator[Tuple[str, ast.AST]]:
    args = fn.args.posonlyargs + fn.args.args
    defaults = fn.args.defaults
    offset = len(args) - len(defaults)
    by_name = {
        a.arg: defaults[i - offset]
        for i, a in enumerate(args)
        if i >= offset
    }
    for kwarg, kwdef in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if kwdef is not None:
            by_name[kwarg.arg] = kwdef
    wanted = set(names) | {
        args[i].arg for i in nums if 0 <= i < len(args)
    }
    for pname in wanted:
        if pname in by_name:
            yield pname, by_name[pname]


class WallClockInterval(Rule):
    name = "wallclock-interval"
    summary = "time.time() used for interval arithmetic"
    hint = (
        "wall clock jumps (NTP slew, suspend); use time.monotonic() for "
        "durations and keep time.time() only for reported timestamps"
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        # Per scope, EVERY assignment to a name is recorded with its
        # position and whether the value is time.time(): taint at a
        # subtraction follows the LAST assignment before it, so
        # `t0 = time.time()` (timestamp) followed by `t0 = time.monotonic()`
        # doesn't poison later monotonic interval math.
        scopes: Dict[
            Optional[ast.AST], Dict[str, List[Tuple[int, int, bool]]]
        ] = {}
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Assign):
                continue
            scope = info.enclosing_function(node)
            is_wall = self._is_time_call(info, node.value)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    scopes.setdefault(scope, {}).setdefault(t.id, []).append(
                        (node.lineno, node.col_offset, is_wall)
                    )
        for per_name in scopes.values():
            for entries in per_name.values():
                entries.sort()

        def tainted(scope, name: str, pos: Tuple[int, int]) -> bool:
            for s in (scope, None):
                entries = scopes.get(s, {}).get(name)
                if entries:
                    before = [e for e in entries if (e[0], e[1]) < pos]
                    if before:
                        return before[-1][2]
            return False

        for node in ast.walk(info.tree):
            if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)):
                continue
            scope = info.enclosing_function(node)
            pos = (node.lineno, node.col_offset)
            for side in (node.left, node.right):
                if self._is_time_call(info, side) or (
                    isinstance(side, ast.Name)
                    and tainted(scope, side.id, pos)
                ):
                    yield self.finding(
                        info,
                        node,
                        "interval computed from time.time(): save/heartbeat "
                        "math breaks when the wall clock steps",
                    )
                    break

    @staticmethod
    def _is_time_call(info: ModuleInfo, node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Call)
            and info.dotted_name(node.func) == "time.time"
        )


_BROAD = {"Exception", "BaseException"}


class BroadExcept(Rule):
    name = "broad-except"
    summary = "broad `except Exception` without a rationale"
    hint = (
        "narrow the exception type, re-raise, or add a comment on/above "
        "the except line saying why swallowing everything is safe here"
    )

    def check(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(info, node.type):
                continue
            if self._has_rationale(info, node) or self._reraises(node):
                continue
            label = (
                "bare `except:`"
                if node.type is None
                else f"`except {info.dotted_name(node.type) or 'Exception'}`"
            )
            yield self.finding(
                info,
                node,
                f"{label} swallows every failure (including bugs) with no "
                "stated rationale",
            )

    @staticmethod
    def _is_broad(info: ModuleInfo, type_node: Optional[ast.AST]) -> bool:
        if type_node is None:
            return True
        if isinstance(type_node, ast.Tuple):
            return any(
                info.dotted_name(e).split(".")[-1] in _BROAD
                for e in type_node.elts
            )
        return info.dotted_name(type_node).split(".")[-1] in _BROAD

    @staticmethod
    def _has_rationale(info: ModuleInfo, node: ast.ExceptHandler) -> bool:
        candidates = {node.lineno, node.lineno - 1}
        if node.body:
            candidates.add(node.body[0].lineno)
            candidates.add(node.body[0].lineno - 1)
        return any(line in info.comments for line in candidates)

    @staticmethod
    def _reraises(node: ast.ExceptHandler) -> bool:
        return any(
            isinstance(n, ast.Raise) and n.exc is None
            for n in ast.walk(node)
        )


RULES: Tuple[Rule, ...] = (
    HostSyncHotPath(),
    HostSyncItemLoop(),
    PrngKeyReuse(),
    JitNoDonate(),
    ImpureCallInJit(),
    JitInLoop(),
    NonHashableStatic(),
    WallClockInterval(),
    BroadExcept(),
)

RULES_BY_NAME: Dict[str, Rule] = {r.name: r for r in RULES}
