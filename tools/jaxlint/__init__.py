"""jaxlint — JAX-aware static analysis for the seist_tpu stack.

Ordinary linters can't see the bug classes that cost a TPU training stack
the most: silent retraces, host syncs in hot paths, PRNG key reuse,
non-donated train state. jaxlint is an AST pass with a repo-tuned rule
catalog for exactly those hazards (see tools/jaxlint/rules.py for the
catalog, docs/STATIC_ANALYSIS.md for the workflow).

Usage:
    python -m tools.jaxlint seist_tpu                 # lint the package
    python -m tools.jaxlint --list-rules              # rule catalog
    python -m tools.jaxlint seist_tpu --update-baseline

A checked-in baseline (tools/jaxlint_baseline.json) grandfathers accepted
findings; the gate (``make lint``) fails only on NEW violations. Inline
suppression requires a rationale:

    x = arr.item()  # jaxlint: disable=host-sync-item-loop -- one scalar, cold path
"""

from tools.jaxlint.engine import (  # noqa: F401
    Finding,
    Baseline,
    lint_paths,
    lint_source,
)
from tools.jaxlint.rules import RULES, Rule  # noqa: F401
from tools.jaxlint.runtime import CompileBudget, tracer_leak_check  # noqa: F401
