"""Run bench.py over the BASELINE.md per-config matrix; collect JSON lines.

Sequentially benchmarks each config from BASELINE.json's `configs` list
(SURVEY.md §6) on the live TPU chip via bench.py subprocesses (one backend
probe each, cached results on tunnel failure), writing
``tools/bench_matrix.json`` and printing a BASELINE.md-ready table.

Usage:
    python tools/bench_matrix.py [--steps 20] [--only seist_m_pmp,...]
    python tools/bench_matrix.py --mode eval --out tools/bench_matrix_eval.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_TOOLS)

# (model, batch) — batch chosen so batch*in_samples stays ~2M samples
# (the flagship's 256 x 8192 working set); all in_samples 8192 per the
# reference training shape (ref main.py:119-149).
CONFIGS = [
    ("seist_s_dpk", 256),
    ("seist_m_dpk", 256),
    ("seist_l_dpk", 256),
    ("phasenet", 256),
    ("eqtransformer", 64),  # BiLSTM scan: far slower per wf, keep runs short
    ("magnet", 256),
    ("ditingmotion", 256),
    ("baz_network", 256),
    # distpt_network: registered but no task spec, matching the reference's
    # commented-out config (ref config.py:112-125) — nothing to train.
    ("seist_m_pmp", 256),
    ("seist_l_emg", 256),
    ("seist_l_baz", 256),
    ("seist_l_dis", 256),
]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--only", type=str, default="")
    ap.add_argument(
        "--mode",
        default="train",
        choices=["train", "eval"],
        help="bench.py BENCH_MODE: full train step or no-grad eval step",
    )
    ap.add_argument(
        "--out",
        default=None,
        help="result JSON (default: bench_matrix.json, or "
        "bench_matrix_eval.json under --mode eval, so an eval sweep can "
        "never clobber the train matrix BASELINE.md cites)",
    )
    args = ap.parse_args()
    if args.out is None:
        name = "bench_matrix_eval.json" if args.mode == "eval" else "bench_matrix.json"
        args.out = os.path.join(_TOOLS, name)

    only = set(args.only.split(",")) if args.only else None
    results = {}
    if os.path.exists(args.out):
        with open(args.out) as f:
            results = json.load(f)

    for model, batch in CONFIGS:
        if only and model not in only:
            continue
        env = dict(
            os.environ,
            BENCH_MODEL=model,
            BENCH_BATCH=str(batch),
            BENCH_STEPS=str(args.steps),
            BENCH_MODE=args.mode,
            BENCH_PROBE_ATTEMPTS="2",
        )
        # Pin the dtype unless the caller chose one: the matrix's rows are
        # only comparable to each other at a fixed dtype, and bench.py's
        # own default may evolve (fp32 -> bf16 in round 2).
        env.setdefault("BENCH_DTYPE", "fp32")
        print(f"=== {model} (batch {batch}) ===", file=sys.stderr, flush=True)
        try:
            r = subprocess.run(
                [sys.executable, os.path.join(_REPO, "bench.py")],
                capture_output=True,
                text=True,
                env=env,
                timeout=3600,
            )
        except subprocess.TimeoutExpired:
            payload = {"error": "timeout after 3600s"}
            r = None
        if r is not None:
            sys.stderr.write(r.stderr[-800:] + "\n")
            line = (
                r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "{}"
            )
            try:
                payload = json.loads(line)
            except json.JSONDecodeError:
                payload = {"error": f"unparseable: {line[:200]}"}
        # Keep-last-good: a failed re-run must not clobber a prior
        # measurement (mirrors bench.py's own cache policy) — but mark the
        # kept entry stale so the table can't pass it off as fresh.
        if payload.get("value") or model not in results:
            results[model] = payload
        else:
            results[model]["stale"] = True
            results[model]["stale_error"] = payload.get("error", "")
        with open(args.out, "w") as f:  # persist incrementally
            json.dump(results, f, indent=1)
        print(json.dumps(payload), flush=True)

    print("\n| config | batch | wf/s/chip | step ms | MFU | note |", flush=True)
    print("|---|---|---|---|---|---|", flush=True)
    for model, _ in CONFIGS:
        p = results.get(model)
        if not p or not p.get("value"):
            continue
        # A cached replay carries both a value and error/cached markers
        # (bench.py _fail) — print it, flagged, rather than dropping it.
        # Same for entries kept by keep-last-good after a failed re-run.
        note = "cached (stale)" if (p.get("cached") or p.get("stale")) else ""
        print(
            f"| {model} | {p.get('batch')} | {p.get('value'):,.0f} | "
            f"{p.get('step_time_ms')} | {p.get('mfu', 0) * 100:.1f}% | "
            f"{note} |",
            flush=True,
        )


if __name__ == "__main__":
    main()
