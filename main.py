"""Launcher — `python main.py --model-name seist_m_dpk --dataset-name diting ...`

Thin wrapper over seist_tpu.cli (the reference's root main.py equivalent).
"""

import os

if os.environ.get("JAX_PLATFORMS"):
    # Honor JAX_PLATFORMS even where a sitecustomize registers an
    # accelerator plugin at interpreter start (the env var alone is ignored
    # there, and a wedged remote backend then hangs init for minutes):
    # jax.config wins over the registration if set before any device query.
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from seist_tpu.cli import main

if __name__ == "__main__":
    main()
