"""Launcher — `python main.py --model-name seist_m_dpk --dataset-name diting ...`

Thin wrapper over seist_tpu.cli (the reference's root main.py equivalent).
"""

from seist_tpu.utils.platform import honor_jax_platforms

honor_jax_platforms()

from seist_tpu.cli import main

if __name__ == "__main__":
    main()
